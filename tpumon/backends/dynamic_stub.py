"""Runtime-built gRPC stubs from reflection-fetched descriptors.

The DCGM-hostengine analogue (SURVEY.md §3.3) requires reading metrics
over gRPC from the libtpu runtime's monitoring service — whose ``.proto``
files are not installed in this environment (SURVEY.md §7 hard part (c)).
Instead of vendoring guessed protos, this module builds the client at
runtime from the server's own schema:

1. :func:`tpumon.backends.reflection.file_containing_symbol` fetches the
   serialized ``FileDescriptorProto`` set for the service symbol;
2. the descriptors land in a private ``DescriptorPool`` (dependency-order
   insertion, tolerant of duplicates across responses);
3. ``google.protobuf.message_factory.GetMessageClass`` materializes the
   request/response message classes;
4. each service method becomes a callable on :class:`DynamicServiceStub`
   with proper serializers, so calls are type-checked protobuf end to end
   — no hand-rolled bytes once the schema is known.

The stub is schema-agnostic: it works against whatever metric service
shape the runtime actually serves, and the test suite drives it against a
fake monitoring server whose descriptors are authored with
``descriptor_pb2`` (tests/test_grpc_backend.py), proving the whole
reflection → pool → stub → call path with zero pre-shared protos.
"""

from __future__ import annotations

import logging

from tpumon.backends.reflection import file_containing_symbol

log = logging.getLogger(__name__)


class StubBuildError(RuntimeError):
    """The service's schema could not be fetched or assembled."""


def build_pool(fdp_blobs: list[bytes]):
    """Assemble serialized FileDescriptorProtos into a fresh DescriptorPool.

    Reflection servers return the defining file plus transitive deps in
    arbitrary order; ``DescriptorPool.Add`` requires dependencies first.
    Iterate until a full pass makes no progress, skipping files whose
    deps haven't landed yet; duplicates (same file in two responses) are
    ignored.
    """
    from google.protobuf import descriptor_pb2, descriptor_pool

    pool = descriptor_pool.DescriptorPool()
    pending = []
    for blob in fdp_blobs:
        fdp = descriptor_pb2.FileDescriptorProto()
        try:
            fdp.ParseFromString(blob)
        except Exception as exc:
            raise StubBuildError(f"undecodable FileDescriptorProto: {exc}") from exc
        pending.append(fdp)

    added: set[str] = set()
    while pending:
        progressed = False
        still_pending = []
        for fdp in pending:
            if fdp.name in added:
                progressed = True
                continue
            if all(dep in added for dep in fdp.dependency):
                # Skip files already registered (e.g. well-known types a
                # server echoes back) by asking the pool directly, rather
                # than substring-matching the exception text — protobuf's
                # duplicate-registration wording varies across versions
                # and C++/pure-Python implementations.
                already = True
                try:
                    pool.FindFileByName(fdp.name)
                except KeyError:
                    already = False
                if not already:
                    try:
                        pool.Add(fdp)
                    except Exception as exc:
                        raise StubBuildError(
                            f"descriptor {fdp.name} rejected: {exc}"
                        ) from exc
                added.add(fdp.name)
                progressed = True
            else:
                still_pending.append(fdp)
        if not progressed:
            missing = {
                dep
                for fdp in still_pending
                for dep in fdp.dependency
                if dep not in added
            }
            raise StubBuildError(
                f"descriptor dependencies never arrived: {sorted(missing)}"
            )
        pending = still_pending
    return pool


class DynamicServiceStub:
    """Callable method stubs for one gRPC service, built from reflection.

    ``stub.methods`` maps unary method name → :class:`DynamicMethod`;
    ``stub.call(name, timeout=..., **fields)`` constructs the request
    message from keyword fields and returns the decoded response message.
    Server-streaming methods land in ``stub.stream_methods`` (→
    :class:`DynamicStreamMethod`); ``stub.open_stream(name, **fields)``
    starts one and returns the live gRPC call — an iterator of decoded
    responses that also supports ``cancel()``. Client-streaming methods
    are skipped (nothing on the monitoring surface sends request
    streams).
    """

    def __init__(self, channel, service_name: str, pool) -> None:
        from google.protobuf import message_factory

        try:
            svc = pool.FindServiceByName(service_name)
        except KeyError as exc:
            raise StubBuildError(
                f"service {service_name} not in fetched descriptors"
            ) from exc
        self.service_name = service_name
        self.methods: dict[str, DynamicMethod] = {}
        self.stream_methods: dict[str, DynamicStreamMethod] = {}
        for method in svc.methods:
            req_cls = message_factory.GetMessageClass(method.input_type)
            resp_cls = message_factory.GetMessageClass(method.output_type)
            if method.client_streaming:
                log.debug(
                    "skipping client-streaming method %s/%s",
                    service_name,
                    method.name,
                )
                continue
            path = f"/{service_name}/{method.name}"
            if method.server_streaming:
                callable_ = channel.unary_stream(
                    path,
                    request_serializer=lambda msg: msg.SerializeToString(),
                    response_deserializer=resp_cls.FromString,
                )
                self.stream_methods[method.name] = DynamicStreamMethod(
                    method.name, req_cls, resp_cls, callable_
                )
                continue
            callable_ = channel.unary_unary(
                path,
                request_serializer=lambda msg: msg.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )
            self.methods[method.name] = DynamicMethod(
                method.name, req_cls, resp_cls, callable_
            )

    def call(self, method_name: str, timeout: float = 2.0, **fields):
        method = self.methods.get(method_name)
        if method is None:
            raise StubBuildError(
                f"{self.service_name} has no unary method {method_name!r} "
                f"(has: {sorted(self.methods)})"
            )
        return method(timeout=timeout, **fields)

    def open_stream(self, method_name: str, timeout=None, **fields):
        """Start a server-streaming call; returns the live gRPC call
        (iterator of decoded responses, ``cancel()``-able). ``timeout``
        None means the stream lives until cancelled or the server ends
        it — the right default for a long-lived metric watch."""
        method = self.stream_methods.get(method_name)
        if method is None:
            raise StubBuildError(
                f"{self.service_name} has no server-streaming method "
                f"{method_name!r} (has: {sorted(self.stream_methods)})"
            )
        return method(timeout=timeout, **fields)


class DynamicMethod:
    def __init__(self, name: str, req_cls, resp_cls, callable_) -> None:
        self.name = name
        self.request_class = req_cls
        self.response_class = resp_cls
        self._callable = callable_

    def __call__(self, timeout: float = 2.0, **fields):
        req = self.request_class(**fields)
        return self._callable(req, timeout=timeout)


class DynamicStreamMethod(DynamicMethod):
    """A server-streaming method; calling it returns the live call object
    (iterator of decoded responses; supports ``cancel()``). Only the
    timeout default differs from the unary base: None, because a metric
    watch lives until cancelled or the server ends it."""

    def __call__(self, timeout=None, **fields):
        return super().__call__(timeout=timeout, **fields)


def build_stub(
    channel, service_name: str, timeout: float = 2.0
) -> DynamicServiceStub:
    """Reflection → descriptor pool → callable stub, in one step.

    Raises :class:`StubBuildError` when the server is unreachable, does
    not speak reflection, or does not define ``service_name``.
    """
    blobs = file_containing_symbol(channel, service_name, timeout)
    if blobs is None:
        raise StubBuildError(
            f"reflection unavailable while resolving {service_name}"
        )
    if not blobs:
        raise StubBuildError(f"server has no descriptors for {service_name}")
    pool = build_pool(blobs)
    return DynamicServiceStub(channel, service_name, pool)


def message_records(msg) -> list[tuple[dict, float | None]]:
    """Flatten a response message into (attributes, value) records.

    Schema-agnostic walk used to convert whatever metric-response shape
    the runtime serves into the SDK's per-row string-vector form:

    - the *record set* is the deepest repeated message field found by
      walking singular message fields down from the root (e.g.
      ``response.metric.metrics`` in the cloud-TPU runtime shape);
    - within one record, scalar leaves reached through singular message
      fields are collected — numeric leaves under a field named like a
      measurement (gauge/value/data) become the record's value, string
      and integer leaves elsewhere become attributes keyed by their
      field name (e.g. device-id, core-id).

    Returns [] when no repeated message field exists (the "runtime
    detached" empty response — SURVEY.md §2.2 absent-not-zero).
    """
    container = _find_record_list(msg)
    if container is None:
        return []
    return [_flatten_record(record) for record in container]


_VALUE_FIELD_HINTS = ("gauge", "value", "data", "measurement", "counter")


def _find_record_list(msg, depth: int = 0):
    """Deepest repeated composite field reachable via set singular fields.

    Depth is tracked explicitly: a shallow repeated field declared after a
    nested one (e.g. a trailing ``repeated Warning warnings`` next to
    ``metric.metrics``) must not shadow the deeper record list.
    """
    best: tuple[int, object] | None = None
    for field, value in msg.ListFields():
        if field.type != field.TYPE_MESSAGE:
            continue
        if _is_repeated(field):
            candidate: tuple[int, object] | None = (depth, value)
        else:
            candidate = _find_record_list(value, depth + 1)
        if candidate is not None and (best is None or candidate[0] > best[0]):
            best = candidate
    if depth > 0:
        return best
    return best[1] if best is not None else None


_ATTR_FIELD_HINTS = ("attribute", "attributes", "label", "labels", "tag")
_KEY_FIELD_NAMES = ("key", "name")


def _is_repeated(field) -> bool:
    is_rep = getattr(field, "is_repeated", None)
    if is_rep is not None:  # protobuf >= 5.27 property (label() deprecated)
        return bool(is_rep() if callable(is_rep) else is_rep)
    return field.label == field.LABEL_REPEATED


def _scalar_leaves(msg) -> list[tuple[str, object]]:
    """All set scalar leaves of a message, depth-first, as (name, value)."""
    leaves: list[tuple[str, object]] = []
    for field, val in msg.ListFields():
        if field.type == field.TYPE_MESSAGE:
            items = val if _is_repeated(field) else [val]
            for item in items:
                leaves.extend(_scalar_leaves(item))
        elif not _is_repeated(field):
            leaves.append((field.name, val))
    return leaves


def _attr_pair(entry) -> tuple[str, object] | None:
    """Interpret one attribute-list entry as a (key, value) pair.

    Cloud-TPU shape: ``Attribute{key: "device-id", value{int_attr: 0}}``.
    The key is the string leaf named key/name; the value is the first
    other scalar leaf (wherever the oneof nests it).

    proto3 presence caveat: a zero-valued scalar (``int_attr: 0`` — chip
    0's index!) does not serialize, so the value submessage arrives
    present but leaf-less. That submessage's presence is the tell: it
    means "a value was set and it was the zero value" → 0, while a pair
    with no value submessage at all degrades to "".
    """
    leaves = _scalar_leaves(entry)
    key = next(
        (v for n, v in leaves if n in _KEY_FIELD_NAMES and isinstance(v, str)),
        None,
    )
    if key is None:
        return None
    rest = [v for n, v in leaves if not (n in _KEY_FIELD_NAMES and v == key)]
    if rest:
        return (key, rest[0])
    has_value_msg = any(
        field.type == field.TYPE_MESSAGE for field, _ in entry.ListFields()
    )
    return (key, 0) if has_value_msg else (key, "")


def _flatten_record(record) -> tuple[dict, float | None]:
    attrs: dict[str, object] = {}
    value: float | None = None

    for field, val in record.ListFields():
        lname = field.name.lower()
        is_attr_list = (
            _is_repeated(field)
            and field.type == field.TYPE_MESSAGE
            and any(hint in lname for hint in _ATTR_FIELD_HINTS)
        )
        if is_attr_list:
            for entry in val:
                pair = _attr_pair(entry)
                if pair is not None:
                    attrs[pair[0]] = pair[1]
            continue
        hinted = any(hint in lname for hint in _VALUE_FIELD_HINTS)
        if field.type == field.TYPE_MESSAGE and not _is_repeated(field):
            leaves = _scalar_leaves(val)
            for leaf_name, leaf_val in leaves:
                if (
                    hinted
                    and isinstance(leaf_val, (int, float))
                    and not isinstance(leaf_val, bool)
                ):
                    value = float(leaf_val)
                else:
                    attrs[leaf_name] = leaf_val
            if hinted and value is None:
                # proto3 presence: the measurement submessage is set but
                # all-defaults — "a value was recorded and it was zero"
                # (gauge{as_double: 0.0} serializes leaf-less).
                value = 0.0
        elif _is_repeated(field):
            continue  # repeated scalars / unhinted record lists: no meaning
        elif isinstance(val, (int, float)) and not isinstance(val, bool):
            if hinted:
                value = float(val)
            else:
                attrs[field.name] = val
        elif isinstance(val, str):
            attrs[field.name] = val
    return attrs, value


__all__ = [
    "StubBuildError",
    "DynamicServiceStub",
    "DynamicMethod",
    "DynamicStreamMethod",
    "build_pool",
    "build_stub",
    "message_records",
]
