"""gRPC monitoring backend — the DCGM-hostengine-analogue path (SURVEY.md §3.3).

The libtpu runtime hosts a local monitoring gRPC service (observed live on
127.0.0.1:8431 — ``tpuz.get_core_state_summary`` dials it and gets
``Connection refused`` when no runtime is attached, SURVEY.md §2.2). Its
protos are not shipped in this environment, so the metric transport is
built **from the server's own schema at runtime** (SURVEY.md §7 hard part
(c), solved rather than sidestepped):

1. reflection ``file_containing_symbol`` fetches the service's serialized
   descriptors (:mod:`tpumon.backends.reflection`);
2. :mod:`tpumon.backends.dynamic_stub` assembles them into a descriptor
   pool and materializes callable unary stubs;
3. metric enumeration and reads go over those stubs, with responses
   flattened generically into the SDK's per-row string-vector form.

Merge-and-dedupe with the SDK path (SURVEY.md §3.3 "merge into the same
registry … dedupe so coverage counts each metric once"): the libtpu SDK —
itself a client of this same service — remains the primary source for
every metric it lists; the gRPC stub serves metrics the SDK does *not*
list (the "SDK surface lags the service" case) and becomes the sole
transport when the SDK is absent entirely. ``sources()`` exposes the
per-metric routing for doctor/coverage accounting, and each unified name
appears exactly once in ``list_metrics()``.

When neither reflection nor the SDK is available the backend degrades to
the documented delegation-only behavior (reachability probing still works,
sampling raises BackendError).
"""

from __future__ import annotations

import logging
import threading
import time

from tpumon.backends.base import BackendError, RawMetric
from tpumon.discovery.topology import Topology, discover
from tpumon.trace import trace_span

log = logging.getLogger(__name__)

#: Full name of the runtime monitoring service to resolve via reflection.
#: The cloud-TPU runtime's public surface (tpu-info genre) names it
#: ``tpu.monitoring.runtime.RuntimeMetricService``; overridable for other
#: runtimes/tests via TPUMON_GRPC_SERVICE / --grpc-service.
DEFAULT_SERVICE = "tpu.monitoring.runtime.RuntimeMetricService"

#: Best-effort aliases: runtime gRPC metric names → libtpu SDK names, so
#: the same unified ``accelerator_*`` family is produced whichever
#: transport served the sample (dedupe requires one namespace).
GRPC_METRIC_ALIASES: dict[str, str] = {
    "tpu.runtime.hbm.memory.total.bytes": "hbm_capacity_total",
    "tpu.runtime.hbm.memory.usage.bytes": "hbm_capacity_usage",
    "tpu.runtime.tensorcore.dutycycle.percent": "duty_cycle_pct",
}

#: Tokens with no metric-identity content: vendor/namespace prefixes and
#: units. Dropped before comparing a server name to an SDK name.
_NOISE_TOKENS = frozenset(
    {
        "tpu", "runtime", "metric", "metrics", "bytes", "percent", "pct",
        "ratio", "microseconds", "usec", "us", "ms", "seconds", "sec",
    }
)

#: Known fused spellings → their split tokens, so "dutycycle.percent"
#: and "duty_cycle_pct" land on the same token set.
_COMPOUND_TOKENS: dict[str, tuple[str, ...]] = {
    "dutycycle": ("duty", "cycle"),
    "linkhealth": ("link", "health"),
    "linkbandwidth": ("link", "bandwidth"),
    "minrtt": ("min", "rtt"),
    "deliveryrate": ("delivery", "rate"),
    "queuesize": ("queue", "size"),
}


#: Spelling variants normalized to one token, so qualifier comparison
#: doesn't treat "…memory.used.bytes" and "…usage…" as different
#: siblings when they name the same measurement.
_SYNONYMS: dict[str, str] = {
    "used": "usage",
    "util": "utilization",
    "utilisation": "utilization",
}


def _semantic_tokens(name: str) -> frozenset:
    import re

    out: set[str] = set()
    for tok in re.split(r"[._\-/: ]+", name.lower()):
        if not tok or tok in _NOISE_TOKENS:
            continue
        for t in _COMPOUND_TOKENS.get(tok, (tok,)):
            out.add(_SYNONYMS.get(t, t))
    return frozenset(out)


#: Qualifier tokens that distinguish sibling metrics of one family
#: (hbm_capacity_usage vs hbm_capacity_total). A rename suspicion
#: requires both names to carry the SAME qualifiers — shared family
#: tokens alone (hbm+capacity) must never merge siblings.
_QUALIFIER_TOKENS = frozenset(
    {
        # "used" is absent on purpose: _SYNONYMS rewrites it to "usage"
        # before any qualifier comparison happens.
        "total", "usage", "free", "min", "max",
        "read", "write", "rx", "tx", "in", "out", "send", "recv",
    }
)


def suspect_rename(server_name: str, sdk_names) -> str | None:
    """The SDK metric ``server_name`` most plausibly renames, or None.

    Guard for the alias table being a best-effort guess
    (GRPC_METRIC_ALIASES): when the real service spells a metric
    differently than the guess, the raw name would otherwise enter the
    merged list **next to** the SDK name for the same physical metric —
    double-counting it in coverage accounting. Two shared semantic
    tokens (e.g. ``hbm``+``total`` for
    ``tpu.runtime.hbm.memory.total.bytes`` vs ``hbm_capacity_total``)
    with identical qualifier tokens mark the pair as the same metric;
    the SDK name wins, and doctor surfaces the suspicion (SURVEY §3.3
    "coverage counts each metric once").
    """
    server_tokens = _semantic_tokens(server_name)
    best: str | None = None
    best_overlap = 1  # require >= 2 shared tokens
    for sdk in sdk_names:
        sdk_tokens = _semantic_tokens(sdk)
        if (server_tokens & _QUALIFIER_TOKENS) != (
            sdk_tokens & _QUALIFIER_TOKENS
        ):
            continue  # usage vs total etc.: siblings, not renames
        overlap = len(server_tokens & sdk_tokens)
        if overlap > best_overlap:
            best, best_overlap = sdk, overlap
    return best


def _pick_metric_name(attrs: dict) -> str | None:
    """The metric name carried by one list-response record.

    Prefer a field whose key says it's the name (``metric_name``,
    ``name``, then any ``*name`` suffix — NOT bare substring matching,
    which would adopt ``namespace``): records can carry other string
    fields (unit, description) declared *before* the name, and "first
    non-empty string" would silently adopt one of those as the metric's
    identity — sampling that identity then returns empty forever.
    """
    for k, v in attrs.items():
        if k.lower() in ("metric_name", "name") and isinstance(v, str) and v:
            return v
    for k, v in attrs.items():
        if k.lower().endswith("name") and isinstance(v, str) and v:
            return v
    for v in attrs.values():
        if isinstance(v, str) and v:
            return v
    return None


#: After a stub build fails, wait this long before re-dialing reflection
#: (the 1 Hz poll loop calls list_metrics every second; a dead runtime
#: must not eat a reflection round-trip per poll).
_STUB_RETRY_SECONDS = 30.0

#: A streamed sample older than this is stale: the watch is presumed
#: wedged and the tick falls back to the unary poll. Ten 1 Hz pushes of
#: silence is decisive, yet short enough that a consumer sees at most a
#: brief gap in push-fed data.
_STREAM_FRESH_SECONDS = 10.0

#: After a watch stream dies, wait this long before re-opening it (unary
#: fallback carries the metric meanwhile) — same throttling rationale as
#: _STUB_RETRY_SECONDS, scaled to a cheaper operation.
_STREAM_RETRY_SECONDS = 15.0

#: Consecutive stub-call failures after which the cached stub is dropped
#: and rebuilt from reflection — a runtime restart can change the schema
#: out from under a long-running exporter, and a stale stub would
#: otherwise fail every poll for the life of the process.
_STUB_FAILURE_LIMIT = 3


#: Id-attribute ordering for composite keys: a (device, core) pair must
#: sort device-major regardless of attribute spelling or field order.
_ID_HINTS = ("device", "chip", "core", "index", "id")


def _id_rank(key: str) -> int:
    lkey = key.lower()
    for rank, hint in enumerate(_ID_HINTS):
        if hint in lkey:
            return rank
    return len(_ID_HINTS)


def _composite_ids_dense(keys: list[tuple]) -> bool:
    """True iff composite (major, ..., minor) id tuples tile a dense,
    duplicate-free grid: majors are 0..k-1 and each major carries the
    same dense minor set — the only layout positional relabeling can
    attribute correctly."""
    if len(set(keys)) != len(keys):
        return False
    majors: dict = {}
    for key in keys:
        majors.setdefault(key[0], []).append(key[1:])
    if sorted(majors) != list(range(len(majors))):
        return False
    minor_sets = [tuple(sorted(v)) for v in majors.values()]
    if len(set(minor_sets)) != 1:
        return False
    minors = minor_sets[0]
    if len(minors[0]) == 1:
        return [m[0] for m in minors] == list(range(len(minors)))
    return _composite_ids_dense(list(minors))


def _records_to_rows(records, metric: str = "") -> tuple[str, ...]:
    """(attrs, value) records → the SDK's per-row string vector.

    - records carrying integer id attributes (device/chip/core) sort by
      the id (device-major for composite ids) and emit plain value
      strings — the PER_CHIP/PER_CORE wire shape. The downstream parser
      labels these **by list position**, so dense ids ``0..n-1`` are
      validated: a sparse id set (chip 0 detached, 1..3 reporting) is
      dropped with a warning rather than silently re-attributed to the
      wrong chips;
    - records carrying a string attribute emit ``"key: value"`` — the
      KEYED wire shape;
    - a bare single record emits just the value.

    Records with no numeric value are dropped (a metric row without a
    measurement carries nothing for the parser).
    """
    rows: list[tuple[object, str]] = []
    single_ids: list[int] = []
    composite_ids: list[tuple] = []
    for attrs, value in records:
        if value is None:
            continue
        int_attrs = [
            (k, v)
            for k, v in attrs.items()
            if isinstance(v, int) and not isinstance(v, bool)
        ]
        # An id-named integer attribute wins even when auxiliary string
        # attributes (units, descriptions) ride along — otherwise a
        # PER_CHIP metric would mis-render as "percent: 20.0" keyed rows.
        id_attrs = sorted(
            (
                (k, v)
                for k, v in int_attrs
                if any(h in k.lower() for h in _ID_HINTS)
            ),
            key=lambda kv: (_id_rank(kv[0]), kv[0]),
        )
        str_attrs = [(k, v) for k, v in attrs.items() if isinstance(v, str) and v]
        if len(id_attrs) == 1:
            single_ids.append(id_attrs[0][1])
            rows.append(((0, (id_attrs[0][1],)), str(value)))
        elif len(id_attrs) > 1:
            # Per-core shape (device-id + core-id): device-major order by
            # the hint ranking above, not server send-order.
            key = tuple(v for _, v in id_attrs)
            composite_ids.append(key)
            rows.append(((0, key), str(value)))
        elif len(int_attrs) == 1 and not str_attrs:
            single_ids.append(int_attrs[0][1])
            rows.append(((0, (int_attrs[0][1],)), str(value)))
        elif str_attrs:
            rows.append(((1, str_attrs[0][1]), f"{str_attrs[0][1]}: {value}"))
        else:
            rows.append(((2, len(rows)), str(value)))
    # Positional relabeling downstream is only safe when the ids are
    # exactly 0..n-1: anything else would attribute samples to the wrong
    # device. Drop (absent ≠ wrong) and say so.
    if single_ids and sorted(single_ids) != list(range(len(single_ids))):
        log.warning(
            "%s: monitoring service returned non-contiguous device ids %s; "
            "dropping samples to avoid misattributing them by position",
            metric or "metric",
            sorted(single_ids),
        )
        return ()
    if composite_ids and not _composite_ids_dense(composite_ids):
        # Same hazard as above for (device, core) rows: the flattened
        # list is relabeled positionally downstream, so every device must
        # be present with a dense 0..k-1 core set.
        log.warning(
            "%s: monitoring service returned sparse/duplicate composite "
            "ids %s; dropping samples to avoid misattributing them",
            metric or "metric",
            sorted(composite_ids),
        )
        return ()
    rows.sort(key=lambda r: r[0])
    return tuple(text for _, text in rows)


class _MetricWatch:
    """Latest-sample cache for one metric's server-streaming watch.

    The SURVEY §3.3 "subscribe" half: a reader thread drains the
    runtime's push stream and keeps only the newest converted row
    vector; the 1 Hz poll serves that cached sample when fresh and falls
    back to the unary read otherwise. Mirrors the exporter's own
    ``grpc_service.py`` Watch from the consumer side: push when the
    stream is healthy, poll when it is not, same families either way.
    """

    def __init__(self, metric: str, server_name: str, open_call, convert) -> None:
        self.metric = metric
        #: The server-side spelling this watch subscribed with; a rename
        #: in a later enumeration invalidates the subscription.
        self.server_name = server_name
        self._open_call = open_call  # () -> live gRPC stream call
        self._convert = convert  # response message -> row tuple
        self._lock = threading.Lock()
        self._rows: tuple[str, ...] | None = None
        self._at = 0.0
        self._call = None
        self._thread: threading.Thread | None = None
        self._died_at: float | None = None
        self._closed = False

    def fresh_rows(self, window: float) -> tuple[str, ...] | None:
        """The newest streamed rows if pushed within ``window`` seconds."""
        with self._lock:
            if (
                self._rows is not None
                and time.monotonic() - self._at <= window
            ):
                return self._rows
        return None

    def ensure_running(self) -> None:
        """Open the stream (throttled after a death); no-op when live."""
        with self._lock:
            if self._closed:
                return
            if self._thread is not None and self._thread.is_alive():
                return
            now = time.monotonic()
            if (
                self._died_at is not None
                and now - self._died_at < _STREAM_RETRY_SECONDS
            ):
                return
            try:
                call = self._open_call()
            except Exception as exc:
                log.debug("watch(%s) failed to open: %s", self.metric, exc)
                self._died_at = now
                return
            self._call = call
            self._thread = threading.Thread(
                target=self._run,
                args=(call,),
                name=f"tpumon-watch-{self.metric}",  # thread: grpc-watch — per-metric f-string name, one stable role
                daemon=True,
            )
            self._thread.start()

    def _run(self, call) -> None:
        try:
            for resp in call:
                rows = self._convert(resp)
                with self._lock:
                    self._rows = rows
                    self._at = time.monotonic()
        except Exception as exc:
            if not self._closed:
                log.debug("watch(%s) stream ended: %s", self.metric, exc)
        finally:
            with self._lock:
                # Server-completed streams land here too: a clean end
                # still means "no more pushes", so throttle the reopen.
                if not self._closed:
                    self._died_at = time.monotonic()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            call = self._call
        if call is not None:
            try:
                call.cancel()
            except Exception as exc:
                log.debug("watch cancel failed (already dead?): %s", exc)


class GrpcMonitoringBackend:
    name = "grpc"

    def __init__(
        self,
        addr: str = "localhost:8431",
        timeout: float = 2.0,
        topology_file: str | None = None,
        service: str = DEFAULT_SERVICE,
        watch: bool = True,
        retry=None,
    ) -> None:
        from tpumon.resilience import RetryCounter, RetryPolicy

        self.addr = addr
        self.timeout = timeout
        self.service = service
        #: Transport-level retry (bounded exponential backoff with
        #: jitter, tpumon/resilience/policy.py) around each unary RPC;
        #: the per-attempt deadline stays ``timeout``. Sustained failure
        #: is the collector-level circuit breaker's job, not retries'.
        self.retry = retry if retry is not None else RetryPolicy()
        #: Retries performed, by call kind — folded into
        #: tpumon_retries_total by the poller (delta-read).
        self._retries = RetryCounter()
        #: Subscribe to a server-streaming watch method when the service
        #: has one; False pins every read to the unary poll (ops escape
        #: hatch, TPUMON_GRPC_WATCH=0).
        self.watch = watch
        self._topology_file = topology_file
        self._channel = None
        self._stub = None
        self._stub_failed_at: float | None = None
        self._stub_call_failures = 0
        self._list_method: str | None = None
        self._get_method: str | None = None
        self._watch_method: str | None = None
        self._watches: dict[str, _MetricWatch] = {}
        #: Instance-level staleness window so tests (and unusual poll
        #: intervals) can tune it without reaching into module globals.
        self.stream_fresh_seconds = _STREAM_FRESH_SECONDS
        self._sources: dict[str, str] = {}
        self._suspected_renames: dict[str, str] = {}
        #: unified SDK-style name → the server's own metric name.
        self._grpc_names: dict[str, str] = {}
        try:
            import grpc

            self._grpc = grpc
            self._channel = grpc.insecure_channel(addr)
        except Exception as exc:
            log.warning("grpcio unavailable (%s); reachability checks off", exc)
            self._grpc = None
        # The SDK rides the same service; it stays the primary transport
        # for every metric it lists (merge/dedupe contract above). Its
        # absence switches the backend to grpc-only mode, not failure.
        self._delegate = None
        self._topology: Topology | None = None
        try:
            from tpumon.backends.libtpu_backend import LibtpuBackend

            self._delegate = LibtpuBackend(topology_file)
            # Share the configured transport-retry policy with the SDK
            # delegate (attribute, not ctor kwarg: test doubles keep the
            # original constructor signature).
            self._delegate.retry = self.retry
        except BackendError as exc:
            log.info("libtpu SDK unavailable (%s); grpc-only mode", exc)

    # -- probes -----------------------------------------------------------

    def grpc_available(self) -> bool:
        """False when grpcio itself is missing (vs the service being down)."""
        return self._channel is not None

    def service_reachable(self) -> bool:
        """True iff the runtime monitoring service accepts connections."""
        if self._channel is None:
            return False
        try:
            fut = self._grpc.channel_ready_future(self._channel)
            fut.result(timeout=self.timeout)
            return True
        except Exception as exc:
            log.debug("monitoring service unreachable: %s", exc)
            return False

    def services(self) -> list[str] | None:
        """Names of the gRPC services the endpoint exposes, via hand-rolled
        server reflection (tpumon.backends.reflection — no protos shipped).
        None when unreachable or reflection is not spoken."""
        if self._channel is None:
            return None
        from tpumon.backends.reflection import list_services

        return list_services(self._channel, self.timeout)

    # -- dynamic stub -----------------------------------------------------

    def _ensure_stub(self):
        """Build (or return) the reflection-derived stub; None when the
        service/schema is unavailable (retry throttled to avoid a
        reflection dial per 1 Hz poll)."""
        if self._stub is not None:
            return self._stub
        if self._channel is None:
            return None
        now = time.monotonic()
        if (
            self._stub_failed_at is not None
            and now - self._stub_failed_at < _STUB_RETRY_SECONDS
        ):
            return None
        from tpumon.backends.dynamic_stub import StubBuildError, build_stub

        try:
            stub = build_stub(self._channel, self.service, self.timeout)
        except StubBuildError as exc:
            log.debug("monitoring stub unavailable: %s", exc)
            self._stub_failed_at = now
            return None
        self._list_method = self._pick_method(stub, want_list=True)
        self._get_method = self._pick_method(stub, want_list=False)
        self._watch_method = (
            self._pick_watch_method(stub) if self.watch else None
        )
        if self._get_method is None:
            log.warning(
                "service %s has no metric-read method (methods: %s)",
                self.service,
                sorted(stub.methods),
            )
            self._stub_failed_at = now
            return None
        self._stub = stub
        self._stub_failed_at = None
        self._stub_call_failures = 0
        log.info(
            "monitoring stub built from reflection: %s (list=%s get=%s "
            "watch=%s)",
            self.service,
            self._list_method,
            self._get_method,
            self._watch_method,
        )
        return stub

    def _note_stub_call(self, ok: bool) -> None:
        """Track consecutive stub-call failures; drop the cached stub
        after _STUB_FAILURE_LIMIT so the (throttled) rebuild path can
        re-resolve a schema that changed under us (runtime restart)."""
        if ok:
            self._stub_call_failures = 0
            return
        self._stub_call_failures += 1
        if self._stub is not None and (
            self._stub_call_failures >= _STUB_FAILURE_LIMIT
        ):
            log.warning(
                "dropping monitoring stub after %d consecutive call "
                "failures; will rebuild from reflection",
                self._stub_call_failures,
            )
            self._stub = None
            self._stub_failed_at = time.monotonic()
            self._stub_call_failures = 0
            # Watches hold method callables from the dropped stub; a
            # schema change would leave them decoding stale shapes.
            self._close_watches()

    @staticmethod
    def _pick_method(stub, want_list: bool) -> str | None:
        for name in sorted(stub.methods):
            lname = name.lower()
            if "metric" not in lname:
                continue
            if want_list == ("list" in lname or "supported" in lname):
                return name
        return None

    @staticmethod
    def _pick_watch_method(stub) -> str | None:
        """A server-streaming metric-read method, if the service has one.

        Prefer an explicit subscribe spelling; otherwise any streaming
        method about metrics — the monitoring genre has exactly one.
        """
        hints = ("watch", "stream", "subscribe", "monitor")
        candidates = [
            n for n in sorted(stub.stream_methods) if "metric" in n.lower()
        ]
        for name in candidates:
            if any(h in name.lower() for h in hints):
                return name
        return candidates[0] if candidates else None

    def _close_watches(self) -> None:
        watches, self._watches = self._watches, {}
        for watch in watches.values():
            watch.close()

    def _watch_rows(
        self, stub, unified: str, server_name: str
    ) -> tuple[str, ...] | None:
        """Fresh push-fed rows for ``unified``, or None (→ unary poll).

        Lazily opens the watch on first request for the metric; the
        stream warms up in the background while unary carries the tick.
        """
        from tpumon.backends.dynamic_stub import message_records

        watch = self._watches.get(unified)
        if watch is None:
            method = stub.stream_methods[self._watch_method]
            name_field = self._request_name_field(method)
            fields = {name_field: server_name} if name_field else {}

            def open_call():
                return stub.open_stream(self._watch_method, **fields)

            def convert(resp) -> tuple[str, ...]:
                return _records_to_rows(
                    message_records(resp), metric=unified
                )

            watch = _MetricWatch(unified, server_name, open_call, convert)
            self._watches[unified] = watch
        watch.ensure_running()
        return watch.fresh_rows(self.stream_fresh_seconds)

    @staticmethod
    def _request_name_field(method) -> str | None:
        """The request field carrying the metric name: ``metric_name``
        preferred, else the first string field."""
        desc = method.request_class.DESCRIPTOR
        for field in desc.fields:
            if field.name == "metric_name":
                return field.name
        for field in desc.fields:
            if field.type == field.TYPE_STRING:
                return field.name
        return None

    def _retrying(self, call: str, fn):
        """Run one unary RPC under the transport retry policy, counting
        retries by call kind."""
        return self._retries.call(call, fn, self.retry)

    def retry_counts(self) -> dict[str, int]:
        """Cumulative transport-retry counts by call kind (this backend
        plus the SDK delegate) — the tpumon_retries_total feed."""
        out = self._retries.counts()
        if self._delegate is not None:
            delegate_counts = getattr(self._delegate, "retry_counts", None)
            if delegate_counts is not None:
                for call, n in delegate_counts().items():
                    out[call] = out.get(call, 0) + n
        return out

    def reset(self) -> None:
        """Watchdog recovery: tear down the channel (failing any
        in-flight RPC at the transport layer), drop the cached stub and
        watches, and re-dial a fresh channel so the next poll rebuilds
        from reflection immediately (no retry throttle)."""
        log.warning("resetting monitoring channel to %s (recovery)", self.addr)
        self._close_watches()
        self._stub = None
        self._stub_failed_at = None
        self._stub_call_failures = 0
        if self._channel is not None:
            try:
                self._channel.close()
            except Exception as exc:
                log.debug("channel close failed during reset: %s", exc)
            self._channel = None
        if self._grpc is not None:
            try:
                self._channel = self._grpc.insecure_channel(self.addr)
            except Exception as exc:
                log.warning("channel re-dial failed: %s", exc)
        delegate_reset = getattr(self._delegate, "reset", None)
        if delegate_reset is not None:
            delegate_reset()

    def _grpc_list(self) -> dict[str, str]:
        """Enumerate the service's metrics → {unified name: server name}."""
        stub = self._ensure_stub()
        if stub is None or self._list_method is None:
            return {}
        from tpumon.backends.dynamic_stub import message_records

        try:
            # Nested under the poll cycle's list_metrics span when the
            # exporter's trace plane is on (tpumon.trace); no-op
            # otherwise — doctor and ad-hoc callers pay nothing.
            with trace_span(f"rpc:{self._list_method}", stage="backend_rpc"):
                resp = self._retrying(
                    "grpc:list",
                    lambda: stub.call(self._list_method, timeout=self.timeout),
                )
        except Exception as exc:
            log.debug("grpc %s failed: %s", self._list_method, exc)
            self._note_stub_call(ok=False)
            return {}
        self._note_stub_call(ok=True)
        names: dict[str, str] = {}
        for attrs, _ in message_records(resp):
            name = _pick_metric_name(attrs)
            if name:
                names[GRPC_METRIC_ALIASES.get(name, name)] = name
        return names

    def _grpc_sample(self, unified: str) -> RawMetric:
        stub = self._ensure_stub()
        if stub is None or self._get_method is None:
            raise BackendError(
                f"monitoring service stub unavailable for {unified}"
            )
        from tpumon.backends.dynamic_stub import message_records

        server_name = self._grpc_names.get(unified, unified)
        if self._watch_method is not None:
            rows = self._watch_rows(stub, unified, server_name)
            if rows is not None:
                return RawMetric(unified, rows)
        method = stub.methods[self._get_method]
        name_field = self._request_name_field(method)
        fields = {name_field: server_name} if name_field else {}
        try:
            with trace_span(
                f"rpc:{self._get_method}:{server_name}", stage="backend_rpc"
            ):
                resp = self._retrying(
                    "grpc:get",
                    lambda: stub.call(
                        self._get_method, timeout=self.timeout, **fields
                    ),
                )
        except Exception as exc:
            self._note_stub_call(ok=False)
            raise BackendError(
                f"grpc {self._get_method}({server_name}) failed: {exc}"
            ) from exc
        self._note_stub_call(ok=True)
        return RawMetric(
            unified, _records_to_rows(message_records(resp), metric=unified)
        )

    # -- Backend protocol -------------------------------------------------

    def list_metrics(self) -> tuple[str, ...]:
        """Union of SDK metrics and gRPC-only metrics, each name once.

        SDK names keep SDK routing (primary path); names only the service
        lists route to the stub. Routing is exposed via :meth:`sources`.
        """
        sdk_names: tuple[str, ...] = ()
        if self._delegate is not None:
            sdk_names = self._delegate.list_metrics()
        grpc_names = self._grpc_list()
        self._grpc_names = grpc_names
        sources = {name: "sdk" for name in sdk_names}
        merged = list(sdk_names)
        suspected: dict[str, str] = {}
        for name in grpc_names:
            if name in sources:
                continue  # aliased/exact dedupe: SDK stays primary
            match = suspect_rename(grpc_names[name], sdk_names)
            if match is not None:
                # Likely the same physical metric under the server's own
                # spelling: counting it again would inflate coverage and
                # serve one measurement under two families. Route nothing;
                # remember the suspicion for doctor.
                suspected[grpc_names[name]] = match
                continue
            sources[name] = "grpc"
            merged.append(name)
        self._sources = sources
        self._suspected_renames = suspected
        # Reconcile watches against the fresh enumeration: a metric that
        # left the grpc routing (delisted, or rerouted to the SDK) or
        # changed its server-side spelling would otherwise leak a parked
        # reader thread + open server stream for the life of the process.
        for name, watch in list(self._watches.items()):
            if (
                sources.get(name) != "grpc"
                or grpc_names.get(name, name) != watch.server_name
            ):
                self._watches.pop(name).close()
        if suspected:
            log.info(
                "grpc metrics suppressed as suspected SDK renames: %s",
                ", ".join(f"{g}→{s}" for g, s in sorted(suspected.items())),
            )
        if not merged and self._delegate is None:
            raise BackendError(
                "no metric source: libtpu SDK absent and monitoring "
                f"service at {self.addr} unavailable"
            )
        return tuple(merged)

    def sources(self) -> dict[str, str]:
        """Per-metric transport routing from the last list_metrics():
        unified name → 'sdk' | 'grpc' (the dedupe accounting surface)."""
        return dict(self._sources)

    def suspected_renames(self) -> dict[str, str]:
        """Server metric names suppressed from the merged list because
        they look like renamed SDK metrics (server name → SDK name), from
        the last list_metrics(). Doctor warns on these."""
        return dict(self._suspected_renames)

    def watch_states(self) -> dict[str, str]:
        """Per-metric watch-stream state (doctor's push/poll surface):
        'streaming' = fresh push-fed rows are serving the poll;
        'open-idle' = stream up but nothing pushed inside the freshness
        window (unary fallback carries the metric);
        'down' = stream dead, reopen throttled (unary fallback)."""
        out: dict[str, str] = {}
        for name, watch in self._watches.items():
            if watch.fresh_rows(self.stream_fresh_seconds) is not None:
                out[name] = "streaming"
            elif watch._thread is not None and watch._thread.is_alive():
                out[name] = "open-idle"
            else:
                out[name] = "down"
        return out

    def sample(self, name: str) -> RawMetric:
        source = self._sources.get(name)
        if source == "grpc":
            return self._grpc_sample(name)
        if source == "sdk" or self._delegate is not None:
            return self._delegate.sample(name)
        return self._grpc_sample(name)

    def core_states(self) -> dict[str, str]:
        if self._delegate is not None:
            return self._delegate.core_states()
        return {}

    def topology(self) -> Topology:
        if self._delegate is not None:
            return self._delegate.topology()
        if self._topology is None:
            self._topology = discover(self._topology_file)
        return self._topology

    def version(self) -> str:
        if self._delegate is not None:
            return self._delegate.version()
        return f"grpc:{self.service}"

    def close(self) -> None:
        self._close_watches()
        if self._channel is not None:
            try:
                self._channel.close()
            except Exception as exc:
                log.debug("channel close failed: %s", exc)
        if self._delegate is not None:
            self._delegate.close()


__all__ = [
    "GrpcMonitoringBackend",
    "BackendError",
    "DEFAULT_SERVICE",
    "GRPC_METRIC_ALIASES",
    "suspect_rename",
]
