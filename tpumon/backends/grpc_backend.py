"""gRPC monitoring backend — the DCGM-hostengine-analogue path (SURVEY.md §3.3).

The libtpu runtime hosts a local monitoring gRPC service (observed live on
127.0.0.1:8431 — ``tpuz.get_core_state_summary`` dials it and gets
``Connection refused`` when no runtime is attached, SURVEY.md §2.2). Its
proto surface is not shipped in this environment, so this backend:

1. Probes channel reachability itself (``service_reachable`` → the
   ``exporter_grpc_service_up`` signal and /healthz detail), and
2. Delegates metric reads to the libtpu SDK, which is a client of the same
   service — keeping coverage accounting honest (SURVEY.md §7 hard part (c):
   'degrade gracefully to the SDK path') while still exercising the
   process-boundary the DCGM path implies.

When the protos become available, ``sample`` can switch to direct stubs
without touching the exporter core (same Backend protocol).
"""

from __future__ import annotations

import logging

from tpumon.backends.base import BackendError, RawMetric
from tpumon.backends.libtpu_backend import LibtpuBackend
from tpumon.discovery.topology import Topology

log = logging.getLogger(__name__)


class GrpcMonitoringBackend:
    name = "grpc"

    def __init__(
        self,
        addr: str = "localhost:8431",
        timeout: float = 2.0,
        topology_file: str | None = None,
    ) -> None:
        self.addr = addr
        self.timeout = timeout
        self._channel = None
        try:
            import grpc

            self._grpc = grpc
            self._channel = grpc.insecure_channel(addr)
        except Exception as exc:
            log.warning("grpcio unavailable (%s); reachability checks off", exc)
            self._grpc = None
        # The SDK rides the same service; it is the metric transport.
        self._delegate = LibtpuBackend(topology_file)

    def grpc_available(self) -> bool:
        """False when grpcio itself is missing (vs the service being down)."""
        return self._channel is not None

    def service_reachable(self) -> bool:
        """True iff the runtime monitoring service accepts connections."""
        if self._channel is None:
            return False
        try:
            fut = self._grpc.channel_ready_future(self._channel)
            fut.result(timeout=self.timeout)
            return True
        except Exception:
            return False

    def services(self) -> list[str] | None:
        """Names of the gRPC services the endpoint exposes, via hand-rolled
        server reflection (tpumon.backends.reflection — no protos shipped).
        None when unreachable or reflection is not spoken."""
        if self._channel is None:
            return None
        from tpumon.backends.reflection import list_services

        return list_services(self._channel, self.timeout)

    def list_metrics(self) -> tuple[str, ...]:
        return self._delegate.list_metrics()

    def sample(self, name: str) -> RawMetric:
        return self._delegate.sample(name)

    def topology(self) -> Topology:
        return self._delegate.topology()

    def version(self) -> str:
        return self._delegate.version()

    def close(self) -> None:
        if self._channel is not None:
            try:
                self._channel.close()
            except Exception:
                pass
        self._delegate.close()


__all__ = ["GrpcMonitoringBackend", "BackendError"]
