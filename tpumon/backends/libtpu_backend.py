"""libtpu device backend — the NVML-replacement path (SURVEY.md §1 L1, §2.3).

Adapter over ``libtpu.sdk.tpumonitoring`` (runtime metrics),
``libtpu.sdk.slice`` (chip coordinates, consumed via discovery), and
``libtpu.sdk.tpuz`` (core state). Where the reference genre does ctypes FFI
into ``libnvidia-ml.so``, this consumes the libtpu wheel's shipped SDK over
its native ``.so`` (surface verified live on libtpu 0.0.34, SURVEY.md §2.2).

Operational facts encoded here, all observed live:

- ``get_metric(name).data()`` returns ``[]`` for every metric when no
  runtime/workload is attached to the TPU — that is a valid "no sample"
  state, not an error and not zero.
- ``slice.get_chip_coordinates()`` can raise ``RuntimeError`` on hosts whose
  hostname carries no worker index; discovery treats coords as optional.
- ``tpuz.get_core_state_summary()`` dials the local monitoring gRPC port
  (127.0.0.1:8431) and raises when the runtime is down; the core-state
  collector degrades to absent.
"""

from __future__ import annotations

import logging

from tpumon.backends.base import BackendError, RawMetric
from tpumon.discovery.topology import Topology, discover

log = logging.getLogger(__name__)


class LibtpuBackend:
    name = "libtpu"

    def __init__(self, topology_file: str | None = None, retry=None) -> None:
        try:
            from libtpu.sdk import tpumonitoring
        except Exception as exc:  # ImportError or native-load failure
            raise BackendError(f"libtpu SDK unavailable: {exc}") from exc
        from tpumon.resilience import RetryCounter, RetryPolicy

        self._mon = tpumonitoring
        self._topology_file = topology_file
        self._topology = discover(topology_file)
        #: Transport-level retry (tpumon/resilience/policy.py): one SDK
        #: call blip — a runtime restarting mid-poll — is absorbed here;
        #: sustained failure belongs to the collector's circuit breaker.
        self.retry = retry if retry is not None else RetryPolicy()
        #: Retries performed, by call kind (tpumon_retries_total feed).
        self._retries = RetryCounter()
        #: Set by reset() (watchdog thread); consumed on the poller
        #: thread before the next SDK call.
        self._needs_rebind = False

    def _retrying(self, call: str, fn):
        return self._retries.call(call, fn, self.retry)

    def retry_counts(self) -> dict[str, int]:
        return self._retries.counts()

    def list_metrics(self) -> tuple[str, ...]:
        self._maybe_rebind()
        try:
            return tuple(
                self._retrying(
                    "libtpu:list", self._mon.list_supported_metrics
                )
            )
        except Exception as exc:
            raise BackendError(f"list_supported_metrics failed: {exc}") from exc

    def sample(self, name: str) -> RawMetric:
        self._maybe_rebind()
        try:
            data = self._retrying(
                "libtpu:sample", lambda: self._mon.get_metric(name).data()
            )
        except Exception as exc:
            raise BackendError(f"get_metric({name}) failed: {exc}") from exc
        if data is None:
            return RawMetric(name, ())
        return RawMetric(name, tuple(str(entry) for entry in data))

    def reset(self) -> None:
        """Watchdog recovery hook (runs on the watchdog thread).

        The SDK is in-process: a stuck native call cannot be failed from
        another thread (unlike the gRPC channel-close path), and
        reloading the module concurrently with an in-flight native call
        could corrupt the process. So reset() only *schedules* a re-bind
        of the SDK entry points + re-discovery; the poller thread
        performs it before its next SDK call — recovery for a runtime
        restart that left the cached module handle pointing at dead
        state, not for an unabortable native hang."""
        self._needs_rebind = True

    def _maybe_rebind(self) -> None:
        if not self._needs_rebind:
            return
        self._needs_rebind = False
        try:
            import importlib

            self._mon = importlib.reload(self._mon)
        except Exception as exc:
            log.warning("libtpu SDK re-bind failed: %s", exc)
        try:
            self._topology = discover(self._topology_file)
        except Exception as exc:
            log.warning("topology re-discovery failed: %s", exc)

    def core_states(self) -> dict[str, str]:
        """Per-core state via tpuz; empty dict when the runtime is down."""
        try:
            from libtpu.sdk import tpuz

            summary = tpuz.get_core_state_summary()
        except Exception as exc:
            log.debug("core state unavailable: %s", exc)
            return {}
        if isinstance(summary, dict):
            return {str(k): str(v) for k, v in summary.items()}
        return {"summary": str(summary)}

    def topology(self) -> Topology:
        return self._topology

    def version(self) -> str:
        try:
            import importlib.metadata as md

            return md.version("libtpu")
        except Exception as exc:
            log.debug("libtpu version lookup failed: %s", exc)
            return "unknown"

    def close(self) -> None:
        pass
