"""libtpu device backend — the NVML-replacement path (SURVEY.md §1 L1, §2.3).

Adapter over ``libtpu.sdk.tpumonitoring`` (runtime metrics),
``libtpu.sdk.slice`` (chip coordinates, consumed via discovery), and
``libtpu.sdk.tpuz`` (core state). Where the reference genre does ctypes FFI
into ``libnvidia-ml.so``, this consumes the libtpu wheel's shipped SDK over
its native ``.so`` (surface verified live on libtpu 0.0.34, SURVEY.md §2.2).

Operational facts encoded here, all observed live:

- ``get_metric(name).data()`` returns ``[]`` for every metric when no
  runtime/workload is attached to the TPU — that is a valid "no sample"
  state, not an error and not zero.
- ``slice.get_chip_coordinates()`` can raise ``RuntimeError`` on hosts whose
  hostname carries no worker index; discovery treats coords as optional.
- ``tpuz.get_core_state_summary()`` dials the local monitoring gRPC port
  (127.0.0.1:8431) and raises when the runtime is down; the core-state
  collector degrades to absent.
"""

from __future__ import annotations

import logging

from tpumon.backends.base import BackendError, RawMetric
from tpumon.discovery.topology import Topology, discover

log = logging.getLogger(__name__)


class LibtpuBackend:
    name = "libtpu"

    def __init__(self, topology_file: str | None = None) -> None:
        try:
            from libtpu.sdk import tpumonitoring
        except Exception as exc:  # ImportError or native-load failure
            raise BackendError(f"libtpu SDK unavailable: {exc}") from exc
        self._mon = tpumonitoring
        self._topology = discover(topology_file)

    def list_metrics(self) -> tuple[str, ...]:
        try:
            return tuple(self._mon.list_supported_metrics())
        except Exception as exc:
            raise BackendError(f"list_supported_metrics failed: {exc}") from exc

    def sample(self, name: str) -> RawMetric:
        try:
            data = self._mon.get_metric(name).data()
        except Exception as exc:
            raise BackendError(f"get_metric({name}) failed: {exc}") from exc
        if data is None:
            return RawMetric(name, ())
        return RawMetric(name, tuple(str(entry) for entry in data))

    def core_states(self) -> dict[str, str]:
        """Per-core state via tpuz; empty dict when the runtime is down."""
        try:
            from libtpu.sdk import tpuz

            summary = tpuz.get_core_state_summary()
        except Exception as exc:
            log.debug("core state unavailable: %s", exc)
            return {}
        if isinstance(summary, dict):
            return {str(k): str(v) for k, v in summary.items()}
        return {"summary": str(summary)}

    def topology(self) -> Topology:
        return self._topology

    def version(self) -> str:
        try:
            import importlib.metadata as md

            return md.version("libtpu")
        except Exception:
            return "unknown"

    def close(self) -> None:
        pass
