"""Stub backend: zero accelerators (BASELINE.json config 1).

The exporter must run on CPU-only nodes of a mixed pool and expose
``accelerator_device_count 0`` plus its self-telemetry, never crashing for
lack of a device (SURVEY.md §3.1 'fallback: zero devices → stub mode').
"""

from __future__ import annotations

import socket

from tpumon.backends.base import RawMetric
from tpumon.discovery.topology import Topology


class StubBackend:
    name = "stub"

    def __init__(self, topology: Topology | None = None) -> None:
        self._topology = topology or Topology(
            accelerator_type="none", hostname=socket.gethostname(), chips=()
        )

    def list_metrics(self) -> tuple[str, ...]:
        return ()

    def sample(self, name: str) -> RawMetric:
        return RawMetric(name, ())

    def topology(self) -> Topology:
        return self._topology

    def version(self) -> str:
        from tpumon import __version__

        return __version__

    def close(self) -> None:
        pass
