"""Hand-rolled gRPC server-reflection client.

The cloud-TPU runtime hosts its monitoring gRPC service locally
(127.0.0.1:8431, SURVEY.md §2.2) but its protos are not shipped in this
environment — and neither is ``grpcio-reflection``. The reflection
protocol itself, though, is tiny for the two calls we need, both over the
bidi-streaming ``ServerReflectionInfo`` method:

- ``list_services`` (request field 7) → service names
  (``list_services_response.service[].name``, fields 6 → 1 → 1);
- ``file_containing_symbol`` (request field 6) → the serialized
  ``FileDescriptorProto`` set defining a symbol
  (``file_descriptor_response.file_descriptor_proto``, fields 4 → 1) —
  the input :mod:`tpumon.backends.dynamic_stub` turns into callable
  method stubs at runtime, which is how the grpc backend reads metrics
  from a service whose protos were never installed (SURVEY.md §3.3,
  §7 hard part (c)).

This module encodes/decodes exactly that with a ~40-line varint codec —
the same no-proto approach as ``tpumon/attribution/podresources_pb2.py``.

Wire reference (public grpc reflection.proto, v1alpha):

    ServerReflectionRequest  { host=1; file_containing_symbol=6;
                               list_services=7; }
    ServerReflectionResponse { file_descriptor_response=4;
                               list_services_response=6; error_response=7 }
    FileDescriptorResponse   { repeated bytes file_descriptor_proto=1; }
    ListServiceResponse      { repeated ServiceResponse service=1; }
    ServiceResponse          { name=1; }
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)

REFLECTION_METHOD = (
    "/grpc.reflection.v1alpha.ServerReflection/ServerReflectionInfo"
)


def _encode_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _decode_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        byte = data[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _len_field(field: int, payload: bytes) -> bytes:
    return _encode_varint((field << 3) | 2) + _encode_varint(len(payload)) + payload


def _iter_fields(data: bytes):
    """Yield (field_number, wire_type, value, end_pos) over a message."""
    pos = 0
    while pos < len(data):
        tag, pos = _decode_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            value, pos = _decode_varint(data, pos)
        elif wire == 2:  # length-delimited
            length, pos = _decode_varint(data, pos)
            if pos + length > len(data):
                raise ValueError("truncated field")
            value = data[pos : pos + length]
            pos += length
        elif wire == 5:  # fixed32
            if pos + 4 > len(data):
                raise ValueError("truncated fixed32")
            value, pos = data[pos : pos + 4], pos + 4
        elif wire == 1:  # fixed64
            if pos + 8 > len(data):
                raise ValueError("truncated fixed64")
            value, pos = data[pos : pos + 8], pos + 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


def encode_list_services_request() -> bytes:
    """ServerReflectionRequest{list_services: "*"} (field 7, string)."""
    return _len_field(7, b"*")


def encode_file_containing_symbol_request(symbol: str) -> bytes:
    """ServerReflectionRequest{file_containing_symbol: symbol} (field 6)."""
    return _len_field(6, symbol.encode("utf-8"))


def decode_file_descriptor_response(data: bytes) -> list[bytes]:
    """ServerReflectionResponse → serialized FileDescriptorProto blobs.

    [] when the response is an error_response (unknown symbol) or carries
    no descriptors — both well-formed protocol outcomes.
    """
    blobs: list[bytes] = []
    for field, wire, value in _iter_fields(data):
        if field == 4 and wire == 2:  # file_descriptor_response
            for f2, w2, fdp in _iter_fields(value):
                if f2 == 1 and w2 == 2:  # file_descriptor_proto (bytes)
                    blobs.append(fdp)
    return blobs


def file_containing_symbol(
    channel, symbol: str, timeout: float = 2.0
) -> list[bytes] | None:
    """Fetch the FileDescriptorProto set defining ``symbol`` (a service or
    message full name) via reflection.

    Returns the serialized blobs (the defining file plus any transitive
    dependencies the server chooses to include), [] when the server
    answered but doesn't know the symbol, None when the server is
    unreachable / doesn't speak reflection.
    """
    try:
        call = channel.stream_stream(
            REFLECTION_METHOD,
            request_serializer=None,
            response_deserializer=None,
        )
        responses = call(
            iter([encode_file_containing_symbol_request(symbol)]),
            timeout=timeout,
        )
        try:
            for raw in responses:
                return decode_file_descriptor_response(raw)
            return []
        finally:
            responses.cancel()
    except Exception as exc:
        log.debug("reflection file_containing_symbol(%s) failed: %s", symbol, exc)
        return None


def decode_list_services_response(data: bytes) -> list[str]:
    """ServerReflectionResponse → service names; [] when the response is an
    error_response or carries no list (both are well-formed protocol
    outcomes, not parse failures)."""
    names: list[str] = []
    for field, wire, value in _iter_fields(data):
        if field == 6 and wire == 2:  # list_services_response
            for f2, w2, svc in _iter_fields(value):
                if f2 == 1 and w2 == 2:  # ServiceResponse
                    for f3, w3, name in _iter_fields(svc):
                        if f3 == 1 and w3 == 2:  # name
                            names.append(name.decode("utf-8", "replace"))
    return names


def list_services(channel, timeout: float = 2.0) -> list[str] | None:
    """Enumerate services via reflection; None when the server doesn't
    speak reflection / is unreachable (callers fall back to the boolean
    channel probe)."""
    try:
        call = channel.stream_stream(
            REFLECTION_METHOD,
            request_serializer=None,  # raw bytes in
            response_deserializer=None,  # raw bytes out
        )
        responses = call(
            iter([encode_list_services_request()]), timeout=timeout
        )
        try:
            for raw in responses:
                return sorted(decode_list_services_response(raw))
            return []
        finally:
            # One response is all we take; cancel the bidi stream instead
            # of leaving it open until GC (matters for per-poll callers).
            responses.cancel()
    except Exception as exc:
        log.debug("reflection list_services failed: %s", exc)
        return None
