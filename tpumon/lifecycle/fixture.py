"""Hermetic doubles for the lifecycle plane: a scripted workload feed
and a lifecycle-event device backend.

Test/CI doubles mirroring the roles FakeProcTree/StragglerBackend play
for the hostcorr plane:

- :class:`ScriptedWorkload` serves a REAL harness-style metrics page —
  the same :class:`~tpumon.workload.stats.WorkloadStats` +
  ``StatsCollector`` + ``ExporterServer`` stack ``python -m
  tpumon.workload.harness --metrics-port`` runs, minus jax — with
  setters to script step rate, phase times, collective-wait fraction,
  SIGTERM flags, and checkpoint spans mid-run. What the lifecycle
  plane's probe parses in tests is byte-for-byte what a live harness
  serves.
- :class:`LifecycleBackend` wraps any device backend and scripts the
  device half of the lifecycle signatures: duty collapse (preemption),
  a shrunken visible chip set (elastic resize → topology
  re-enumeration), while counting every ``sample()`` call — the
  "zero additional device queries per cycle" evidence in
  ``soak.py --preempt``.

Used by tests/test_lifecycle.py and tools/soak.py; never imported by
the exporter itself.
"""

from __future__ import annotations

import time
from collections import Counter


class ScriptedWorkload:
    """One scriptable workload feed (ephemeral port by default;
    ``port`` pins it so a "preempted" feed can return on the same
    address, the way a rescheduled pod keeps its Service endpoint)."""

    def __init__(self, steps_per_second: float = 2.0, port: int = 0) -> None:
        from prometheus_client.registry import CollectorRegistry

        from tpumon.exporter.server import (
            ExporterServer,
            _make_app,
            registry_renderer,
        )
        from tpumon.exporter.telemetry import SelfTelemetry
        from tpumon.workload.stats import StatsCollector, WorkloadStats

        self.stats = WorkloadStats()
        self.stats.configure(
            flops_per_step=1e9, tokens_per_step=1024,
            peak_flops_total=None, axes={"dp": 2, "tp": 2},
        )
        registry = CollectorRegistry()
        registry.register(StatsCollector(self.stats))
        telemetry = SelfTelemetry(registry)
        telemetry.last_poll.set(time.time())
        telemetry.up.set(1)  # same stance as the harness: serving is liveness
        inner = _make_app(
            registry_renderer(registry), telemetry, lambda: (True, "ok\n")
        )
        #: Process-death emulation: server.close() stops the LISTENER,
        #: but a prober's keep-alive connection rides its handler thread
        #: and would keep being served — a "preempted" feed that still
        #: answers. A real SIGKILL drops every connection; the closest
        #: WSGI-level equivalent is refusing with 503 (the probe treats
        #: any non-200 as feed-gone and drops its connection).
        self._dead = False

        def app(environ, start_response):
            if self._dead:
                body = b"workload gone\n"
                start_response(
                    "503 Service Unavailable",
                    [
                        ("Content-Type", "text/plain; charset=utf-8"),
                        ("Content-Length", str(len(body))),
                    ],
                )
                return [body]
            return inner(environ, start_response)

        self.server = ExporterServer(app, "127.0.0.1", port)
        self._steps = 0
        self.set_rate(steps_per_second)

    @property
    def url(self) -> str:
        return self.server.url

    def start(self) -> None:
        self.server.start()

    def close(self) -> None:
        self._dead = True  # live keep-alive connections start refusing
        self.server.close()

    # -- script surface ----------------------------------------------------

    def set_rate(self, steps_per_second: float, loss: float = 2.5) -> None:
        """Publish one window at this rate (step counter advances)."""
        window_steps = max(1, int(steps_per_second))
        self._steps += window_steps
        self.stats.record(
            loss, window_steps, window_steps / max(steps_per_second, 1e-9)
        )

    def set_phases(self, fwd: float, bwd: float, optimizer: float) -> None:
        self.stats.record_phases(
            {"fwd": fwd, "bwd": bwd, "optimizer": optimizer}
        )

    def set_collective_wait(self, fraction: float) -> None:
        self.stats.record_collective_wait(fraction)

    def mark_terminating(self) -> None:
        self.stats.mark_terminating()

    def record_checkpoint(self, op: str, seconds: float) -> None:
        self.stats.record_checkpoint(op, seconds)


class LifecycleBackend:
    """Wraps a device backend; scripts duty collapse and elastic resize
    while counting every device query."""

    def __init__(self, inner) -> None:
        self._inner = inner
        #: True = every chip reports ~0 duty (slice preempted).
        self.duty_zero = False
        #: Pin every chip's duty to this constant (None = the inner
        #: backend's noisy stream) — the "steady preset" shape the
        #: efficiency soak baselines on (a per-cycle-noisy duty would
        #: drown the tokens/J EWMA in model jitter).
        self.duty_constant: float | None = None
        #: Multiply every duty reading (clamped to 100): the efficiency
        #: soak's injection — the same step rate suddenly costs more
        #: duty (and so more modeled watts), tokens/J drops.
        self.duty_scale = 1.0
        #: Visible chip cap (None = all): topology() and per-chip
        #: samples truncate to the first N chips — the elastic-resize
        #: re-enumeration signature.
        self.visible_chips: int | None = None
        #: metric name -> sample() call count (query-budget evidence).
        self.calls: Counter = Counter()

    @property
    def name(self) -> str:
        return self._inner.name

    def topology(self):
        topo = self._inner.topology()
        if self.visible_chips is None or self.visible_chips >= len(topo.chips):
            return topo
        import dataclasses

        # num_chips/num_cores are derived properties of `chips`, so one
        # replace() re-enumerates the whole identity surface.
        return dataclasses.replace(
            topo, chips=topo.chips[: self.visible_chips]
        )

    def sample(self, metric: str):
        from tpumon.backends.base import RawMetric

        self.calls[metric] += 1
        raw = self._inner.sample(metric)
        n = len(raw.data)
        if self.visible_chips is not None and n:
            # Per-chip and per-core vectors truncate with the topology
            # (a real re-enumeration shrinks every surface together);
            # other payload shapes (per-link strings) pass through.
            topo = self._inner.topology()
            full = len(topo.chips)
            if full and self.visible_chips < full:
                if n == full:
                    raw = RawMetric(metric, raw.data[: self.visible_chips])
                elif n == topo.num_cores and full:
                    per_chip = n // full
                    raw = RawMetric(
                        metric, raw.data[: self.visible_chips * per_chip]
                    )
        if metric == "duty_cycle_pct" and raw.data:
            if self.duty_zero:
                return RawMetric(metric, tuple("0.00" for _ in raw.data))
            if self.duty_constant is not None or self.duty_scale != 1.0:
                base = self.duty_constant
                out = []
                for value in raw.data:
                    try:
                        duty = base if base is not None else float(value)
                    except ValueError:
                        out.append(value)  # malformed stays malformed
                        continue
                    out.append(f"{min(100.0, duty * self.duty_scale):.2f}")
                return RawMetric(metric, tuple(out))
        return raw

    def __getattr__(self, attr):
        return getattr(self._inner, attr)
