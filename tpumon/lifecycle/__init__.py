"""Workload-lifecycle robustness plane (ISSUE 10).

Closes the monitor↔trainer loop: the exporter probes the workload
harness's own metrics port (``tpu_step_*`` families —
tpumon/workload/stats.py), classifies preemption / elastic-resize /
checkpoint-restore transitions from the joined step+device+membership
signals, suppresses the false straggler/stall/regression verdicts a
clean transition would otherwise raise (counted, never silent), and
feeds step-time-regression and ICI-contention detectors into the
anomaly engine.
"""

from tpumon.lifecycle.detectors import (
    KINDS,
    LIFECYCLE_DETECTOR_NAMES,
    SUPPRESSIBLE_DETECTORS,
    LifecycleThresholds,
    LifecycleTracker,
    lifecycle_detectors,
)
from tpumon.lifecycle.plane import LifecyclePlane
from tpumon.lifecycle.probe import StepProbe, step_snapshot_from_text

__all__ = [
    "KINDS",
    "LIFECYCLE_DETECTOR_NAMES",
    "LifecyclePlane",
    "LifecycleThresholds",
    "LifecycleTracker",
    "StepProbe",
    "SUPPRESSIBLE_DETECTORS",
    "lifecycle_detectors",
    "step_snapshot_from_text",
]
