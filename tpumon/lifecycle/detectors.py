"""Workload-lifecycle classification and the step-signal detectors.

Three halves:

- :class:`LifecycleTracker` — runs inside the LifecyclePlane's
  poll-cycle pass, joining this cycle's workload step feeds
  (tpumon/lifecycle/probe.py) with the SAME cycle's device snapshot
  into a lifecycle verdict: is a **clean lifecycle transition** —
  slice preemption, elastic resize, checkpoint restore — in progress?
  Signatures (ISSUE 10):

  - *preemption*: a feed flags SIGTERM (``tpu_step_terminating``) or a
    previously-available feed disappears, joined with a duty collapse
    (or runtime detach) within ``window_s``;
  - *resize*: the device chip-set signature changes while the exporter
    stays up (topology re-enumeration — elastic resize, not death);
  - *restore*: a feed's checkpoint-restore span count advances, or a
    lost feed returns reporting a restore.

  A recognized transition opens a **suppression window**
  (``suppress_s``, refreshed by further signals, closed early after
  ``steady_cycles`` clean cycles): detectors whose verdicts are
  *expected* during a clean transition — straggler, stall, duty/HBM
  z-score, step regression — are suppressed by the AnomalyEngine and
  counted (``tpu_anomaly_suppressed_total``) instead of raised. A
  regression that persists PAST the window fires normally: suppression
  delays detection by at most the window, it never blinds it.

- :class:`StepRegressionDetector` / :class:`CollectiveWaitDetector` —
  streaming detectors with the tpumon.anomaly observe() contract,
  consuming the ``lifecycle`` block the plane injects into
  PollStats.snapshot: EWMA z-score on per-feed step duration (the
  trainer got slower), and collective-wait-fraction growth (the fabric
  is contended — two workloads on one pool interfering reads as BOTH
  feeds' wait fraction climbing while duty stays high, which is
  contention, not a straggler).

- :class:`LifecycleEventDetector` — translates the tracker's
  transitions into the engine's onset/clear event stream so lifecycle
  events get /anomalies replay, bounded rings, and history windows.

Thresholds follow the AnomalyThresholds pattern: every field is a
``TPUMON_LIFECYCLE_<FIELD>`` env var, malformed values keep the
default, re-parsed only when the env changes.
"""

from __future__ import annotations

import logging
import math
import os
from dataclasses import dataclass, fields

from tpumon.health import WARN

log = logging.getLogger(__name__)

#: Lifecycle transition kinds, in exposition order.
KINDS = ("preemption", "resize", "restore")

#: Detectors whose verdicts a clean lifecycle transition suppresses.
#: The ``lifecycle`` detector itself is never suppressed (it IS the
#: transition), and absence-aging still clears events normally.
SUPPRESSIBLE_DETECTORS = (
    "duty_ewma", "hbm_ewma", "ici_flap", "bw_cusum", "queue_stall",
    "host_straggler", "host_stall", "step_regression", "collective_wait",
    "efficiency_regression",
)


@dataclass(frozen=True)
class LifecycleThresholds:
    """Classifier/detector tuning, overridable via TPUMON_LIFECYCLE_*."""

    #: Seconds two signature halves (SIGTERM/feed-loss and duty
    #: collapse) may be apart and still join into one preemption.
    window_s: float = 30.0
    #: Suppression window opened by a recognized transition; refreshed
    #: by further lifecycle signals.
    suppress_s: float = 60.0
    #: Consecutive signal-free cycles that close the window early.
    steady_cycles: float = 10.0
    #: Consecutive unavailable cycles before a feed counts as lost
    #: (one failed probe is routinely a blip, not a preemption).
    lost_cycles: float = 3.0
    #: Mean duty at/below this reads as a duty collapse.
    duty_collapse_pct: float = 5.0
    #: Step-regression EWMA: samples before arming, onset/clear z, and
    #: the relative std floor (fraction of the baseline mean) so a
    #: near-constant step time can't make z explode on jitter.
    step_warmup: float = 10.0
    step_z_warn: float = 4.0
    step_z_clear: float = 2.0
    step_min_rel_std: float = 0.05
    #: Collective-wait growth: samples before arming, absolute onset
    #: floor, and the growth-over-baseline that onsets below it.
    wait_warmup: float = 10.0
    wait_abs_warn: float = 0.4
    wait_growth: float = 0.15

    @classmethod
    def from_env(cls, environ=None) -> "LifecycleThresholds":
        env = os.environ if environ is None else environ
        kwargs = {}
        for f in fields(cls):
            raw = env.get("TPUMON_LIFECYCLE_" + f.name.upper())
            if raw is None:
                continue
            try:
                kwargs[f.name] = float(raw)
            except ValueError:
                log.warning(
                    "ignoring malformed TPUMON_LIFECYCLE_%s=%r",
                    f.name.upper(), raw,
                )
        return cls(**kwargs)


#: (env-values key, parsed thresholds) — re-parse only when the env
#: changed, same cache shape as anomaly/hostcorr env_thresholds.
_env_cache: tuple | None = None


def env_thresholds() -> LifecycleThresholds:
    global _env_cache
    key = tuple(
        os.environ.get("TPUMON_LIFECYCLE_" + f.name.upper())
        for f in fields(LifecycleThresholds)
    )
    if _env_cache is None or _env_cache[0] != key:
        _env_cache = (key, LifecycleThresholds.from_env())
    return _env_cache[1]


class LifecycleTracker:
    """Per-cycle lifecycle classification; poller thread only.

    ``update(now, feeds, snap, t)`` returns this cycle's lifecycle
    block (also the plane's injection payload): transition state, the
    suppression list, newly-onset event kinds, and the joined step
    telemetry the step detectors consume.
    """

    def __init__(self) -> None:
        #: Last seen device chip-id signature (frozenset; None before
        #: the first non-empty enumeration).
        self._chip_sig: frozenset | None = None
        #: url -> consecutive unavailable cycles (feeds that were once
        #: available only).
        self._gone_cycles: dict[str, int] = {}
        #: url -> last seen restore-span count.
        self._restores: dict[str, float] = {}
        #: url -> feed was lost (for return-detection).
        self._lost: set[str] = set()
        #: Pending signature halves: kind -> ts of the half-signal.
        self._pending_preempt_ts: float | None = None
        self._pending_collapse_ts: float | None = None
        #: Suppression window state.
        self._suppress_until = 0.0
        self._steady_streak = 0
        #: Last recognized EVENT (not mere signal): ongoing signals may
        #: refresh the window only within a bounded horizon of it, so a
        #: permanently-idle node (duty 0 forever after its slice left)
        #: cannot hold suppression open indefinitely.
        self._last_event_ts = 0.0
        #: Kinds already counted inside the current window (dedup).
        self._latched: set[str] = set()

    @property
    def transition_active(self) -> bool:
        return self._suppress_until > 0.0

    def update(self, now: float, feeds: list[dict], snap: dict,
               t: LifecycleThresholds) -> dict:
        """One cycle. ``feeds``: [{url, available, was_available,
        snapshot}, ...]; ``snap``: this cycle's parsed device snapshot
        (tpumon.smi shape)."""
        new_events: list[str] = []
        signals: list[str] = []

        # -- device-side signals ------------------------------------------
        chips = snap.get("chips") or {}
        duties = [
            row.get("duty_pct") for row in chips.values()
            if row.get("duty_pct") is not None
        ]
        mean_duty = sum(duties) / len(duties) if duties else None
        collapse = mean_duty is not None and mean_duty <= t.duty_collapse_pct
        sig = frozenset(chips)
        detached = self._chip_sig is not None and self._chip_sig and not sig
        resized = (
            self._chip_sig is not None
            and bool(self._chip_sig)
            and bool(sig)
            and sig != self._chip_sig
        )
        if sig or self._chip_sig is None:
            # Empty enumerations don't overwrite the remembered shape:
            # a detach-then-return must compare against the pre-detach
            # signature, or every recovery would read as a resize.
            if resized:
                signals.append("membership")
            self._chip_sig = sig if sig else self._chip_sig
        if collapse or detached:
            self._pending_collapse_ts = now
            signals.append("collapse" if collapse else "detach")

        # -- workload-side signals ----------------------------------------
        terminating = False
        lost = False
        restored = False
        returned = False
        for feed in feeds:
            url = feed["url"]
            fsnap = feed.get("snapshot") or {}
            if feed.get("available"):
                if url in self._lost:
                    self._lost.discard(url)
                    returned = True
                self._gone_cycles[url] = 0
                if fsnap.get("terminating"):
                    terminating = True
                restore_count = (
                    (fsnap.get("checkpoints") or {})
                    .get("restore", {})
                    .get("count")
                )
                if restore_count is not None:
                    seen = self._restores.get(url, 0.0)
                    if restore_count > seen:
                        restored = True
                    self._restores[url] = restore_count
            elif feed.get("was_available"):
                n = self._gone_cycles.get(url, 0) + 1
                self._gone_cycles[url] = n
                if n == int(max(1, t.lost_cycles)):
                    lost = True
                    self._lost.add(url)
                    # A lost feed's process is (about to be) gone; the
                    # replacement restarts its restore counter from
                    # scratch. Forget the high-water mark, or a
                    # rescheduled pod's restore (count 1 again) would
                    # never read as new and the restore storm it is
                    # part of would go unclassified.
                    self._restores.pop(url, None)
        if terminating:
            signals.append("terminating")
            self._pending_preempt_ts = now
        if lost:
            signals.append("feed_lost")
            self._pending_preempt_ts = now
        if restored:
            signals.append("restore_span")
        if returned:
            signals.append("feed_returned")

        # -- classification -----------------------------------------------
        def onset(kind: str) -> None:
            if kind not in self._latched:
                self._latched.add(kind)
                new_events.append(kind)
            self._last_event_ts = now
            self._suppress_until = max(
                self._suppress_until, now + t.suppress_s
            )

        if (
            self._pending_preempt_ts is not None
            and self._pending_collapse_ts is not None
            and abs(self._pending_preempt_ts - self._pending_collapse_ts)
            <= t.window_s
        ):
            onset("preemption")
            self._pending_preempt_ts = None
            self._pending_collapse_ts = None
        if resized:
            onset("resize")
        if restored:
            # Only a restore SPAN reads as a restore; a plain feed
            # return (probe blip, rescheduled pod that did not restore)
            # does not.
            onset("restore")
        # Expire stale half-signals so a SIGTERM today can't pair with
        # a duty collapse an hour later.
        for attr in ("_pending_preempt_ts", "_pending_collapse_ts"):
            ts = getattr(self, attr)
            if ts is not None and now - ts > t.window_s:
                setattr(self, attr, None)

        # -- window upkeep -------------------------------------------------
        if self._suppress_until > 0.0:
            if signals:
                self._steady_streak = 0
                # Ongoing lifecycle signals (duty still collapsed, the
                # feed still flagging SIGTERM) REFRESH the window — a
                # 20 s preempted phase must not lapse between the
                # preemption event and the restore — but only within a
                # bounded horizon of the last recognized event, so a
                # node that stays idle forever eventually returns to
                # normal detection (an idle node's wedged runtime is
                # still queue_stall's to find).
                if now - self._last_event_ts <= 4.0 * t.suppress_s:
                    self._suppress_until = max(
                        self._suppress_until, now + t.suppress_s
                    )
            else:
                self._steady_streak += 1
                if self._steady_streak >= int(max(1, t.steady_cycles)):
                    # Early close: the transition finished and the node
                    # has been quiet — stop deferring real detection.
                    self._suppress_until = 0.0
            if now >= self._suppress_until:
                self._suppress_until = 0.0
            if self._suppress_until == 0.0:
                self._latched.clear()
                self._steady_streak = 0

        active = self._suppress_until > 0.0
        block: dict = {
            "transition": active,
            "kinds": sorted(self._latched) if active else [],
            "new_events": new_events,
            "signals": signals,
            "suppress": list(SUPPRESSIBLE_DETECTORS) if active else [],
            "suppress_until": self._suppress_until if active else None,
            "mean_duty_pct": mean_duty,
        }
        return block


class StepRegressionDetector:
    """EWMA z-score on per-feed step duration: the trainer got slower.

    The baseline freezes while anomalous (a regression that *stays*
    regressed keeps its event active) and RESETS on a lifecycle
    transition — after an elastic resize the mesh changed, so the old
    step-time baseline is not evidence about the new one; the detector
    re-warms on post-transition data and genuine post-event regressions
    still fire, just ``step_warmup`` cycles later.
    """

    name = "step_regression"
    _family = "tpu_lifecycle_step_duration_seconds"

    def __init__(self) -> None:
        #: feed url -> (_Ewma-style mean/var/n) on step seconds.
        self._state: dict[str, list] = {}  # url -> [mean, var, n]
        self._active: set[str] = set()

    def _reset(self) -> None:
        self._state.clear()
        self._active.clear()

    def observe(self, ts: float, snap: dict, t) -> list:
        from tpumon.anomaly.detectors import Reading

        lc = snap.get("lifecycle") or {}
        lt = env_thresholds()
        if lc.get("transition"):
            # The transition is the explanation; re-baseline after it.
            self._reset()
            return []
        out: list[Reading] = []
        feeds = lc.get("feeds") or {}
        for url in sorted(feeds):
            step_s = (feeds[url] or {}).get("step_seconds")
            if step_s is None or step_s <= 0:
                continue
            mean, var, n = self._state.setdefault(url, [0.0, 0.0, 0])
            alpha = 0.1
            if n >= lt.step_warmup:
                std = max(
                    math.sqrt(max(var, 0.0)),
                    lt.step_min_rel_std * max(mean, 1e-9),
                )
                z = (step_s - mean) / std
                was = url in self._active
                # One-sided: only SLOWER is a regression (faster steps
                # re-baseline silently — nobody pages on a speedup).
                active = z >= (lt.step_z_clear if was else lt.step_z_warn)
                if active or was:
                    out.append(
                        Reading(
                            f"feed:{url}",
                            active,
                            WARN,
                            step_s,
                            f"workload step time {step_s * 1e3:.0f} ms is "
                            f"{z:.1f}σ above its {mean * 1e3:.0f} ms "
                            "baseline — step-time regression",
                            self._family,
                            (),
                        )
                    )
                if active:
                    self._active.add(url)
                    continue  # freeze baseline while anomalous
                self._active.discard(url)
            # EWMA update (unfrozen path).
            if n == 0:
                self._state[url] = [step_s, 0.0, 1]
            else:
                d = step_s - mean
                mean += alpha * d
                var = (1.0 - alpha) * (var + alpha * d * d)
                self._state[url] = [mean, var, n + 1]
        return out


class CollectiveWaitDetector:
    """Collective-wait-fraction growth: ICI contention, not a straggler.

    Two workloads on one pool interfering shows as BOTH feeds' wait
    fraction climbing while duty stays high and no chip lags the slice
    median — the attribution the straggler plane cannot make alone.
    """

    name = "collective_wait"
    _family = "tpu_lifecycle_collective_wait_fraction"

    def __init__(self) -> None:
        self._state: dict[str, list] = {}  # url -> [mean, n]
        self._active: set[str] = set()

    def observe(self, ts: float, snap: dict, t) -> list:
        from tpumon.anomaly.detectors import Reading

        lc = snap.get("lifecycle") or {}
        lt = env_thresholds()
        if lc.get("transition"):
            self._state.clear()
            self._active.clear()
            return []
        out: list[Reading] = []
        feeds = lc.get("feeds") or {}
        for url in sorted(feeds):
            frac = (feeds[url] or {}).get("collective_wait_fraction")
            if frac is None:
                continue
            mean, n = self._state.setdefault(url, [0.0, 0])
            if n >= lt.wait_warmup:
                threshold = min(lt.wait_abs_warn, mean + lt.wait_growth)
                was = url in self._active
                active = frac >= (threshold / 2.0 if was else threshold)
                if active or was:
                    out.append(
                        Reading(
                            f"feed:{url}",
                            active,
                            WARN,
                            frac,
                            f"collective-wait fraction {frac:.0%} (baseline "
                            f"{mean:.0%}) — ICI contention: the fabric is "
                            "contended, the chips are busy; interference, "
                            "not a straggler",
                            self._family,
                            (),
                        )
                    )
                if active:
                    self._active.add(url)
                    continue  # freeze baseline while contended
                self._active.discard(url)
            self._state[url] = [mean + 0.1 * (frac - mean), n + 1]
        return out


class LifecycleEventDetector:
    """Engine adapter over the tracker's transitions: one event per
    suppression window, message naming the recognized kinds — so
    preemption/resize/restore get /anomalies replay and rings."""

    name = "lifecycle"
    _family = "tpu_lifecycle_state"

    def __init__(self) -> None:
        self._active = False

    def observe(self, ts: float, snap: dict, t) -> list:
        from tpumon.anomaly.detectors import Reading

        lc = snap.get("lifecycle") or {}
        active = bool(lc.get("transition"))
        was = self._active
        self._active = active
        if not active and not was:
            return []
        kinds = lc.get("kinds") or []
        return [
            Reading(
                "node",
                active,
                WARN,
                float(len(kinds)),
                "workload lifecycle transition "
                f"({'/'.join(kinds) if kinds else 'signals pending'}) — "
                "straggler/stall/regression verdicts suppressed while "
                "the window holds",
                self._family,
                (),
            )
        ]


def lifecycle_detectors() -> list:
    """The step/lifecycle detector roster appended to the anomaly
    engine when the lifecycle plane is enabled."""
    return [
        StepRegressionDetector(),
        CollectiveWaitDetector(),
        LifecycleEventDetector(),
    ]


LIFECYCLE_DETECTOR_NAMES: tuple[str, ...] = (
    "step_regression", "collective_wait", "lifecycle",
)


__all__ = [
    "KINDS",
    "LIFECYCLE_DETECTOR_NAMES",
    "LifecycleEventDetector",
    "LifecycleThresholds",
    "LifecycleTracker",
    "CollectiveWaitDetector",
    "StepRegressionDetector",
    "SUPPRESSIBLE_DETECTORS",
    "env_thresholds",
    "lifecycle_detectors",
]
