"""The workload-lifecycle robustness plane wired into the poll loop.

One :meth:`LifecyclePlane.cycle` call per poll, fed the PollStats the
collector already computed. The pass:

1. probes the configured workload step feeds (bounded localhost HTTP,
   tpumon/lifecycle/probe.py — **zero device queries**, preserving the
   collector's scrape-latency design rule);
2. joins them with the SAME cycle's device snapshot through the
   :class:`~tpumon.lifecycle.detectors.LifecycleTracker`: is a clean
   preemption / elastic resize / checkpoint restore in progress?
3. appends one time-aligned record to the bounded lifecycle ring
   (served as ``GET /lifecycle``, ``?since=`` replay like /hostcorr);
4. injects a ``lifecycle`` block into ``PollStats.snapshot`` so the
   anomaly engine sees the suppression list and the step detectors
   (step_regression, collective_wait) see the per-feed step telemetry;
5. returns the ``tpu_lifecycle_*`` families for this cycle's page
   (names/help/labels from the LIFECYCLE_FAMILIES registry, so docs
   and dashboards cannot drift).

Graceful degradation: with no feeds configured the plane still tracks
device-side lifecycle signatures (resize via topology re-enumeration);
an unreachable feed is the NORMAL no-workload state, never an error.
"""

from __future__ import annotations

import logging
import threading
from collections import Counter, deque

from prometheus_client.core import CounterMetricFamily, GaugeMetricFamily

from tpumon.lifecycle.detectors import LifecycleTracker, env_thresholds
from tpumon.lifecycle.probe import StepProbe, parse_step_urls

log = logging.getLogger(__name__)


class LifecyclePlane:
    """Thread model: ``cycle`` runs on the poller thread only;
    ``replay``/``snapshot``/``resize`` may be called from HTTP threads —
    shared state (ring, last record, event totals) is guarded by one
    lock held for deque/dict work only."""

    def __init__(
        self,
        step_urls: str = "",
        ring: int = 600,
        probes: list | None = None,
        probe_timeout: float = 1.0,
    ) -> None:
        self._probes = (
            probes
            if probes is not None
            else [
                StepProbe(url, timeout=probe_timeout)
                for url in parse_step_urls(step_urls)
            ]
        )
        self._tracker = LifecycleTracker()
        self._full_ring = max(1, int(ring))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._full_ring)  # guarded-by: self._lock
        self._last: dict | None = None  # guarded-by: self._lock
        self._totals: Counter = Counter()  # guarded-by: self._lock
        self._cycles = 0  # guarded-by: self._lock

    @property
    def ring_capacity(self) -> int:
        return self._full_ring

    @property
    def probes(self) -> list:
        return self._probes

    def resize(self, n: int) -> None:
        """Re-cap the lifecycle ring in place — the memory-watermark
        response (tpumon/guard/memwatch); newest records retained,
        reversible."""
        n = max(1, int(n))
        with self._lock:
            if n == self._ring.maxlen:
                return
            self._ring = deque(self._ring, maxlen=n)

    def close(self) -> None:
        for probe in self._probes:
            probe.close()

    # -- poll-loop integration --------------------------------------------

    def cycle(self, now: float, stats) -> list:
        """One Poller cycle: probe, classify, record, inject, emit."""
        t = env_thresholds()
        feeds: list[dict] = []
        feed_snaps: dict[str, dict] = {}
        available = 0
        for probe in self._probes:
            ok, snap = probe.sample()
            if ok:
                available += 1
                feed_snaps[probe.url] = snap
            feeds.append(
                {
                    "url": probe.url,
                    "available": ok,
                    "was_available": probe.was_available,
                    "snapshot": snap,
                }
            )
        device_snap = stats.snapshot if stats.snapshot is not None else {}
        block = self._tracker.update(now, feeds, device_snap, t)
        block["feeds"] = feed_snaps
        block["available"] = available
        block["configured"] = len(self._probes)

        # Joined step telemetry across available feeds: mean step rate
        # (hosts in one dp job all report the job's rate — a mean, not a
        # sum, is the honest merge), worst collective wait. Injected
        # into the block as THE canonical join — downstream consumers
        # (the energy plane's efficiency math) read these instead of
        # re-deriving their own merge that could silently diverge.
        def _mean(key: str) -> float | None:
            vals = [
                s.get(key)
                for s in feed_snaps.values()
                if s.get(key) is not None
            ]
            return sum(vals) / len(vals) if vals else None

        waits = [
            s.get("collective_wait_fraction")
            for s in feed_snaps.values()
            if s.get("collective_wait_fraction") is not None
        ]
        step_rate = _mean("steps_per_second")
        step_seconds = _mean("step_seconds")
        tokens_per_second = _mean("tokens_per_second")
        worst_wait = max(waits) if waits else None
        block["step_rate"] = step_rate
        block["step_seconds"] = step_seconds
        block["tokens_per_second"] = tokens_per_second

        # Serving-side join (inference preset, tpumon/workload/serve.py):
        # replicas serve independent request streams, so throughput and
        # queue depth SUM across feeds; TTFT takes the worst feed (the
        # SLO-relevant tail) and SLO attainment / batch size are means.
        def _sum(key: str) -> float | None:
            vals = [
                s.get(key)
                for s in feed_snaps.values()
                if s.get(key) is not None
            ]
            return sum(vals) if vals else None

        def _worst(key: str) -> float | None:
            vals = [
                s.get(key)
                for s in feed_snaps.values()
                if s.get(key) is not None
            ]
            return max(vals) if vals else None

        serve = {
            "requests_per_second": _sum("serve_requests_per_second"),
            "queue_depth": _sum("serve_queue_depth"),
            "ttft_seconds": _worst("serve_ttft_seconds"),
            "slo_attainment_ratio": _mean("serve_slo_attainment_ratio"),
            "batch_size": _mean("serve_batch_size"),
        }
        block["serve"] = serve

        record = {
            "ts": now,
            "transition": block["transition"],
            "kinds": list(block["kinds"]),
            "signals": list(block["signals"]),
            "new_events": list(block["new_events"]),
            "workloads": {"configured": len(self._probes), "available": available},
            "step_rate": step_rate,
            "step_seconds": step_seconds,
            "collective_wait_fraction": worst_wait,
            "mean_duty_pct": block.get("mean_duty_pct"),
        }
        with self._lock:
            self._cycles += 1
            for kind in block["new_events"]:
                self._totals[kind] += 1
            self._ring.append(record)
            self._last = record
            totals = dict(self._totals)

        if stats.snapshot is not None:
            # The anomaly engine reads this block from the snapshot it
            # is fed anyway — the suppression list and the step
            # detectors' inputs travel on the same bus, no side channel.
            stats.snapshot["lifecycle"] = block
        return self._families(
            stats.base_keys, stats.base_vals, block,
            step_rate, step_seconds, worst_wait, totals, available,
        )

    # -- exposition --------------------------------------------------------

    def _families(
        self, base_keys, base_vals, block,
        step_rate, step_seconds, worst_wait, totals, available,
    ) -> list:
        from tpumon.families import LIFECYCLE_FAMILIES

        labels = tuple(base_keys)
        vals = tuple(base_vals)

        def fam(name, cls):
            _, help_text, extra = LIFECYCLE_FAMILIES[name]
            return cls(name, help_text, labels=labels + extra)

        workloads = fam("tpu_lifecycle_workloads", GaugeMetricFamily)
        workloads.add_metric(vals + ("available",), float(available))
        workloads.add_metric(
            vals + ("absent",), float(len(self._probes) - available)
        )
        state = fam("tpu_lifecycle_state", GaugeMetricFamily)
        state.add_metric(vals, 1.0 if block["transition"] else 0.0)
        out = [workloads, state]

        if totals:
            events = fam("tpu_lifecycle_events_total", CounterMetricFamily)
            for kind in sorted(totals):
                events.add_metric(vals + (kind,), float(totals[kind]))
            out.append(events)
        if step_rate is not None:
            rate = fam("tpu_lifecycle_step_rate", GaugeMetricFamily)
            rate.add_metric(vals, step_rate)
            out.append(rate)
        if step_seconds is not None:
            dur = fam(
                "tpu_lifecycle_step_duration_seconds", GaugeMetricFamily
            )
            dur.add_metric(vals, step_seconds)
            out.append(dur)
        if worst_wait is not None:
            wait = fam(
                "tpu_lifecycle_collective_wait_fraction", GaugeMetricFamily
            )
            wait.add_metric(vals, worst_wait)
            out.append(wait)
        # Checkpoint spans summed over the probed feeds — the fleet
        # tier's goodput ledger (tpumon/ledger) reads this off the node
        # page to charge checkpoint windows to the right bucket; a feed
        # process restart resets its share (ordinary counter-reset
        # semantics downstream).
        ckpt_totals: dict[str, float] = {}
        for snap in block.get("feeds", {}).values():
            for op, row in (snap.get("checkpoints") or {}).items():
                count = row.get("count")
                if count is not None:
                    ckpt_totals[op] = ckpt_totals.get(op, 0.0) + count
        if ckpt_totals:
            ckpts = fam(
                "tpu_lifecycle_checkpoints_total", CounterMetricFamily
            )
            for op in sorted(ckpt_totals):
                ckpts.add_metric(vals + (op,), ckpt_totals[op])
            out.append(ckpts)
        # Serving join (inference preset): absent unless at least one
        # probed feed reports the serve_* side — the fleet actuation
        # tier (tpumon/actuate) rolls these up per slice.
        for key, name in (
            ("requests_per_second", "tpu_lifecycle_serve_requests_per_second"),
            ("queue_depth", "tpu_lifecycle_serve_queue_depth"),
            ("ttft_seconds", "tpu_lifecycle_serve_ttft_seconds"),
            (
                "slo_attainment_ratio",
                "tpu_lifecycle_serve_slo_attainment_ratio",
            ),
            ("batch_size", "tpu_lifecycle_serve_batch_size"),
        ):
            value = block.get("serve", {}).get(key)
            if value is not None:
                g = fam(name, GaugeMetricFamily)
                g.add_metric(vals, value)
                out.append(g)
        return out

    # -- query surfaces ----------------------------------------------------

    def replay(self, since: float = 0.0) -> tuple[dict, list]:
        """(/lifecycle envelope, records at/after ``since``) — the
        server bounds the record list and stamps continuation tokens."""
        with self._lock:
            records = [r for r in self._ring if r["ts"] >= since]
            last = self._last
            totals = dict(self._totals)
            cycles = self._cycles
            capacity = self._ring.maxlen
        doc = {
            "cycles": cycles,
            "ring_capacity": capacity,
            "workloads": dict(last["workloads"]) if last else {
                "configured": len(self._probes), "available": 0
            },
            "transition": bool(last and last["transition"]),
            "kinds": list(last["kinds"]) if last else [],
            "events_total": totals,
        }
        return doc, records

    def snapshot(self) -> dict:
        """The /debug/vars "lifecycle" block: O(1) occupancy + state."""
        with self._lock:
            return {
                "cycles": self._cycles,
                "records": len(self._ring),
                "ring_capacity": self._ring.maxlen,
                "workloads": (
                    dict(self._last["workloads"]) if self._last else {
                        "configured": len(self._probes), "available": 0
                    }
                ),
                "transition": bool(self._last and self._last["transition"]),
                "kinds": list(self._last["kinds"]) if self._last else [],
                "events_total": dict(self._totals),
                "probes": [
                    {
                        "url": p.url,
                        "available": p.available,
                        "error": p.last_error or None,
                    }
                    for p in self._probes
                ],
            }
