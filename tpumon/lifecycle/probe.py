"""Workload step-feed probing: the monitor watches the trainer it ships.

One :class:`StepProbe` per configured workload metrics URL
(``TPUMON_LIFECYCLE_STEP_URLS``, CSV — typically the harness's
``--metrics-port`` on localhost). The probe runs once per poll cycle on
the poller thread: a bounded keep-alive HTTP GET plus a targeted line
parse — **zero device queries**, same budget rule as tpumon/hostcorr.
A workload that isn't running is the NORMAL state, not an error: the
feed reads ``available=False`` and every step-derived family goes
absent (absent-not-zero).

The parser is the fleet tier's targeted-line-scan idiom
(tpumon/fleet/ingest.py node_snapshot_from_text): the lifecycle plane
wants ~10 families off a page whose bulk is collective-op counters, so
scanning lines beats a general exposition parse by the same two orders
of magnitude measured there.
"""

from __future__ import annotations

import http.client
import logging
import re
import urllib.error

log = logging.getLogger(__name__)

#: Everything a workload page fetch can throw (the fleet ingest set).
PROBE_ERRORS: tuple[type[BaseException], ...] = (
    urllib.error.URLError,
    OSError,
    http.client.HTTPException,
    ValueError,
)

#: Workload pages are small (a few KB of counters); a page past this is
#: not a harness.
MAX_PAGE_BYTES = 1 << 20

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')

#: Bare-value families lifted into the snapshot, name -> snapshot key.
_SCALARS = {
    "tpu_step_counter": "step",
    "tpu_step_duration_seconds": "step_seconds",
    "tpu_step_collective_wait_fraction": "collective_wait_fraction",
    "tpu_step_terminating": "terminating",
    "workload_steps_per_second": "steps_per_second",
    "workload_tokens_per_second": "tokens_per_second",
    "workload_steps_total": "steps_total",
    "workload_loss": "loss",
    "workload_mfu_ratio": "mfu",
    # Serving-preset families (tpumon/workload/serve.py): lifted under
    # serve_* keys so the plane can join them per feed and the fleet
    # actuation tier can roll them up per slice.
    "tpu_serve_requests_per_second": "serve_requests_per_second",
    "tpu_serve_queue_depth": "serve_queue_depth",
    "tpu_serve_ttft_seconds": "serve_ttft_seconds",
    "tpu_serve_slo_attainment_ratio": "serve_slo_attainment_ratio",
    "tpu_serve_batch_size": "serve_batch_size",
}


def step_snapshot_from_text(text: str) -> dict:
    """Parse one workload /metrics page into the lifecycle plane's step
    snapshot. Keys absent when the page doesn't carry them."""
    snap: dict = {}
    phases: dict[str, float] = {}
    checkpoints: dict[str, dict[str, float]] = {}
    for line in text.splitlines():
        if not line or line[0] == "#":
            continue
        brace = line.find("{")
        space = line.find(" ") if brace < 0 else -1
        name = line[:brace] if brace >= 0 else line[:space]
        if name in _SCALARS:
            try:
                snap[_SCALARS[name]] = float(line.rsplit(" ", 1)[1])
            except ValueError:
                continue
        elif name == "tpu_step_phase_seconds":
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            try:
                phases[labels.get("phase", "?")] = float(
                    line.rsplit(" ", 1)[1]
                )
            except ValueError:
                continue
        elif name in ("tpu_step_checkpoint_seconds",
                      "tpu_step_checkpoints_total"):
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            op = labels.get("op", "?")
            try:
                value = float(line.rsplit(" ", 1)[1])
            except ValueError:
                continue
            row = checkpoints.setdefault(op, {})
            if name == "tpu_step_checkpoint_seconds":
                row["last_s"] = value
            else:
                row["count"] = value
        elif name == "workload_mesh_info":
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            axes = {}
            for axis in ("dp", "tp", "sp", "pp", "ep"):
                try:
                    axes[axis] = int(labels.get(axis, "1"))
                except ValueError:
                    axes[axis] = 1
            snap["axes"] = axes
    if "terminating" in snap:
        snap["terminating"] = snap["terminating"] > 0
    if phases:
        snap["phases"] = phases
    if checkpoints:
        snap["checkpoints"] = checkpoints
    return snap


class StepProbe:
    """One workload feed's probe state; poller thread only.

    ``sample()`` returns ``(available, snapshot)``: available means the
    fetch succeeded AND the page parsed as a workload page (it carries
    at least one step/workload family). Consecutive failures after a
    period of availability are the feed-loss signal the preemption
    classifier consumes — surfaced as ``was_available``.
    """

    def __init__(self, url: str, timeout: float = 1.0) -> None:
        self.url = url.strip().rstrip("/")
        if not self.url.startswith(("http://", "https://")):
            self.url = "http://" + self.url
        self._tls = self.url.startswith("https://")
        #: host[:port] only — a URL carrying a path must not poison the
        #: connection's host string.
        self._host = self.url.split("//", 1)[1].split("/", 1)[0]
        self.timeout = timeout
        self.available = False
        #: True once this feed has EVER answered — distinguishes "no
        #: workload scheduled here yet" from "the workload went away".
        self.was_available = False
        self.snapshot: dict = {}
        self.last_error = ""
        #: Persistent connection; probe() is poller-thread-only.
        self._conn: http.client.HTTPConnection | None = None

    def _fetch(self) -> str:
        if self._conn is None:
            conn_cls = (
                http.client.HTTPSConnection
                if self._tls
                else http.client.HTTPConnection
            )
            self._conn = conn_cls(self._host, timeout=self.timeout)
        try:
            self._conn.request("GET", "/metrics")
            resp = self._conn.getresponse()
            body = resp.read(MAX_PAGE_BYTES + 1)
            if resp.status != 200:
                raise http.client.HTTPException(f"status {resp.status}")
            if len(body) > MAX_PAGE_BYTES:
                raise ValueError("workload page exceeds size cap")
            return body.decode()
        except BaseException:
            # Whatever happened, the connection's framing is suspect.
            try:
                self._conn.close()
            finally:
                self._conn = None
            raise

    def sample(self) -> tuple[bool, dict]:
        try:
            text = self._fetch()
        except PROBE_ERRORS as exc:
            self.available = False
            self.last_error = str(exc)[:200]
            return False, self.snapshot
        snap = step_snapshot_from_text(text)
        if not snap:
            # Something answered on the port but it isn't a workload
            # page — treat as absent, keep the last real snapshot.
            self.available = False
            self.last_error = "no step families on page"
            return False, self.snapshot
        self.available = True
        self.was_available = True
        self.snapshot = snap
        self.last_error = ""
        return True, snap

    def close(self) -> None:
        conn = self._conn
        if conn is not None:
            self._conn = None
            conn.close()


def parse_step_urls(raw: str) -> list[str]:
    """``TPUMON_LIFECYCLE_STEP_URLS`` CSV -> cleaned URL list."""
    if not raw or not raw.strip():
        return []
    return [p.strip() for p in raw.split(",") if p.strip()]


__all__ = [
    "MAX_PAGE_BYTES",
    "PROBE_ERRORS",
    "StepProbe",
    "parse_step_urls",
    "step_snapshot_from_text",
]
