"""Structured JSON logging, trace-id correlated (TPUMON_LOG_FORMAT=json).

One JSON object per line on the standard logging stream: machine
-parseable (jq / log pipelines), and every record emitted while a poll
cycle is open on the logging thread carries that cycle's ``trace_id`` —
so a "history record failed" log line pins to the exact span tree in
``/debug/traces`` instead of "sometime around then".
"""

from __future__ import annotations

import json
import logging

from tpumon.trace.tracer import current_trace_id


class JsonLogFormatter(logging.Formatter):
    """Line-per-record JSON; opt-in via TPUMON_LOG_FORMAT=json."""

    def format(self, record: logging.LogRecord) -> str:
        doc: dict = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        trace_id = current_trace_id()
        if trace_id is not None:
            doc["trace_id"] = trace_id
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc, ensure_ascii=False)
