"""Internal trace plane for the exporter itself (ISSUE 2).

The rest of tpumon observes the accelerator; this package observes the
monitor. A dependency-free span tracer wraps every stage of the poll
pipeline in nested spans (per-cycle trace id, monotonic start/duration),
keeps completed cycles in a bounded ring, and promotes cycles that
overran the configured budget to a slow-cycle flight-recorder ring that
retains the full span tree plus the poll's ``PollStats`` — so "the
exporter is slow" becomes "stage X ate the budget in cycle Y" without a
redeploy.

Design rule inherited from the scrape-latency headline: **nothing here
touches the scrape path**. Spans are recorded on the poll thread (and
the gRPC serving threads for their own RPCs); traces render to JSON
lazily, on ``/debug/traces`` reads only.
"""

from tpumon.trace.logfmt import JsonLogFormatter
from tpumon.trace.tracer import (
    CycleTrace,
    Span,
    Tracer,
    current_trace_id,
    trace_span,
)

__all__ = [
    "CycleTrace",
    "JsonLogFormatter",
    "Span",
    "Tracer",
    "current_trace_id",
    "trace_span",
]
