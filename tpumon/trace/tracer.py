"""Span tracer for the exporter's own pipeline — stdlib only.

Thread model: a :class:`Tracer` is shared, but a *cycle* is thread-local.
``Tracer.cycle()`` installs the tracer as the thread's ambient tracer;
every :func:`trace_span` (or ``Tracer.span``) entered on that thread
while the cycle is open nests into the current span — so code deep in
the pipeline (``build_families`` internals, the gRPC backend's RPCs)
traces itself without any plumbing, and the same code is a no-op on
threads with no open cycle. ``Tracer.span`` called directly with no open
cycle (the exporter's gRPC serving handlers) still feeds the per-stage
duration metric, just without a tree to nest into.

Completed cycles are appended to a bounded ring under a lock; after
``_finish`` a :class:`CycleTrace` is immutable, so ``/debug`` readers
render it to JSON outside the lock.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

#: Budget above which a cycle is promoted to the slow ring (ms).
DEFAULT_SLOW_CYCLE_MS = 250.0
#: Completed-cycle ring capacity (/debug/traces).
DEFAULT_RING = 128
#: Slow-cycle flight-recorder ring capacity (/debug/traces/slow).
DEFAULT_SLOW_RING = 32

_tls = threading.local()


def current_trace_id() -> str | None:
    """Trace id of the cycle open on this thread (log correlation)."""
    return getattr(_tls, "trace_id", None)


@dataclass
class Span:
    """One timed stage; ``start`` is seconds since its cycle began."""

    name: str
    start: float = 0.0
    duration: float = 0.0
    status: str = "ok"
    detail: str = ""
    children: list["Span"] = field(default_factory=list)

    def to_dict(self) -> dict:
        doc: dict = {
            "name": self.name,
            "start_seconds": round(self.start, 6),
            "duration_seconds": round(self.duration, 6),
            "status": self.status,
        }
        if self.detail:
            doc["detail"] = self.detail
        if self.children:
            doc["spans"] = [c.to_dict() for c in self.children]
        return doc


@dataclass
class CycleTrace:
    """One poll cycle's span tree plus its identity and verdict."""

    trace_id: str
    seq: int
    start_ts: float  # wall clock, for ?since= replay
    root: Span
    duration: float = 0.0
    status: str = "ok"
    slow: bool = False
    #: Scalar PollStats summary, attached by the poller before the cycle
    #: closes (the slow ring's flight-recorder payload).
    stats: dict | None = None

    def set_stats(self, stats) -> None:
        """Attach a PollStats' scalar fields (never the parsed snapshot —
        that is megabyte-scale and already served by /metrics)."""
        self.stats = {
            "backend_errors": stats.backend_errors,
            "parse_errors": stats.parse_errors,
            "families": stats.families,
            "points": stats.points,
            "coverage": stats.coverage,
            "unmapped": list(stats.unmapped),
        }

    def to_dict(self) -> dict:
        doc: dict = {
            "id": self.trace_id,
            "seq": self.seq,
            "start_ts": self.start_ts,
            "end_ts": self.start_ts + self.duration,
            "duration_seconds": round(self.duration, 6),
            "status": self.status,
            "slow": self.slow,
            "spans": [c.to_dict() for c in self.root.children],
        }
        if self.stats is not None:
            doc["stats"] = self.stats
        return doc


class Tracer:
    """Bounded-ring cycle recorder plus the ambient-span machinery.

    ``observe`` (optional) is called as ``observe(stage, seconds)`` for
    every span that maps to a stage bucket — top-level pipeline stages
    under their own name, nested spans only when they pass an explicit
    ``stage=`` (the gRPC RPC/serving spans) — feeding the
    ``tpumon_trace_stage_duration_seconds`` self-metric without giving
    per-metric span names label cardinality.
    """

    def __init__(
        self,
        slow_cycle_ms: float = DEFAULT_SLOW_CYCLE_MS,
        ring: int = DEFAULT_RING,
        slow_ring: int = DEFAULT_SLOW_RING,
        observe=None,
    ) -> None:
        self.slow_cycle_ms = float(slow_cycle_ms)
        self._observe = observe
        self._lock = threading.Lock()
        self._ring: deque[CycleTrace] = deque(maxlen=max(1, int(ring)))  # guarded-by: self._lock
        self._slow: deque[CycleTrace] = deque(maxlen=max(1, int(slow_ring)))  # guarded-by: self._lock
        self._seq = 0  # guarded-by: self._lock
        self._cycles = 0  # guarded-by: self._lock
        #: Memory-watermark degradation (tpumon/guard/memwatch): rings
        #: quartered, slow-cycle capture suspended. Reversible.
        self._degraded = False  # guarded-by: self._lock
        self._full_caps = (self._ring.maxlen, self._slow.maxlen)

    # -- recording (poll thread) ------------------------------------------

    @contextmanager
    def cycle(self):
        """Open one traced cycle on this thread; yields the CycleTrace
        (or None when a cycle is already open — the outer one wins)."""
        if getattr(_tls, "tracer", None) is not None:
            yield None
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
        ct = CycleTrace(
            trace_id=f"{seq:08x}",
            seq=seq,
            start_ts=time.time(),
            root=Span("cycle"),
        )
        _tls.tracer = self
        _tls.stack = [ct.root]
        _tls.t0 = time.perf_counter()
        _tls.trace_id = ct.trace_id
        try:
            yield ct
        except BaseException as exc:
            ct.status = ct.root.status = "error"
            ct.root.detail = repr(exc)[:200]
            raise
        finally:
            ct.duration = ct.root.duration = time.perf_counter() - _tls.t0
            _tls.tracer = None
            _tls.stack = None
            _tls.trace_id = None
            self._finish(ct)

    def _finish(self, ct: CycleTrace) -> None:
        ct.slow = ct.duration * 1000.0 >= self.slow_cycle_ms
        with self._lock:
            self._cycles += 1
            self._ring.append(ct)
            if ct.slow and not self._degraded:
                # Slow-cycle capture retains full span trees + stats;
                # under memory pressure that flight recorder is the
                # first thing to stop growing.
                self._slow.append(ct)

    # -- memory-watermark degradation (tpumon/guard/memwatch) -------------

    def degrade(self) -> None:
        """Quarter both rings (newest entries retained) and suspend
        slow-cycle capture; reversed by :meth:`restore`."""
        with self._lock:
            if self._degraded:
                return
            self._degraded = True
            self._ring = deque(
                self._ring, maxlen=max(1, self._full_caps[0] // 4)
            )
            self._slow = deque(
                self._slow, maxlen=max(1, self._full_caps[1] // 4)
            )

    def restore(self) -> None:
        """Back to full ring capacity + slow capture (contents kept)."""
        with self._lock:
            if not self._degraded:
                return
            self._degraded = False
            self._ring = deque(self._ring, maxlen=self._full_caps[0])
            self._slow = deque(self._slow, maxlen=self._full_caps[1])

    @contextmanager
    def span(self, name: str, stage: str | None = None):
        """One timed span. Nests into this thread's open cycle when there
        is one; otherwise tree-less (stage metric only)."""
        stack = getattr(_tls, "stack", None)
        in_cycle = stack is not None and getattr(_tls, "tracer", None) is self
        t0 = time.perf_counter()
        sp = Span(name, (t0 - _tls.t0) if in_cycle else 0.0)
        if in_cycle:
            stack[-1].children.append(sp)
            stack.append(sp)
        try:
            yield sp
        except BaseException as exc:
            sp.status = "error"
            sp.detail = repr(exc)[:200]
            raise
        finally:
            sp.duration = time.perf_counter() - t0
            top_level = False
            if in_cycle:
                stack.pop()
                top_level = len(stack) == 1
            bucket = stage if stage is not None else (
                name if (top_level or not in_cycle) else None
            )
            if self._observe is not None and bucket:
                try:
                    self._observe(bucket, sp.duration)
                except Exception:
                    # A metrics hiccup must never fail the stage.
                    log.debug("stage observer failed", exc_info=True)

    # -- query (HTTP threads) ---------------------------------------------

    def traces(self, slow: bool = False, since: float = 0.0) -> list[dict]:
        """Retained cycle traces ending at/after ``since`` (the /history
        replay semantics), oldest first, rendered lazily."""
        with self._lock:
            items = list(self._slow if slow else self._ring)
        return [
            ct.to_dict()
            for ct in items
            if ct.start_ts + ct.duration >= since
        ]

    def counts(self) -> dict:
        """Ring occupancy for /debug/vars and the trace envelopes."""
        with self._lock:
            return {
                "cycles": self._cycles,
                "ring": len(self._ring),
                "ring_capacity": self._ring.maxlen,
                "slow": len(self._slow),
                "slow_capacity": self._slow.maxlen,
                "degraded": self._degraded,
            }


@contextmanager
def trace_span(name: str, stage: str | None = None):
    """Ambient span: nests into this thread's open cycle, no-op (yields
    None) when none — how pipeline internals trace themselves without a
    tracer reference."""
    tracer = getattr(_tls, "tracer", None)
    if tracer is None:
        yield None
        return
    with tracer.span(name, stage=stage) as sp:
        yield sp
