"""Memory watermarks: staged, reversible degradation before the OOM kill.

The DaemonSet pod runs under a hard container memory limit (256Mi in the
shipped manifests); crossing it is a kill, not a degradation. The
watchdog samples RSS once per poll cycle (one /proc read — psutil when
present, /proc/self/statm otherwise) and walks a three-state machine:

- **NORMAL (0)** — full service.
- **SOFT (1)** — RSS crossed the soft watermark: every registered
  degrade hook fires once (the exporter shrinks the trace/history/
  anomaly rings to a quarter and disables slow-cycle capture), cutting
  the bounded-but-large consumers before the kernel cuts the process.
- **HARD (2)** — RSS crossed the hard watermark: the ingress guard
  reads this state and sheds every debug-class request with
  ``reason="memory"`` — metrics-only serving, because the JSON replay
  endpoints are exactly the transient allocations left.

Both transitions are reversible with 10% hysteresis (re-entering NORMAL
restores the rings), and always observable: ``tpumon_guard_state`` and
``tpumon_guard_rss_bytes`` ride the self-telemetry page, /debug/vars
carries the full snapshot, and state changes log at WARNING.
"""

from __future__ import annotations

import logging
import os
from typing import Callable

log = logging.getLogger(__name__)

NORMAL, SOFT, HARD = 0, 1, 2
STATE_NAMES = {NORMAL: "normal", SOFT: "soft", HARD: "hard"}

#: Fraction of a watermark RSS must drop below to leave its state —
#: without it, a process sitting exactly at the watermark would flap the
#: ring shrink/restore hooks every cycle.
HYSTERESIS = 0.9

#: Auto-watermark fractions of the container memory limit (the 256Mi
#: DaemonSet default → soft ~201 MB, hard ~241 MB).
AUTO_SOFT_FRACTION = 0.75
AUTO_HARD_FRACTION = 0.90

#: cgroup values at/above this are "no limit" sentinels (v1 reports
#: 2^63-1, v2 the literal "max").
_NO_LIMIT = float(1 << 60)


def container_memory_limit() -> float | None:
    """This process's cgroup memory limit in bytes, or None when
    unlimited/undetectable (bare processes, test runners)."""
    for path in (
        "/sys/fs/cgroup/memory.max",  # v2
        "/sys/fs/cgroup/memory/memory.limit_in_bytes",  # v1
    ):
        try:
            with open(path, encoding="ascii") as fh:
                raw = fh.read().strip()
        except OSError:
            continue
        if raw == "max":
            return None
        try:
            value = float(raw)
        except ValueError:
            continue
        if value <= 0 or value >= _NO_LIMIT:
            return None
        return value
    return None


def resolve_watermarks(
    soft_mb: float, hard_mb: float, limit_fn=container_memory_limit
) -> tuple[float, float]:
    """Knob semantics → byte thresholds: ``>0`` is an absolute MB value,
    ``0`` is auto (a fraction of the container memory limit; disarmed
    when the process has no meaningful limit — test runners and
    embedders must not inherit DaemonSet-sized thresholds), ``<0``
    disables that stage."""
    limit = limit_fn() if (soft_mb == 0 or hard_mb == 0) else None

    def one(mb: float, fraction: float) -> float:
        if mb > 0:
            return mb * 1e6
        if mb < 0:
            return 0.0
        return limit * fraction if limit else 0.0

    return one(soft_mb, AUTO_SOFT_FRACTION), one(hard_mb, AUTO_HARD_FRACTION)


def _default_rss_fn():
    """Best available RSS reader, or None when the platform has none
    (the watchdog then disarms rather than guessing)."""
    try:
        import psutil

        info = psutil.Process(os.getpid()).memory_info
        return lambda: float(info().rss)
    except ImportError:
        pass
    try:
        page = os.sysconf("SC_PAGE_SIZE")
        open("/proc/self/statm", "rb").close()  # probe readability

        def rss() -> float:
            with open("/proc/self/statm", "rb") as fh:
                return float(int(fh.read().split()[1]) * page)

        return rss
    except (OSError, ValueError, AttributeError):
        return None


class MemoryWatch:
    """The RSS state machine; ``check()`` runs once per poll cycle.

    ``soft_bytes``/``hard_bytes`` <= 0 disable their stage. ``rss_fn``
    is injectable for tests; when no reader exists the watch stays
    disarmed at NORMAL. ``degrade``/``restore`` hooks are registered via
    :meth:`add_hooks` and fire on the NORMAL→(SOFT|HARD) and →NORMAL
    edges; a raising hook is logged and skipped — the state machine must
    never wedge on a consumer bug.
    """

    def __init__(
        self, soft_bytes: float, hard_bytes: float, rss_fn=None
    ) -> None:
        self.soft_bytes = float(soft_bytes)
        self.hard_bytes = float(hard_bytes)
        if 0 < self.hard_bytes < self.soft_bytes:
            # Malformed knobs degrade to a sane order, never crash.
            self.soft_bytes = self.hard_bytes
        self._rss_fn = rss_fn if rss_fn is not None else _default_rss_fn()
        self.state = NORMAL
        self.last_rss = 0.0
        self.max_rss = 0.0
        self.transitions = 0
        self._hooks: list[tuple[Callable[[], None], Callable[[], None]]] = []  # (degrade, restore)

    @property
    def armed(self) -> bool:
        return self._rss_fn is not None and (
            self.soft_bytes > 0 or self.hard_bytes > 0
        )

    def add_hooks(
        self, degrade: Callable[[], None], restore: Callable[[], None]
    ) -> None:
        self._hooks.append((degrade, restore))

    def _fire(self, index: int, label: str) -> None:
        for pair in self._hooks:
            try:
                pair[index]()
            except Exception:
                log.exception("memory watchdog %s hook failed", label)

    def check(self) -> int:
        """Sample RSS, transition, fire hooks on edges; returns state."""
        if not self.armed:
            return self.state
        try:
            rss = float(self._rss_fn())
        except Exception:
            log.exception("RSS sampling failed; memory watchdog disarmed")
            self._rss_fn = None
            if self.state != NORMAL:
                # Disarming while degraded would freeze SOFT/HARD (and
                # its shedding) until process restart — no sample can
                # ever clear it. Blind is blind: restore full service.
                self.state = NORMAL
                self.transitions += 1
                self._fire(1, "restore")
            return self.state
        self.last_rss = rss
        self.max_rss = max(self.max_rss, rss)

        new = self.state
        if self.state == NORMAL:
            if 0 < self.hard_bytes <= rss:
                new = HARD
            elif 0 < self.soft_bytes <= rss:
                new = SOFT
        elif self.state == SOFT:
            if 0 < self.hard_bytes <= rss:
                new = HARD
            elif rss < self.soft_bytes * HYSTERESIS:
                new = NORMAL
        elif self.state == HARD:
            if rss < self.hard_bytes * HYSTERESIS:
                # Fall back to SOFT (not straight to NORMAL) so the ring
                # shrink persists until RSS is genuinely back under the
                # soft watermark too.
                new = (
                    SOFT
                    if 0 < self.soft_bytes * HYSTERESIS <= rss
                    else NORMAL
                )
        if new == self.state:
            return self.state

        old = self.state
        self.state = new
        self.transitions += 1
        log.warning(
            "memory watermark: %s -> %s (rss %.1f MB, soft %.1f / hard "
            "%.1f MB)",
            STATE_NAMES[old], STATE_NAMES[new], rss / 1e6,
            self.soft_bytes / 1e6, self.hard_bytes / 1e6,
        )
        if old == NORMAL:
            self._fire(0, "degrade")
        elif new == NORMAL:
            self._fire(1, "restore")
        return self.state

    def snapshot(self) -> dict:
        """The /debug/vars "guard" memory block."""
        return {
            "state": STATE_NAMES[self.state],
            "armed": self.armed,
            "rss_bytes": self.last_rss,
            "max_rss_bytes": self.max_rss,
            "soft_bytes": self.soft_bytes,
            "hard_bytes": self.hard_bytes,
            "transitions": self.transitions,
        }


__all__ = ["HARD", "MemoryWatch", "NORMAL", "SOFT", "STATE_NAMES"]
