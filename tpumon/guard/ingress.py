"""Scrape admission control: caps, rate limits, deadlines, shedding.

The scrape path serves cached bytes, so a *well-behaved* scraper can
never hurt the exporter — but nothing in HTTP makes clients well
behaved. :class:`IngressGuard` is the policy object both serving planes
consult:

- the WSGI middleware (:meth:`IngressGuard.wsgi`) classifies each
  request into an endpoint class, enforces a concurrency cap and a
  token-bucket rate limit per class, and answers ``503 Service
  Unavailable`` with ``Retry-After`` and a pre-built static body when
  saturated — shedding costs one dict lookup and a counter increment,
  never a render;
- the HTTP handler (tpumon/exporter/server.py) reads the deadline knobs
  to evict idle keep-alive connections and kill slowloris (header bytes
  must complete within ``header_timeout_s`` of the first byte);
- the gRPC service (tpumon/exporter/grpc_service.py) counts its
  per-client Watch-stream sheds through the same
  ``tpumon_shed_requests_total{endpoint,reason}`` funnel;
- the memory watchdog (tpumon/guard/memwatch.py) plugs in as
  ``memory_state``: at the hard watermark every debug-class endpoint is
  shed with ``reason="memory"`` — metrics-only serving.

Everything is lock-cheap: admission is O(1) under one small mutex per
endpoint class, far off the poll loop's thread.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

log = logging.getLogger(__name__)

#: Endpoint classes with independent caps/buckets. The health probes are
#: deliberately unlisted: kubelet liveness must keep answering while
#: everything else sheds, or overload converts into a restart storm.
METRICS = "metrics"
DEBUG = "debug"

#: Pre-built shed response (the whole point is that shedding is cheaper
#: than serving).
SHED_BODY = b"overloaded: request shed, retry later\n"
SHED_STATUS = "503 Service Unavailable"
SHED_HEADERS = (
    ("Content-Type", "text/plain; charset=utf-8"),
    ("Retry-After", "1"),
    ("Content-Length", str(len(SHED_BODY))),
)


class TokenBucket:
    """Classic token bucket; ``rate`` tokens/s, capacity ``burst``.

    ``rate <= 0`` disables the bucket (always allows). Injectable clock
    for deterministic tests.
    """

    def __init__(self, rate: float, burst: float, clock=time.monotonic) -> None:
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._tokens = self.burst  # guarded-by: self._lock
        self._last = clock()  # guarded-by: self._lock
        self._lock = threading.Lock()

    def allow(self) -> bool:
        if self.rate <= 0:
            return True
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


class _EndpointPolicy:
    """Concurrency cap + rate bucket for one endpoint class."""

    def __init__(self, max_inflight: int, rps: float, clock) -> None:
        self.max_inflight = int(max_inflight)
        self.bucket = TokenBucket(rps, burst=2.0 * rps, clock=clock)
        self.inflight = 0  # guarded-by: self.lock
        self.lock = threading.Lock()

    def admit(self) -> str | None:
        """None = admitted (caller must release()); else the shed reason.

        Concurrency is checked BEFORE a rate token is consumed: a
        concurrency-shed burst must not drain the bucket and convert
        later well-paced requests into misattributed "rate" sheds."""
        with self.lock:
            if self.max_inflight > 0 and self.inflight >= self.max_inflight:
                return "concurrency"
            if not self.bucket.allow():
                return "rate"
            self.inflight += 1
        return None

    def release(self) -> None:
        with self.lock:
            self.inflight -= 1


class IngressGuard:
    """The admission-control policy shared by the HTTP and gRPC planes.

    ``count_shed(endpoint, reason)`` feeds the
    ``tpumon_shed_requests_total`` counter through an injected observer
    (the exporter passes the self-telemetry counter; tests pass a dict
    recorder); ``memory_state`` (a ``() -> int`` callable, 0/1/2) is the
    memwatch plug — at 2 (hard watermark) debug-class requests shed with
    ``reason="memory"``.
    """

    def __init__(
        self,
        metrics_inflight: int = 16,
        debug_inflight: int = 4,
        metrics_rps: float = 0.0,
        debug_rps: float = 20.0,
        header_timeout_s: float = 5.0,
        idle_timeout_s: float = 65.0,
        write_timeout_s: float = 10.0,
        watch_per_client: int = 4,
        memory_state: Callable[[], int] | None = None,
        observe_shed: Callable[[str, str], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.header_timeout_s = max(0.0, float(header_timeout_s))
        self.idle_timeout_s = max(0.0, float(idle_timeout_s))
        self.write_timeout_s = max(0.0, float(write_timeout_s))
        self.watch_per_client = int(watch_per_client)
        self._memory_state = memory_state
        self._observe_shed = observe_shed
        self._policies = {
            METRICS: _EndpointPolicy(metrics_inflight, metrics_rps, clock),
            DEBUG: _EndpointPolicy(debug_inflight, debug_rps, clock),
        }
        self._shed_lock = threading.Lock()
        #: (endpoint, reason) -> count, for /debug/vars and tests.
        self.shed_counts: dict[tuple[str, str], int] = {}  # guarded-by: self._shed_lock

    # -- classification ----------------------------------------------------

    @staticmethod
    def classify(path: str) -> tuple[str | None, str | None]:
        """(endpoint label, policy class) for a request path; (None, None)
        leaves the request unguarded (health probes, 404s)."""
        if path in ("/metrics", "/"):
            return METRICS, METRICS
        if path == "/history":
            return "history", DEBUG
        if path == "/anomalies":
            return "anomalies", DEBUG
        if path == "/hostcorr":
            # Host-correlation replay (tpumon/hostcorr): serializes ring
            # records per request — debug-class budget.
            return "hostcorr", DEBUG
        if path == "/lifecycle":
            # Lifecycle replay (tpumon/lifecycle): serializes ring
            # records per request — debug-class budget.
            return "lifecycle", DEBUG
        if path == "/fleet":
            # Fleet-tier JSON API (tpumon/fleet/server.py): allocates a
            # full per-node document per request — debug-class budget.
            return "fleet", DEBUG
        if path == "/ledger":
            # Ledger range query (tpumon/ledger): decodes sealed chunks
            # per request — debug-class budget, bounded + continuation.
            return "ledger", DEBUG
        if path == "/hints":
            # Placement-hint table (tpumon/actuate): serializes the
            # per-slice read model per request — debug-class budget.
            return "hints", DEBUG
        if path.startswith("/apis/"):
            # External Metrics API (tpumon/actuate/adapter.py): served
            # off the pre-computed read model, but per-request JSON
            # construction — debug-class budget. An HPA polls at ~15 s
            # cadence, far inside the budget; the guard bounds abuse.
            return "external_metrics", DEBUG
        if path.startswith("/debug/") or path == "/health/devices":
            return DEBUG, DEBUG
        return None, None

    # -- accounting --------------------------------------------------------

    def count_shed(self, endpoint: str, reason: str) -> None:
        with self._shed_lock:
            key = (endpoint, reason)
            self.shed_counts[key] = self.shed_counts.get(key, 0) + 1
        if self._observe_shed is not None:
            try:
                self._observe_shed(endpoint, reason)
            except Exception:
                # A metrics hiccup must never fail the shed path.
                log.debug("shed observer failed", exc_info=True)

    def memory_state(self) -> int:
        if self._memory_state is None:
            return 0
        try:
            return int(self._memory_state())
        except Exception:
            # Failing open (no shed) beats shedding on a broken probe.
            log.debug("memory-state probe failed", exc_info=True)
            return 0

    def snapshot(self) -> dict:
        """The /debug/vars "guard" ingress block."""
        with self._shed_lock:
            shed = {
                f"{ep}:{reason}": n
                for (ep, reason), n in sorted(self.shed_counts.items())
            }
        return {
            "shed": shed,
            "inflight": {
                name: pol.inflight for name, pol in self._policies.items()
            },
            "limits": {
                name: {
                    "max_inflight": pol.max_inflight,
                    "rps": pol.bucket.rate,
                }
                for name, pol in self._policies.items()
            },
            "deadlines": {
                "header_s": self.header_timeout_s,
                "idle_s": self.idle_timeout_s,
                "write_s": self.write_timeout_s,
            },
        }

    # -- WSGI middleware ---------------------------------------------------

    def wsgi(self, app):
        """Wrap a WSGI app in admission control + load shedding."""

        def guarded(environ, start_response):
            endpoint, policy_name = self.classify(
                environ.get("PATH_INFO", "/")
            )
            if endpoint is None:
                return app(environ, start_response)
            if policy_name == DEBUG and self.memory_state() >= 2:
                # Hard watermark: metrics-only serving. The expensive
                # JSON endpoints are exactly the allocations we are
                # trying to stop making.
                self.count_shed(endpoint, "memory")
                start_response(SHED_STATUS, list(SHED_HEADERS))
                return [SHED_BODY]
            policy = self._policies[policy_name]
            reason = policy.admit()
            if reason is not None:
                self.count_shed(endpoint, reason)
                start_response(SHED_STATUS, list(SHED_HEADERS))
                return [SHED_BODY]
            try:
                # Every inner app returns a fully materialized [bytes],
                # so releasing after the call (not after iteration) is
                # correct — nothing streams lazily.
                return app(environ, start_response)
            finally:
                policy.release()

        return guarded


__all__ = [
    "DEBUG",
    "IngressGuard",
    "METRICS",
    "SHED_BODY",
    "SHED_HEADERS",
    "SHED_STATUS",
    "TokenBucket",
]
