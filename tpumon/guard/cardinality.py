"""Cardinality governor: a per-family label-set budget for /metrics.

Pod churn (accelerator_pod_info grows one series per pod placement),
attribution noise, or a runtime that suddenly enumerates per-link series
on a big slice can inflate the exposition page without bound — and every
series costs Prometheus ingestion, the history recorder, and the render
loop forever. The governor runs once per poll cycle on the poller thread
(families are freshly built, so mutation is private) and enforces a hard
per-family series budget:

- the first ``max_series`` samples of a family (build order is
  deterministic, so the surviving set is stable across cycles — no
  series churn from the governor itself) are served untouched;
- every overflow sample collapses into ONE sentinel sample whose
  non-base label values read ``other`` and whose value is the SUM of the
  collapsed samples (bounded cost is the contract; the sentinel is an
  aggregate, not a per-series truth — alert on the drop counter, not on
  ``other``'s value);
- the drop is observable: ``tpumon_cardinality_dropped_series_total
  {family}`` counts collapsed series-samples cumulatively.

Histogram-shaped families (mixed sample names: ``_bucket``/``_sum``/
``_count`` rows) are skipped — their cardinality is already bounded by
the fixed bucket ladder, and summing across mixed row kinds would be
nonsense.
"""

from __future__ import annotations

import logging
from typing import Callable

log = logging.getLogger(__name__)

SENTINEL = "other"


class FamilyIndex:
    """Per-family shape probe backing the governor's histogram check.

    The governor must never collapse a histogram-shaped family (mixed
    ``_bucket``/``_sum``/``_count`` sample names — summing across row
    kinds is nonsense), so every over-budget family needs a name-
    uniformity verdict. At the 1000-series budget the per-cycle Python
    set build was tolerable; at the 10k+ budget the scan runs in C
    (``_exposition.uniform_names``: one attribute fetch + pointer
    compare per sample, ~5 µs per 10k series) when the native renderer
    built, Python-set fallback otherwise. The verdict is deliberately
    NOT cached across cycles: a family's composition can change at a
    constant series count, and a stale uniform=True would collapse
    across mixed row kinds — correctness over a microsecond.
    """

    def __init__(self) -> None:
        self._native = None
        self._native_tried = False

    def uniform(self, name: str, samples) -> bool:
        """True when every sample in the family shares one sample name
        (safe to govern). ``name`` kept for log/debug call sites."""
        if not self._native_tried:
            # Lazy, once: the extension loads asynchronously at startup
            # (prewarm_async) — don't force a compile on the poll path.
            self._native_tried = True
            from tpumon import _native

            ext = _native.load_extension("_exposition")
            self._native = getattr(ext, "uniform_names", None)
        if self._native is not None:
            return bool(self._native(samples))
        return len({s.name for s in samples}) <= 1


class CardinalityGovernor:
    """Per-family series budget with sentinel-``other`` collapse.

    ``observe_drop(family, n)`` (optional) feeds the self-telemetry
    counter; :attr:`dropped` keeps the cumulative per-family tally for
    /debug/vars either way. ``max_series <= 0`` disables the governor
    (``govern`` becomes a no-op).
    """

    def __init__(
        self,
        max_series: int,
        observe_drop: Callable[[str, int], None] | None = None,
    ) -> None:
        self.max_series = int(max_series)
        self._observe_drop = observe_drop
        #: family -> cumulative collapsed-sample count.
        self.dropped: dict[str, int] = {}
        #: Shape verdicts (histogram-family skip), native-backed.
        self._index = FamilyIndex()

    def govern(self, families, base_keys=()) -> int:
        """Enforce the budget in place; returns samples collapsed this
        cycle. ``base_keys`` are the node-constant identity labels —
        preserved on the sentinel sample so it joins like every other
        series."""
        if self.max_series <= 0:
            return 0
        base = set(base_keys)
        collapsed = 0
        for fam in families:
            samples = fam.samples
            if len(samples) <= self.max_series:
                continue
            if not self._index.uniform(fam.name, samples):
                continue  # histogram-shaped: bounded by its bucket ladder
            overflow = samples[self.max_series:]
            if len(overflow) == 1 and all(
                v == SENTINEL
                for k, v in overflow[0].labels.items()
                if k not in base
            ):
                # Already governed (a stale-served family from the
                # last-good cache): budget + its own sentinel. Re-collapsing
                # would count a phantom drop every cycle.
                continue
            del samples[self.max_series:]
            first = overflow[0]
            sentinel_labels = {
                k: (v if k in base else SENTINEL)
                for k, v in first.labels.items()
            }
            total = sum(s.value for s in overflow)
            samples.append(type(first)(first.name, sentinel_labels, total))
            collapsed += len(overflow)
            prev = self.dropped.get(fam.name, 0)
            self.dropped[fam.name] = prev + len(overflow)
            if prev == 0:
                log.warning(
                    "cardinality budget (%d) exceeded for %s: collapsing "
                    "%d series into label value %r",
                    self.max_series, fam.name, len(overflow), SENTINEL,
                )
            if self._observe_drop is not None:
                try:
                    self._observe_drop(fam.name, len(overflow))
                except Exception:
                    # A metrics hiccup must never fail the cycle.
                    log.debug("cardinality drop observer failed", exc_info=True)
        return collapsed

    def snapshot(self) -> dict:
        """The /debug/vars "guard" cardinality block."""
        return {
            "max_series_per_family": self.max_series,
            "dropped": dict(sorted(self.dropped.items())),
        }


__all__ = ["CardinalityGovernor", "FamilyIndex", "SENTINEL"]
