"""Self-protection plane: the exporter guarding itself from its clients.

PR 3 (tpumon/resilience) made the exporter survive a misbehaving device
*backend*; this package makes it survive misbehaving *clients* and its
own unbounded growth — a scrape storm from N Prometheus replicas, a
slowloris connection, a runaway ``/debug/traces?since=`` replay, or
pod-churn-driven label-cardinality explosion must degrade observably
instead of stalling the 1 Hz poll loop or OOM-killing the DaemonSet pod:

- :mod:`tpumon.guard.ingress` — scrape admission control: per-endpoint
  concurrency caps + token-bucket rate limits (:class:`IngressGuard`,
  :class:`TokenBucket`), hard request deadlines (header-read + write
  timeouts that kill slowloris, enforced in the HTTP handler), and load
  shedding that answers ``503 + Retry-After`` with a cheap static body
  when saturated.
- :mod:`tpumon.guard.cardinality` — per-family label-set budget
  (:class:`CardinalityGovernor`): overflow series collapse into a
  sentinel ``other`` label value, bounding /metrics size and Prometheus
  ingestion cost no matter how fast pods churn.
- :mod:`tpumon.guard.memwatch` — RSS/ring-accounting watermarks
  (:class:`MemoryWatch`): at the soft watermark the trace/history/
  anomaly rings shrink and slow-cycle capture stops; at the hard
  watermark serving drops to metrics-only. Both states are reversible
  and surfaced via ``tpumon_guard_state``.
- :mod:`tpumon.guard.stormer` — the client-side chaos counterpart to
  tpumon/resilience/faults.py (:class:`Stormer`): deterministic scrape
  storms, slowloris connections, oversized requests, and Watch-stream
  hammering, so the shedding claims are exercised in CI
  (tests/test_guard.py, ``tools/soak.py --storm``) rather than asserted.

Degradation is always *observable*: ``tpumon_guard_state`` /
``tpumon_shed_requests_total{endpoint,reason}`` /
``tpumon_cardinality_dropped_series_total{family}`` ride the
self-telemetry registry (tpumon/families.py, docs/METRICS.md).
"""

from __future__ import annotations

from tpumon.guard.cardinality import CardinalityGovernor
from tpumon.guard.ingress import IngressGuard, TokenBucket
from tpumon.guard.memwatch import HARD, NORMAL, SOFT, MemoryWatch
from tpumon.guard.stormer import Stormer

__all__ = [
    "CardinalityGovernor",
    "HARD",
    "IngressGuard",
    "MemoryWatch",
    "NORMAL",
    "SOFT",
    "Stormer",
    "TokenBucket",
]
