"""Client-side chaos: the request-generator counterpart to faults.py.

tpumon/resilience/faults.py injects a misbehaving *backend* under the
exporter; :class:`Stormer` points misbehaving *clients* at it — so the
guard plane's shedding/deadline/cap claims are exercised in CI
(tests/test_guard.py, ``tools/soak.py --storm``) rather than asserted:

- **scrape storm** — N threads hammering an endpoint back-to-back over
  persistent connections (the N-Prometheus-replicas / runaway-fan-in
  shape), counting statuses and well-behaved latencies;
- **slowloris** — connections that trickle header bytes forever; the
  server must evict them within the header deadline while normal
  scrapes keep answering;
- **oversized requests** — request lines and header blocks past the
  parser bounds; the server must answer 414/431 and close, never
  allocate proportionally;
- **Watch hammer** — more concurrent gRPC ``Watch`` streams than the
  per-client cap; the overflow must be refused with RESOURCE_EXHAUSTED
  while admitted streams keep receiving pushes.

Everything is deterministic given the knobs (fixed thread counts, fixed
durations, no randomness), and every probe reports an evidence dict the
callers assert on or embed in the soak record.
"""

from __future__ import annotations

import http.client
import socket
import threading
import time


def scrape_storm(
    host: str,
    port: int,
    duration_s: float,
    threads: int = 8,
    path: str = "/metrics",
) -> dict:
    """Hammer ``path`` from ``threads`` persistent connections for
    ``duration_s``; returns status counts, latency stats, and whether
    every 503 carried Retry-After."""
    lock = threading.Lock()
    statuses: dict[int, int] = {}
    lat_ms: list[float] = []
    missing_retry_after = 0
    errors = 0

    def worker() -> None:
        nonlocal missing_retry_after, errors
        conn = http.client.HTTPConnection(host, port, timeout=10)
        deadline = time.monotonic() + duration_s
        try:
            while time.monotonic() < deadline:
                t0 = time.perf_counter()
                try:
                    conn.request("GET", path)
                    resp = conn.getresponse()
                    resp.read()
                except (OSError, http.client.HTTPException):
                    with lock:
                        errors += 1
                    conn.close()
                    conn = http.client.HTTPConnection(host, port, timeout=10)
                    continue
                ms = (time.perf_counter() - t0) * 1e3
                with lock:
                    statuses[resp.status] = statuses.get(resp.status, 0) + 1
                    lat_ms.append(ms)
                    if resp.status == 503 and not resp.getheader(
                        "Retry-After"
                    ):
                        missing_retry_after += 1
        finally:
            conn.close()

    pool = [
        threading.Thread(target=worker, name=f"storm-{i}", daemon=True)
        for i in range(threads)
    ]
    for t in pool:
        t.start()
    for t in pool:
        # Workers are deadline-bounded (duration_s + per-request timeout);
        # the join bound only guards against a wedged worker thread.
        t.join(timeout=duration_s + 30.0)
    # A worker that outlived the join bound may still be appending:
    # aggregate a lock-held snapshot, never the live containers.
    stragglers = sum(1 for t in pool if t.is_alive())
    with lock:
        lat = sorted(lat_ms)
        status_snap = dict(statuses)
        error_count = errors
        missing = missing_retry_after
    return {
        "path": path,
        "threads": threads,
        "requests": sum(status_snap.values()),
        "statuses": {str(k): v for k, v in sorted(status_snap.items())},
        "errors": error_count,
        "missing_retry_after": missing,
        "stragglers": stragglers,
        "p50_ms": round(lat[len(lat) // 2], 3) if lat else None,
        "max_ms": round(lat[-1], 3) if lat else None,
    }


def slowloris(
    host: str,
    port: int,
    duration_s: float,
    conns: int = 2,
    drip_every_s: float = 0.5,
) -> dict:
    """Open ``conns`` connections that never finish their headers,
    dripping one header byte per ``drip_every_s``. Reports how many the
    server closed (evicted) before the duration elapsed."""
    evicted = 0
    held_open = 0
    lock = threading.Lock()

    def worker(i: int) -> None:
        nonlocal evicted, held_open
        try:
            sock = socket.create_connection((host, port), timeout=5)
        except OSError:
            with lock:
                evicted += 1  # couldn't even connect: counted as refused
            return
        try:
            sock.sendall(b"GET /metrics HTTP/1.1\r\nHost: storm\r\nX-Drip: ")
            deadline = time.monotonic() + duration_s
            while time.monotonic() < deadline:
                time.sleep(drip_every_s)
                try:
                    sock.sendall(b"a")
                except OSError:
                    with lock:
                        evicted += 1
                    return
                # A server that closed its side surfaces as EOF on read.
                sock.settimeout(0.01)
                try:
                    if sock.recv(1024) == b"":
                        with lock:
                            evicted += 1
                        return
                except socket.timeout:
                    pass
                except OSError:
                    with lock:
                        evicted += 1
                    return
            with lock:
                held_open += 1
        finally:
            sock.close()

    pool = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(conns)
    ]
    for t in pool:
        t.start()
    for t in pool:
        t.join(timeout=duration_s + 30.0)
    return {"conns": conns, "evicted": evicted, "held_open": held_open}


def oversized_request(host: str, port: int) -> dict:
    """One oversized request line + one oversized header block; returns
    the statuses the server answered (or 'closed')."""

    def probe(payload: bytes) -> str:
        try:
            sock = socket.create_connection((host, port), timeout=5)
        except OSError:
            return "refused"
        try:
            sock.sendall(payload)
            sock.settimeout(5)
            data = sock.recv(256)
            if not data:
                return "closed"
            line = data.split(b"\r\n", 1)[0].decode("latin-1", "replace")
            parts = line.split()
            return parts[1] if len(parts) >= 2 else "garbage"
        except OSError:
            return "closed"
        finally:
            sock.close()

    return {
        "long_request_line": probe(
            b"GET /" + b"a" * 70000 + b" HTTP/1.1\r\n\r\n"
        ),
        # Past the 64KB total-head bound (40 x ~2KB values), not just
        # the stdlib 100-header count limit — this exercises the
        # server's own allocation cap.
        "huge_headers": probe(
            b"GET /metrics HTTP/1.1\r\nHost: x\r\n"
            + b"".join(
                b"X-Flood-%d: %s\r\n" % (i, b"v" * 2048) for i in range(40)
            )
            + b"\r\n"
        ),
    }


def watch_hammer(
    grpc_addr: str, streams: int, duration_s: float, timeout: float = 5.0
) -> dict:
    """Open ``streams`` concurrent Watch streams from this process and
    hold them for ``duration_s``; reports admitted vs refused. Returns
    ``{"skipped": True}`` when grpcio is unavailable."""
    try:
        import grpc

        from tpumon.exporter.grpc_service import METHOD_WATCH
    except ImportError:
        return {"skipped": True}

    admitted = 0
    refused = 0  # RESOURCE_EXHAUSTED only: the cap actually engaged
    errors = 0  # transport failures — NOT evidence of the cap
    lock = threading.Lock()

    def worker() -> None:
        nonlocal admitted, refused, errors
        channel = grpc.insecure_channel(grpc_addr)
        try:
            call = channel.unary_stream(
                METHOD_WATCH, request_serializer=None,
                response_deserializer=None,
            )
            stream = call(b"", timeout=duration_s + timeout)
            try:
                next(iter(stream))  # first push (or the abort)
                with lock:
                    admitted += 1
                time.sleep(duration_s)
            except grpc.RpcError as err:
                code = err.code() if hasattr(err, "code") else None
                with lock:
                    if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                        refused += 1
                    else:
                        errors += 1
            finally:
                stream.cancel()
        finally:
            channel.close()

    pool = [
        threading.Thread(target=worker, daemon=True) for _ in range(streams)
    ]
    for t in pool:
        t.start()
    for t in pool:
        t.join(timeout=duration_s + timeout + 30.0)
    return {
        "streams": streams,
        "admitted": admitted,
        "refused": refused,
        "errors": errors,
    }


class Stormer:
    """Runs every probe concurrently against one exporter — the
    acceptance-test / ``soak.py --storm`` driver."""

    def __init__(
        self, host: str, port: int, grpc_addr: str | None = None
    ) -> None:
        self.host = host
        self.port = port
        self.grpc_addr = grpc_addr

    def run(
        self,
        duration_s: float,
        scrape_threads: int = 8,
        slowloris_conns: int = 2,
        debug_threads: int = 4,
        watch_streams: int = 8,
    ) -> dict:
        """The ISSUE acceptance mix: a /metrics storm at ``scrape_threads``
        × the normal (single-scraper) concurrency, a /debug replay storm,
        slowloris connections, oversized requests, and a Watch hammer —
        all at once, for ``duration_s``."""
        results: dict = {}
        lock = threading.Lock()

        def put(key, fn, *args, **kwargs):
            def run() -> None:
                try:
                    out = fn(*args, **kwargs)
                # tpumon-invariants: disable=except-hygiene (the failure IS the evidence: it lands in the storm report as {"error": ...})
                except Exception as exc:  # evidence, not a crash
                    out = {"error": repr(exc)}
                with lock:
                    results[key] = out

            return threading.Thread(target=run, name=f"storm-{key}", daemon=True)

        jobs = [
            put(
                "scrape_storm", scrape_storm, self.host, self.port,
                duration_s, scrape_threads, "/metrics",
            ),
            put(
                "debug_storm", scrape_storm, self.host, self.port,
                duration_s, debug_threads, "/debug/traces?since=0",
            ),
            put(
                "slowloris", slowloris, self.host, self.port, duration_s,
                slowloris_conns,
            ),
            put("oversized", oversized_request, self.host, self.port),
        ]
        if self.grpc_addr:
            jobs.append(
                put(
                    "watch_hammer", watch_hammer, self.grpc_addr,
                    watch_streams, min(duration_s, 3.0),
                )
            )
        for t in jobs:
            t.start()
        for t in jobs:
            # Every probe is duration-bounded; the join bound keeps a
            # wedged probe thread from hanging the whole storm report.
            t.join(timeout=duration_s + 60.0)
        for t in jobs:
            if t.is_alive():
                # The report contract is "every probe key present,
                # possibly as an error record" — a wedged probe must
                # say so, not vanish into a consumer KeyError.
                key = t.name.removeprefix("storm-")
                with lock:
                    results.setdefault(
                        key, {"error": "probe thread timed out"}
                    )
        return results


__all__ = [
    "Stormer",
    "oversized_request",
    "scrape_storm",
    "slowloris",
    "watch_hammer",
]
