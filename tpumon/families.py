"""Canonical registry of every Prometheus family tpumon can serve.

Single source of truth consumed by the metrics-reference generator
(tpumon/tools/gen_metrics_doc.py), the dashboard PromQL validator
(tests/test_dashboards.py), and a live-scrape coherence test — so the
docs, dashboards, and code cannot drift apart silently. The device
families themselves live in tpumon/schema.py (LIBTPU_SPECS); this module
covers everything else the exporter and harness emit.
"""

from __future__ import annotations

#: family -> (description, extra labels beyond the base identity labels)
IDENTITY_FAMILIES: dict[str, tuple[str, tuple[str, ...]]] = {
    "accelerator_device_count": (
        "Chips visible to this exporter (0 on CPU-only nodes)",
        (),
    ),
    "accelerator_core_count": (
        "Compute cores visible to this exporter",
        (),
    ),
    "accelerator_slice_host_count": (
        "Hosts in this accelerator slice",
        (),
    ),
    "accelerator_info": (
        "Per-chip identity incl. physical coords (PCIe-BDF replacement)",
        ("chip", "coords", "device_id", "cores"),
    ),
    "accelerator_core_state": (
        "Per-core runtime state from the device monitoring service",
        ("core", "state"),
    ),
    "accelerator_pod_info": (
        "Accelerator devices allocated to pods (kubelet pod-resources API)",
        ("namespace", "pod", "container", "resource", "chip", "device_id"),
    ),
    "accelerator_monitor_watch_streams": (
        "Runtime monitoring watch streams by state (streaming / "
        "open-idle / down); absent unless the grpc backend has opened "
        "watches. Unary polling carries any non-streaming metric",
        ("state",),
    ),
}

#: family -> (description, extra labels) — derived by the exporter from
#: device families each poll (tpumon/health.py thresholds), so alerts can
#: fire on verdicts without re-encoding thresholds in PromQL.
HEALTH_FAMILIES: dict[str, tuple[str, tuple[str, ...]]] = {
    "accelerator_health_status": (
        "Node device-health verdict: 0 ok, 1 warn, 2 crit "
        "(dcgmi health -c analogue; thresholds in tpumon/health.py)",
        (),
    ),
    "accelerator_health_findings": (
        "Active device-health findings by severity and check code",
        ("severity", "code"),
    ),
}

#: family -> (description, extra labels) — the streaming anomaly engine
#: (tpumon/anomaly) fed by the poll loop; same severity vocabulary as the
#: health families. `tpu_anomaly_active` is absent when nothing is
#: anomalous (absent-not-zero); `tpu_anomaly_detectors` is always present
#: while the engine is enabled, so "engine armed" is scrapeable.
ANOMALY_FAMILIES: dict[str, tuple[str, tuple[str, ...]]] = {
    "tpu_anomaly_detectors": (
        "Streaming anomaly detectors armed on this node (1 per enabled "
        "detector; tpumon/anomaly)",
        ("detector",),
    ),
    "tpu_anomaly_active": (
        "Currently active anomaly events by detector and severity "
        "(absent when nothing is anomalous)",
        ("detector", "severity"),
    ),
    "tpu_anomaly_events_total": (
        "Anomaly event onsets since exporter start by detector and severity",
        ("detector", "severity"),
    ),
    "tpu_anomaly_suppressed_total": (
        "Detector verdicts suppressed during a clean workload-lifecycle "
        "transition window (tpumon/lifecycle: preemption / elastic "
        "resize / checkpoint restore), by detector — a false straggler "
        "that a preemption would have raised shows up here instead of "
        "as an event",
        ("detector",),
    ),
}

#: family -> (prometheus type, description, extra labels) — the
#: workload-lifecycle robustness plane (tpumon/lifecycle): the exporter
#: probes the workload harness's metrics port (tpu_step_* families
#: below), classifies preemption/resize/restore transitions from the
#: joint step+device+membership signals, and suppresses false verdicts
#: during clean transitions. ``tpu_lifecycle_workloads`` is always
#: present while the plane is enabled; step-derived families are absent
#: when no workload feed answers (absent-not-zero).
LIFECYCLE_FAMILIES: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "tpu_lifecycle_workloads": (
        "gauge",
        "Workload step feeds by probe state (state ∈ available/absent); "
        "a node with no configured feeds reports absent=0 available=0 — "
        "the plane still tracks device-side lifecycle signatures",
        ("state",),
    ),
    "tpu_lifecycle_state": (
        "gauge",
        "0 steady, 1 while a recognized lifecycle transition "
        "(preemption/resize/restore) holds the suppression window open",
        (),
    ),
    "tpu_lifecycle_events_total": (
        "counter",
        "Recognized workload-lifecycle transitions since exporter start "
        "by kind (preemption / resize / restore)",
        ("kind",),
    ),
    "tpu_lifecycle_step_rate": (
        "gauge",
        "Optimizer steps per second reported by the probed workload "
        "feeds (mean over available feeds; absent when none report) — "
        "the fleet tier rolls this up per slice",
        (),
    ),
    "tpu_lifecycle_step_duration_seconds": (
        "gauge",
        "Mean wall seconds per optimizer step over the probed feeds "
        "(absent when none report)",
        (),
    ),
    "tpu_lifecycle_collective_wait_fraction": (
        "gauge",
        "Worst collective-wait fraction across the probed workload "
        "feeds (absent when none report it) — the ICI-contention "
        "detector's input",
        (),
    ),
    "tpu_lifecycle_checkpoints_total": (
        "counter",
        "Checkpoint spans completed across the probed workload feeds "
        "by op (save/restore), summed per node — the fleet goodput "
        "ledger's checkpoint-window signal (a feed restart resets its "
        "share; ordinary counter-reset semantics)",
        ("op",),
    ),
    "tpu_lifecycle_serve_requests_per_second": (
        "gauge",
        "Completed inference requests per second summed over the "
        "probed serving feeds (absent when none report) — the fleet "
        "actuation tier rolls this up per slice",
        (),
    ),
    "tpu_lifecycle_serve_queue_depth": (
        "gauge",
        "Requests admitted but not yet completed, summed over the "
        "probed serving feeds (absent when none report) — the primary "
        "scale-out pressure signal",
        (),
    ),
    "tpu_lifecycle_serve_ttft_seconds": (
        "gauge",
        "Worst time-to-first-token proxy across the probed serving "
        "feeds over the last window (absent when none report)",
        (),
    ),
    "tpu_lifecycle_serve_slo_attainment_ratio": (
        "gauge",
        "Fraction of requests meeting the serving latency SLO over the "
        "last window, mean over the probed serving feeds (absent when "
        "none report) — goodput-under-SLO at node granularity",
        (),
    ),
    "tpu_lifecycle_serve_batch_size": (
        "gauge",
        "Mean effective batch size across the probed serving feeds "
        "over the last window (absent when none report)",
        (),
    ),
}

#: family -> (prometheus type, description, extra labels) — the
#: host-correlation plane (tpumon/hostcorr): 1 Hz procfs/cgroupfs host
#: signals time-aligned with the poll stream, plus the cross-signal
#: straggler verdict. ``tpu_hostcorr_available`` is always present while
#: the plane is enabled (0 on hosts without PSI/schedstat — the
#: graceful-degradation flag); every signal family is absent when its
#: source is unreadable (absent-not-zero), and ``tpu_straggler_verdict``
#: is absent unless a straggler is active.
HOSTCORR_FAMILIES: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "tpu_hostcorr_available": (
        "gauge",
        "1 when the host-correlation sampler reads at least one host "
        "signal group; 0 on kernels without PSI/schedstat — detectors "
        "then fall back to device-only verdicts",
        (),
    ),
    "tpu_hostcorr_signal_available": (
        "gauge",
        "Per-group host-signal availability (signal ∈ psi/sched/net/"
        "disk/vm)",
        ("signal",),
    ),
    "tpu_hostcorr_psi_share": (
        "gauge",
        "cgroup PSI avg10 stall share (0-1 fraction of wall time tasks "
        "stalled on the resource; resource ∈ cpu/memory/io, kind ∈ "
        "some/full)",
        ("resource", "kind"),
    ),
    "tpu_hostcorr_psi_stall_seconds_total": (
        "counter",
        "Cumulative PSI stall seconds by resource and kind (the "
        "kernel's total= counter)",
        ("resource", "kind"),
    ),
    "tpu_hostcorr_pod_psi_share": (
        "gauge",
        "Per-pod cgroup PSI avg10 stall share from the kubepods pod "
        "dir's own *.pressure files (resource ∈ cpu/memory/io, 'some' "
        "kind; pod is the kubepods pod UID) — names WHICH pod is "
        "starving where the node-scope PSI only says that one is; "
        "absent on cgroup-v1 nodes (node-scope PSI is the fallback)",
        ("pod", "resource"),
    ),
    "tpu_hostcorr_sched_delay_seconds_total": (
        "counter",
        "Per-pod scheduler run delay (runnable-but-not-running) "
        "accumulated from /proc/<pid>/schedstat since exporter start; "
        "pod is the kubepods pod UID",
        ("pod",),
    ),
    "tpu_hostcorr_sched_delay_share": (
        "gauge",
        "Per-pod sched-delay rate over the last poll cycle (delay "
        "seconds per wall second; ~1.0 = a core's worth of waiting)",
        ("pod",),
    ),
    "tpu_hostcorr_net_bytes_per_second": (
        "gauge",
        "Physical-NIC byte rate over the last poll cycle (dir ∈ rx/tx; "
        "lo and virtual veth/bridge/tunnel interfaces excluded, so this "
        "reads LOWER than the all-interface host_network_bytes_total on "
        "pod-dense nodes) — DCN/input-pipeline saturation context",
        ("dir",),
    ),
    "tpu_hostcorr_disk_bytes_per_second": (
        "gauge",
        "Physical whole-device disk byte rate over the last poll cycle "
        "(dir ∈ read/write; partitions and dm/md stacked devices "
        "excluded — one payload byte counts once) — checkpoint/"
        "input-pipeline IO context",
        ("dir",),
    ),
    "tpu_hostcorr_page_cache_bytes": (
        "gauge",
        "Host page-cache occupancy (/proc/meminfo Cached)",
        (),
    ),
    "tpu_hostcorr_reclaim_pages_per_second": (
        "gauge",
        "Page-reclaim scan rate (pgscan_kswapd + pgscan_direct) over "
        "the last poll cycle — page-cache pressure",
        (),
    ),
    "tpu_straggler_skew_pct": (
        "gauge",
        "Worst-chip vs median duty-cycle skew in percentage points "
        "(absent when fewer than 2 chips report duty)",
        (),
    ),
    "tpu_straggler_step_skew_ratio": (
        "gauge",
        "Slowest workload feed's step time over the feed median, minus "
        "1 (0.5 = 50% slower) — the straggler-HOST magnitude duty skew "
        "cannot see; absent unless ≥2 lifecycle feeds report step "
        "timing",
        (),
    ),
    "tpu_straggler_verdict": (
        "gauge",
        "1 while a straggler is active: the same chip sat skew_warn_pct "
        "below the slice median for skew_cycles consecutive polls; "
        "cause ∈ device/host-cpu/host-mem/host-io/unknown, chip is the "
        "laggard (absent when no straggler)",
        ("cause", "chip"),
    ),
    "tpu_straggler_events_total": (
        "counter",
        "Straggler episodes since exporter start by attributed cause; "
        "an episode is counted once its cause is established (onset, or "
        "the later unknown→host-* upgrade; never-attributed episodes "
        "count as unknown at clear)",
        ("cause",),
    ),
}

#: family -> (prometheus type, description, extra labels) — the
#: energy/cost plane (tpumon/energy): per-chip power/energy with an
#: explicit provenance label on EVERY family (``source`` ∈ measured /
#: modeled — a dashboard can never pass the duty×TDP model off as a
#: device reading), pod-attributed energy, and the step-efficiency
#: joins against the lifecycle plane's ``tpu_step_*`` telemetry. All
#: families are absent-not-zero: no chips visible → no power series,
#: no workload feed → no efficiency join, ``tpu_step_cost_dollars``
#: absent until TPUMON_ENERGY_DOLLARS_PER_KWH is set.
ENERGY_FAMILIES: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "tpu_energy_power_watts": (
        "gauge",
        "Instantaneous per-chip power draw in watts; source=measured "
        "when the device library reported it (accelerator_power_watts),"
        " source=modeled when estimated from duty cycle × the "
        "accelerator's TDP envelope, HBM-activity adjusted "
        "(tpumon/energy/model.py, TPUMON_ENERGY_TDP_W override)",
        ("chip", "source"),
    ),
    "tpu_energy_joules_total": (
        "counter",
        "Accumulated per-chip energy since exporter start, integrated "
        "at poll cadence with gap honesty: a poll gap past "
        "TPUMON_ENERGY_MAX_GAP_S is integrated only up to the cap (the "
        "skipped remainder is counted in the /debug/vars energy block, "
        "never invented). Keyed per source so a backend flapping "
        "between exposing and hiding power telemetry keeps each series "
        "monotonic",
        ("chip", "source"),
    ),
    "tpu_pod_energy_joules_total": (
        "counter",
        "Chip energy attributed to the pods holding each chip "
        "(accelerator_pod_info join, split equally among co-holders); "
        "the per-pod sums add up to the attributed chips' "
        "tpu_energy_joules_total — energy on unattributed chips stays "
        "chip-only",
        ("namespace", "pod", "source"),
    ),
    "tpu_step_energy_joules": (
        "gauge",
        "Joules THIS NODE spends per optimizer step: node power × the "
        "probed workload feeds' mean step duration (absent when no "
        "feed reports step timing; job-level step energy = sum over "
        "the job's hosts); source=measured only when every "
        "contributing chip's power was a device reading",
        ("source",),
    ),
    "tpu_step_tokens_per_joule": (
        "gauge",
        "Training tokens per joule, node-scoped: the probed feeds' "
        "JOB-global tokens/s split across the slice's hosts (each host "
        "of a dp job reports the job's rate) over THIS node's power — "
        "comparable across jobs of any host count; the headline "
        "efficiency number the efficiency_regression detector "
        "baselines per workload preset",
        ("source",),
    ),
    "tpu_step_cost_dollars": (
        "gauge",
        "Dollars one optimizer step costs at the configured "
        "electricity price (TPUMON_ENERGY_DOLLARS_PER_KWH; absent "
        "while the knob is 0 — a made-up price is worse than none)",
        ("source",),
    ),
}

#: family -> (prometheus type, description, extra labels) — the fleet
#: aggregation tier (tpumon/fleet): pre-aggregated recording-rule-style
#: rollups served by the aggregator's /metrics, plus the aggregator's
#: own self-telemetry. Rollup families carry ``scope`` ∈
#: slice/pool/fleet with ``pool``/``slice`` identity labels (empty at
#: the wider scopes); per-node series are never re-exported.
FLEET_FAMILIES: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "tpu_fleet_hosts": (
        "gauge",
        "Exporter hosts known to the aggregator shard by ingest state: "
        "up (fresh), stale (rolled up from flagged last-good data), "
        "dark (evicted from rollups, still counted)",
        ("scope", "pool", "slice", "state"),
    ),
    "tpu_fleet_chips": (
        "gauge",
        "Accelerator chips contributing to the scope's rollup (dark "
        "hosts excluded)",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_duty_cycle_percent": (
        "gauge",
        "Chip duty-cycle rollup across the scope (stat ∈ mean/min/max)",
        ("scope", "pool", "slice", "stat"),
    ),
    "tpu_fleet_hbm_used_bytes": (
        "gauge",
        "Summed HBM bytes in use across the scope",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_hbm_total_bytes": (
        "gauge",
        "Summed HBM capacity bytes across the scope",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_hbm_headroom_ratio": (
        "gauge",
        "Free fraction of the scope's HBM (1 - used/total)",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_ici_links": (
        "gauge",
        "ICI interconnect links across the scope by health "
        "(state ∈ healthy/degraded)",
        ("scope", "pool", "slice", "state"),
    ),
    "tpu_fleet_ici_health_score": (
        "gauge",
        "ICI health scored per scope: healthy-link fraction, 1.0 = all "
        "clean (absent when the scope reports no links)",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_mfu_ratio": (
        "gauge",
        "Mean model-FLOPs utilization over hosts reporting it (absent "
        "when none do)",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_degraded_hosts": (
        "gauge",
        "Hosts in the scope whose exporter reports degraded serving "
        "(tpumon_degraded)",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_stragglers": (
        "gauge",
        "Hosts in the scope with an active straggler verdict "
        "(tpu_straggler_verdict), by attributed cause — the fleet-wide "
        "straggler ranking the hostcorr plane feeds",
        ("scope", "pool", "slice", "cause"),
    ),
    "tpu_fleet_straggler_skew_pct": (
        "gauge",
        "Worst straggler skew across the scope's hosts (max of each "
        "host's tpu_straggler_skew_pct; absent when no host reports it)",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_straggler_step_skew_ratio": (
        "gauge",
        "Worst step-skew ratio across the scope's hosts (max of each "
        "host's tpu_straggler_step_skew_ratio — the lagging-HOST "
        "magnitude duty skew cannot see; absent when no host reports "
        "it)",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_step_rate": (
        "gauge",
        "Mean workload optimizer steps/s over the scope's hosts "
        "reporting tpu_lifecycle_step_rate (absent when none do) — the "
        "per-slice training-progress rollup the lifecycle plane feeds",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_lifecycle_transitions": (
        "gauge",
        "Hosts in the scope currently inside a workload-lifecycle "
        "transition window (tpu_lifecycle_state == 1: preemption / "
        "resize / restore in progress)",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_energy_watts": (
        "gauge",
        "Summed node power across the scope (tpu_energy_power_watts "
        "rollup); source=measured only when every contributing host's "
        "power was device-reported — one modeled host makes the scope "
        "modeled, so capacity dashboards always know they are reading "
        "an estimate",
        ("scope", "pool", "slice", "source"),
    ),
    "tpu_fleet_tokens_per_joule": (
        "gauge",
        "Mean tokens/joule over the scope's hosts reporting "
        "tpu_step_tokens_per_joule (absent when none do); same "
        "worst-of source labeling as tpu_fleet_energy_watts",
        ("scope", "pool", "slice", "source"),
    ),
    "tpu_fleet_peer_seeded_total": (
        "counter",
        "Feeds adopted on takeover/hand-back that were seeded warm from "
        "an alive peer shard's last-good snapshot instead of starting "
        "cold (stale-flagged by ordinary age classification until the "
        "first live fetch)",
        (),
    ),
    "tpu_fleet_stale_rollup": (
        "gauge",
        "1 when the scope's rollup includes stale (last-good) node "
        "data — stale-flagged, never silently absent",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_visibility_ratio": (
        "gauge",
        "Fraction of the scope's known hosts contributing FRESH data "
        "to the rollup — below 1.0 the rollup is PARTIAL (stale "
        "last-good inclusions, partition, dead feeds, takeover in "
        "progress), never silently renormalized; scope=global covers "
        "the whole universe across shards",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_membership_targets": (
        "gauge",
        "Target universe size by discovery source (static / file / "
        "k8s Endpoints)",
        ("source",),
    ),
    "tpu_fleet_membership_changes_total": (
        "counter",
        "Live membership churn applied after the debounce window, by "
        "op (add/remove of universe targets)",
        ("op",),
    ),
    "tpu_fleet_peer_up": (
        "gauge",
        "Peer aggregator shard liveness from /fleet/summary probes "
        "(1 answering, 0 past the takeover deadline), by peer index",
        ("peer",),
    ),
    "tpu_fleet_takeovers_total": (
        "counter",
        "Orphaned targets this shard adopted after a peer shard died "
        "(rendezvous re-claim over the surviving shards)",
        (),
    ),
    "tpu_fleet_ingest_rejects_total": (
        "counter",
        "Upstream payloads refused before parsing, by reason "
        "(oversized / bad_frame hostile length prefix / undecodable / "
        "unparseable) — a corrupt feed never costs aggregator memory",
        ("reason",),
    ),
    "tpu_fleet_spool_restored_nodes": (
        "gauge",
        "Node snapshots served from the warm-restart spool since "
        "startup (stale-flagged by ordinary age classification)",
        (),
    ),
    "tpu_fleet_spool_errors_total": (
        "counter",
        "Warm-restart spool failures by op (load/write, plus enospc "
        "counted once per memory-only degradation transition); the "
        "aggregator runs on, cold",
        ("op",),
    ),
    "tpu_fleet_spool_degraded": (
        "gauge",
        "1 while the warm-restart spool runs memory-only because the "
        "volume is full / read-only (ENOSPC/EROFS/EDQUOT); clears on "
        "the first retry probe that writes clean",
        (),
    ),
    "tpu_fleet_scrape_duration_seconds": (
        "histogram",
        "Wall time to serve one aggregator /metrics exposition (the "
        "fleet-dashboard p99)",
        (),
    ),
    "tpu_fleet_collect_duration_seconds": (
        "histogram",
        "Wall time of one aggregator collect cycle (ingest scheduling "
        "+ rollup + render)",
        (),
    ),
    "tpu_fleet_node_fetches_total": (
        "counter",
        "Upstream fetch outcomes by transport mode (watch/poll) and "
        "result (ok, error, parse_error, breaker_open)",
        ("mode", "result"),
    ),
    "tpu_fleet_up": (
        "gauge",
        "1 while the aggregator's collect loop completes cycles; 0 "
        "after a wholesale-failed cycle",
        (),
    ),
    "tpu_fleet_shard_targets": (
        "gauge",
        "Upstream targets owned by this shard after rendezvous-hash "
        "assignment (tpumon/fleet/shard.py)",
        (),
    ),
    "tpu_fleet_watch_streams": (
        "gauge",
        "Upstream gRPC Watch fan-in streams by state (streaming/down/"
        "off; off = the target rides HTTP polling)",
        ("state",),
    ),
    "tpu_fleet_fanin_bytes_total": (
        "counter",
        "Accepted fan-in payload bytes by transport mode (watch/poll) "
        "and representation kind (delta frame / full snapshot frame / "
        "text page) — with the delta protocol negotiated, steady-state "
        "bytes track change rate, not fleet size",
        ("mode", "kind"),
    ),
    "tpu_fleet_fanin_frames_total": (
        "counter",
        "Accepted fan-in payloads by transport mode and representation "
        "kind; together with the bytes counter gives bytes/frame per "
        "kind",
        ("mode", "kind"),
    ),
    "tpu_fleet_fanin_resyncs_total": (
        "counter",
        "Full-snapshot frames that replaced live delta base state, by "
        "cause (gap = sequence mismatch forced a resync, epoch = "
        "upstream exporter restarted, full = upstream chose a resync); "
        "a fleet-wide rate spike is a resync storm — see "
        "docs/OPERATIONS.md triage",
        ("reason",),
    ),
    "tpu_fleet_rollup_dirty_nodes": (
        "gauge",
        "Feeds whose rollup-relevant content or ingest state changed "
        "last collect cycle — the observed churn the incremental "
        "rollup's work is proportional to",
        (),
    ),
    "tpu_fleet_rollup_dirty_buckets": (
        "gauge",
        "Slice buckets re-aggregated last collect cycle; all other "
        "buckets' rollups were reused unchanged",
        (),
    ),
    "tpu_fleet_rollup_shards": (
        "gauge",
        "Striped-ingest accumulator shard count "
        "(TPUMON_FLEET_ROLLUP_STRIPES): fan-in writes land in "
        "per-slice shards keyed by rendezvous of the slice identity, "
        "so concurrent apply-delta calls never share a lock",
        (),
    ),
    "tpu_fleet_rollup_shard_entries": (
        "gauge",
        "Feeds held per striped-ingest shard — a skewed distribution "
        "means one slice dominates the fleet and its shard's lock "
        "sees most of the write traffic",
        ("shard",),
    ),
    "tpu_fleet_rollup_shard_writes_total": (
        "counter",
        "Snapshot stores landed per striped-ingest shard (the "
        "writer-contention spread; rate it to see where fan-in write "
        "traffic concentrates)",
        ("shard",),
    ),
    "tpu_fleet_rollup_dirty_stripes": (
        "gauge",
        "Striped-ingest shards actually drained last publish; clean "
        "shards replayed their cached rows, so idle-fleet publish cost "
        "is proportional to this, not to the shard count",
        (),
    ),
}

#: family -> (prometheus type, description, extra labels) — the fleet
#: efficiency ledger (tpumon/ledger): long-horizon tiered storage
#: self-metrics plus the per-job goodput accounting, served on the
#: aggregator's /metrics page beside the FLEET_FAMILIES rollups.
LEDGER_FAMILIES: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "tpu_fleet_goodput_chip_seconds_total": (
        "counter",
        "Chip-seconds accounted per job (scope=slice) and fleet-wide "
        "by goodput bucket (productive / checkpoint / restore / "
        "preempted / idle / contended / unaccounted). Buckets sum to "
        "observed wall-clock × chips per job; partitions and "
        "aggregator-blind windows land in unaccounted, never in idle",
        ("scope", "pool", "slice", "bucket"),
    ),
    "tpu_fleet_goodput_energy_joules_total": (
        "counter",
        "Node energy attributed per job (scope=slice) and fleet-wide: "
        "watts integrated over each feed's visible goodput accounting "
        "windows (unaccounted windows invent no joules); "
        "source=measured only when every contributing window's power "
        "was device-reported",
        ("scope", "pool", "slice", "source"),
    ),
    "tpu_fleet_goodput_energy_dollars_total": (
        "counter",
        "Per-job energy cost at the configured "
        "TPUMON_FLEET_LEDGER_DOLLARS_PER_KWH electricity price; absent "
        "(never 0) when no price is configured",
        ("scope", "pool", "slice"),
    ),
    "tpu_ledger_series": (
        "gauge",
        "Distinct series stored per ledger tier (1s / 10s / 5m)",
        ("tier",),
    ),
    "tpu_ledger_samples_total": (
        "counter",
        "Samples recorded into each ledger tier since start (aggregate "
        "tiers count finalized buckets)",
        ("tier",),
    ),
    "tpu_ledger_bytes": (
        "gauge",
        "Sealed compressed bytes held per ledger tier (open buffers "
        "excluded)",
        ("tier",),
    ),
    "tpu_ledger_dropped_chunks_total": (
        "counter",
        "Sealed chunks dropped by bound (retention age / tier byte "
        "budget) — bounded by construction, drops counted never silent",
        ("reason",),
    ),
    "tpu_ledger_gap_seconds_total": (
        "counter",
        "Wall seconds the ledger could not observe (aggregator "
        "restarts between spool saves): ledgered into the unaccounted "
        "goodput bucket, never interpolated into samples",
        (),
    ),
    "tpu_ledger_queries_total": (
        "counter",
        "GET /ledger range queries served",
        (),
    ),
    "tpu_ledger_spool_errors_total": (
        "counter",
        "Ledger spool failures by op (load / write, plus enospc "
        "counted once per memory-only degradation transition); the "
        "plane runs on, memory-only (absent unless the spool is "
        "configured)",
        ("op",),
    ),
    "tpu_ledger_spool_degraded": (
        "gauge",
        "1 while the ledger spool runs memory-only because the volume "
        "is full / read-only (ENOSPC/EROFS/EDQUOT); absent unless the "
        "spool is configured",
        (),
    ),
    "tpu_ledger_remote_write_total": (
        "counter",
        "Prometheus remote-write push outcomes (result ∈ ok/error); "
        "absent unless TPUMON_FLEET_LEDGER_REMOTE_WRITE_URL is set",
        ("result",),
    ),
}

#: family -> (prometheus type, description, extra labels) — the
#: ledger's analytics read side (tpumon/ledger/analytics.py +
#: forecast.py): waste ranking and capacity forecasting surfaced as
#: exposition beside the LEDGER_FAMILIES rows, so the
#: capacity-planning dashboard and the TPUMonPoolSaturating /
#: TPUMonForecastBreach alerts run off Prometheus, not off /ledger.
ANALYTICS_FAMILIES: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "tpu_fleet_waste_chip_seconds_total": (
        "counter",
        "Wasted chip-seconds per job (scope=slice) and fleet-wide: "
        "the contended + idle goodput buckets — chips held but not "
        "advancing work. A strict subset of "
        "tpu_fleet_goodput_chip_seconds_total, so it conserves "
        "against the same per-job totals",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_waste_fraction_quantile": (
        "gauge",
        "Waste-fraction quantiles (p50/p90/p99) per workload class "
        "(pool/serve-or-train): the cohort a job's percentile "
        "standing in /ledger?view=percentiles is computed against",
        ("wclass", "quantile"),
    ),
    "tpu_fleet_forecast_days_to_saturation": (
        "gauge",
        "Days until the pool saturates (duty rising to 95% or HBM "
        "headroom falling to 5%), least-squares over the ledger's "
        "coarse tier; ABSENT for pools whose history or trend cannot "
        "support a date — never a fabricated one. 0 means already "
        "saturated",
        ("pool",),
    ),
    "tpu_fleet_forecast_slope_per_day": (
        "gauge",
        "Fitted per-day trend slope per pool and signal (signal is "
        "the stored ledger family the fit ran over)",
        ("pool", "signal"),
    ),
    "tpu_fleet_forecast_insufficient_history": (
        "gauge",
        "1 when the pool's history span is below "
        "TPUMON_FLEET_LEDGER_FORECAST_MIN_HISTORY_S and no saturation "
        "date is served, else 0 — the honesty surface capacity alerts "
        "gate on",
        ("pool",),
    ),
}

#: family -> (prometheus type, description)
SELF_FAMILIES: dict[str, tuple[str, str]] = {
    "exporter_scrape_duration_seconds": (
        "histogram",
        "Wall time to render one /metrics exposition (headline p99)",
    ),
    "exporter_poll_duration_seconds": (
        "histogram",
        "Wall time of one device poll cycle",
    ),
    "exporter_metric_coverage_ratio": (
        "gauge",
        "Mapped fraction of the device library's supported metrics "
        "(target ≥0.95; 0.0 during enumeration outages)",
    ),
    "exporter_backend_info": (
        "gauge",
        "Active backend name + device-library version",
    ),
    "collector_errors_total": (
        "counter",
        "Device-query / parse failures by kind (samples dropped, never fatal)",
    ),
    "collector_polls_total": ("counter", "Completed poll cycles"),
    "collector_last_poll_timestamp_seconds": (
        "gauge",
        "Unix time of the last completed poll (liveness)",
    ),
    "collector_poll_lag_seconds": (
        "gauge",
        "Overrun of the configured interval (0 when keeping up)",
    ),
    "tpumon_trace_stage_duration_seconds": (
        "histogram",
        "Per-stage poll-pipeline span durations from the internal trace "
        "plane (tpumon/trace; stage ∈ pipeline stages plus backend_rpc "
        "and grpc_serve — full span trees at /debug/traces)",
    ),
    "tpumon_poll_stage_errors_total": (
        "counter",
        "Swallowed per-cycle stage failures (history record, anomaly "
        "pass) by stage — the cycle survives, the stage's output is "
        "missing",
    ),
    "tpumon_up": (
        "gauge",
        "1 while the poll loop completes cycles; 0 after a "
        "wholesale-failed cycle or a watchdog-detected hang "
        "(tpumon/resilience)",
    ),
    "tpumon_degraded": (
        "gauge",
        "1 when the last cycle served anything other than fresh-complete "
        "data: stale-but-served families, an open circuit breaker, or a "
        "recovered enumeration outage",
    ),
    "tpumon_family_staleness_seconds": (
        "gauge",
        "Age of each family currently served from the last-good cache "
        "instead of a fresh device query (family label); absent when "
        "fresh",
    ),
    "tpumon_breaker_state": (
        "gauge",
        "Per-device-query circuit-breaker state (query label): 0 closed, "
        "1 half-open (probing), 2 open (calls refused, last-good served)",
    ),
    "tpumon_retries_total": (
        "counter",
        "Transport-level device-call retries (bounded exponential "
        "backoff with jitter), by call kind",
    ),
    "tpumon_watchdog_recoveries_total": (
        "counter",
        "Stuck-poll-cycle recoveries: a device call ran past the hang "
        "budget and the watchdog tore the backend down (interrupt + "
        "channel re-init)",
    ),
    "tpumon_guard_state": (
        "gauge",
        "Self-protection memory state (tpumon/guard): 0 normal, 1 soft "
        "watermark (rings shrunk, slow-cycle capture off), 2 hard "
        "watermark (metrics-only serving)",
    ),
    "tpumon_guard_rss_bytes": (
        "gauge",
        "Exporter process RSS sampled by the memory watchdog each poll "
        "cycle (0 until the first sample)",
    ),
    "tpumon_shed_requests_total": (
        "counter",
        "Requests refused by the ingress guard (503 + Retry-After with "
        "a static body), by endpoint class and reason (concurrency, "
        "rate, memory, slowloris)",
    ),
    "tpumon_cardinality_dropped_series_total": (
        "counter",
        "Series collapsed into the sentinel `other` label value by the "
        "per-family cardinality budget, by family",
    ),
    "tpumon_render_delta": (
        "gauge",
        "1 while the incremental (delta) page renderer is active "
        "(TPUMON_RENDER_DELTA): per-family cached byte segments, only "
        "changed families re-render each poll cycle",
    ),
    "tpumon_render_family_cache_hits_total": (
        "counter",
        "Family byte segments served unchanged from the render cache "
        "across poll cycles (delta renderer)",
    ),
    "tpumon_render_invalidated_families": (
        "gauge",
        "Families re-rendered in the last poll cycle because their "
        "samples changed or first appeared",
    ),
    "tpumon_render_encode_saves_total": (
        "counter",
        "Scrape responses served straight from the per-encoding "
        "response cache (zero encode work), by exposition format and "
        "content encoding (format/encoding labels)",
    ),
    "tpumon_exposition_requests_total": (
        "counter",
        "Negotiated /metrics (and gRPC Get/Watch) responses by "
        "exposition format: text, openmetrics, or the compact snapshot "
        "encoding the fleet tier requests (format label)",
    ),
}

#: family -> description (workload-side harness --metrics-port)
WORKLOAD_FAMILIES: dict[str, str] = {
    "workload_collective_ops_total": (
        "XLA collective HLO ops seen by the in-process libtpu HLO logger, by op"
    ),
    "workload_hlo_log_events_total": (
        "Total HLO logger events received in-process"
    ),
    "workload_collective_op_latency_microseconds_total": (
        "Summed per-op latency extracted from HLO logger events (absent "
        "until an event carries a duration figure; correlate with "
        "accelerator_collective_latency_microseconds)"
    ),
    "workload_collective_op_latency_samples_total": (
        "Events that carried a duration figure, by op — the denominator "
        "for average-latency queries"
    ),
    "workload_collective_op_bytes_total": (
        "Summed per-op payload bytes extracted from HLO logger events "
        "(absent until an event carries a size figure)"
    ),
    "workload_steps_total": (
        "Optimizer steps completed by the harness train loop"
    ),
    "workload_mesh_info": (
        "Parallelism degrees (dp/tp/sp/pp/ep labels) of the running "
        "workload's mesh"
    ),
    "workload_loss": (
        "Training loss at the most recent recorded window boundary"
    ),
    "workload_steps_per_second": (
        "Optimizer steps per second over the most recent window (the "
        "train loop syncs once per window, staying pipelined between)"
    ),
    "workload_tokens_per_second": (
        "Training tokens per second over the most recent window"
    ),
    "workload_model_flops_per_step": (
        "Model FLOPs one optimizer step executes (exact per-matmul "
        "accounting, tpumon.workload.flops)"
    ),
    "workload_mfu_ratio": (
        "Live model FLOPs utilization vs the devices' published bf16 "
        "peak (absent when the peak is unknown; correlate with "
        "accelerator_duty_cycle_percent)"
    ),
}

#: family -> description — per-step phase telemetry the workload harness
#: serves on its own metrics port (tpumon/workload/stats.py) and the
#: exporter's lifecycle plane (tpumon/lifecycle) probes: the
#: monitor↔trainer loop. Families are absent until the harness measures
#: them (absent-not-zero); ``tpu_step_terminating`` flips to 1 inside a
#: SIGTERM grace window — the preemption signature.
STEP_FAMILIES: dict[str, str] = {
    "tpu_step_counter": (
        "Training-global optimizer step (checkpoint-resume start step "
        "plus steps completed by this process)"
    ),
    "tpu_step_duration_seconds": (
        "Mean wall seconds per optimizer step over the most recent "
        "stats window (the lifecycle plane's step-time-regression input)"
    ),
    "tpu_step_phase_seconds": (
        "Wall seconds of the last instrumented step's phases (phase ∈ "
        "fwd/bwd/optimizer; harness --phase-stats, one instrumented "
        "step per window)"
    ),
    "tpu_step_collective_wait_fraction": (
        "Fraction of step wall time spent inside collective ops over "
        "the most recent window (ICI-contention signal)"
    ),
    "tpu_step_checkpoint_seconds": (
        "Wall seconds of the most recent checkpoint span by op "
        "(save/restore) — restore spans are the restore-storm signature"
    ),
    "tpu_step_checkpoints_total": (
        "Checkpoint spans completed since process start, by op "
        "(save/restore)"
    ),
    "tpu_step_terminating": (
        "1 once SIGTERM reached the harness (preemption grace window in "
        "progress); 0 while training normally"
    ),
}

#: family -> description — request-level serving telemetry the workload
#: harness's inference preset serves on its metrics port
#: (tpumon/workload/serve.py) and the exporter's lifecycle plane lifts
#: into ``tpu_lifecycle_serve_*``. Families are absent until the serving
#: loop records a window (absent-not-zero).
SERVE_FAMILIES: dict[str, str] = {
    "tpu_serve_requests_total": (
        "Inference requests completed by the serving loop since start"
    ),
    "tpu_serve_requests_per_second": (
        "Completed requests per second over the most recent stats window"
    ),
    "tpu_serve_queue_depth": (
        "Requests admitted but not yet completed (instantaneous) — the "
        "scale-out pressure signal the actuation tier exports to HPAs"
    ),
    "tpu_serve_batch_size": (
        "Mean effective batch size over the most recent window"
    ),
    "tpu_serve_ttft_seconds": (
        "Time-to-first-token proxy over the most recent window: queue "
        "wait plus one decode-step latency for newly admitted requests"
    ),
    "tpu_serve_slo_attainment_ratio": (
        "Fraction of requests whose TTFT proxy met the configured SLO "
        "over the most recent window — goodput under SLO"
    ),
    "tpu_serve_slo_threshold_seconds": (
        "The configured TTFT SLO threshold the attainment ratio is "
        "measured against (constant per run)"
    ),
}

#: family -> (prometheus type, description, extra labels) — the
#: actuation plane (tpumon/actuate): per-slice serving rollups, the
#: placement-hint engine, and External Metrics adapter self-metrics,
#: served on the aggregator's /metrics page beside FLEET_FAMILIES.
#: Serving rollups are absent for scopes with no serving feeds; hint
#: families are absent until a slice has a computed score.
ACTUATE_FAMILIES: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "tpu_fleet_serve_requests_per_second": (
        "gauge",
        "Completed inference requests per second summed over the "
        "scope's serving feeds (scope ∈ fleet/pool/slice)",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_serve_queue_depth": (
        "gauge",
        "Admitted-but-incomplete requests summed over the scope's "
        "serving feeds — the external metric an HPA scales on",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_serve_ttft_seconds": (
        "gauge",
        "Worst time-to-first-token proxy across the scope's serving "
        "feeds",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_serve_slo_attainment_ratio": (
        "gauge",
        "Mean fraction of requests meeting the serving SLO across the "
        "scope's serving feeds — goodput under SLO",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_hint_headroom_score": (
        "gauge",
        "Placement-hint headroom score in [0, 1] per slice (duty + HBM "
        "+ ICI + straggler state + ledger goodput history; higher = "
        "better placement target); pool/fleet scopes are chip-weighted "
        "means",
        ("scope", "pool", "slice"),
    ),
    "tpu_fleet_hint_band": (
        "gauge",
        "1 for the slice's current hysteresis-held placement band "
        "(band ∈ prefer/neutral/avoid), 0 for the others — the "
        "annotation value a scheduler extender consumes",
        ("pool", "slice", "band"),
    ),
    "tpu_fleet_hint_transitions_total": (
        "counter",
        "Published placement-band changes per slice since aggregator "
        "start — a high rate means the hysteresis hold "
        "(TPUMON_FLEET_HINT_HOLD_CYCLES) is too short for the fleet's "
        "load variance",
        ("pool", "slice"),
    ),
    "tpu_fleet_external_metrics_requests_total": (
        "counter",
        "External Metrics API requests served by the adapter, by "
        "metric name and result (ok / stale / withheld / not_found / "
        "bad_request)",
        ("metric", "result"),
    ),
    "tpu_actuate_trust_score": (
        "gauge",
        "Signal-integrity trust in [0, 1] per slice scope "
        "(tpumon/actuate/trust.py: visibility × staleness × contested "
        "× spool warmth); answers below TPUMON_ACTUATE_MIN_TRUST are "
        "withheld from the actuation surfaces",
        ("pool", "slice"),
    ),
    "tpu_actuate_scope_epoch": (
        "gauge",
        "Ownership epoch this shard's answers for the scope were "
        "minted under (Lamport-folded across peer shards); conflicting "
        "claims resolve newest-epoch-wins",
        ("pool", "slice"),
    ),
    "tpu_actuate_hint_frozen": (
        "gauge",
        "1 while the slice's placement band is FROZEN at last-good "
        "(its telemetry is below the trust floor or epoch-conflicted; "
        "decays to neutral after TPUMON_FLEET_HINT_DECAY_S), 0 while "
        "the hysteresis runs live",
        ("pool", "slice"),
    ),
    "tpu_actuate_withheld_total": (
        "counter",
        "Collect cycles a scope's actuation answers were withheld "
        "(External Metric items absent, hint band frozen), by reason "
        "(untrusted / epoch_conflict) — degraded telemetry holds the "
        "world still, it never steers it",
        ("pool", "slice", "reason"),
    ),
    "tpu_actuate_epoch_conflicts_total": (
        "counter",
        "CONTESTED cycles where a peer shard claimed this scope at a "
        "different ownership epoch (split-brain double-answer window, "
        "counted on both sides); resolved newest-epoch-wins — the "
        "older claim withholds, the newer serves. A sustained rate "
        "means a partition is not healing",
        ("pool", "slice"),
    ),
}


def host_family_rows() -> dict[str, tuple[str, str, tuple[str, ...]]]:
    """Host-context families (declared next to their builder)."""
    from tpumon.exporter.host import HOST_FAMILIES

    return HOST_FAMILIES


def distribution_family_rows() -> dict[str, tuple[str, tuple[str, ...]]]:
    """Cumulative 1 Hz utilization histograms (declared next to their
    builder, tpumon/exporter/histograms.py, so names can't drift)."""
    from tpumon.exporter.histograms import DISTRIBUTION_SOURCES

    return {
        family: (help_text, (label_key, "le"))
        for family, help_text, label_key in DISTRIBUTION_SOURCES.values()
    }


def all_family_names() -> set[str]:
    from tpumon.schema import LIBTPU_SPECS

    return (
        {s.family for s in LIBTPU_SPECS}
        | set(IDENTITY_FAMILIES)
        | set(HEALTH_FAMILIES)
        | set(ANOMALY_FAMILIES)
        | set(HOSTCORR_FAMILIES)
        | set(LIFECYCLE_FAMILIES)
        | set(ENERGY_FAMILIES)
        | set(distribution_family_rows())
        | set(SELF_FAMILIES)
        | set(FLEET_FAMILIES)
        | set(LEDGER_FAMILIES)
        | set(ANALYTICS_FAMILIES)
        | set(ACTUATE_FAMILIES)
        | set(WORKLOAD_FAMILIES)
        | set(STEP_FAMILIES)
        | set(SERVE_FAMILIES)
        | set(host_family_rows())
    )
