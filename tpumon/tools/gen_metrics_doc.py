"""Generate docs/METRICS.md from the schema — the single source of truth.

``python -m tpumon.tools.gen_metrics_doc [--check]``: writes the metrics
reference; with ``--check`` exits 1 if the committed file is stale
(used by tests so the doc can never drift from tpumon/schema.py).
"""

from __future__ import annotations

import argparse
import os
import sys

from tpumon.families import (
    ACTUATE_FAMILIES,
    ANALYTICS_FAMILIES,
    ANOMALY_FAMILIES,
    ENERGY_FAMILIES,
    FLEET_FAMILIES,
    HEALTH_FAMILIES,
    HOSTCORR_FAMILIES,
    IDENTITY_FAMILIES,
    LEDGER_FAMILIES,
    LIFECYCLE_FAMILIES,
    SELF_FAMILIES,
    SERVE_FAMILIES,
    STEP_FAMILIES,
    WORKLOAD_FAMILIES,
    distribution_family_rows,
)
from tpumon.schema import LIBTPU_SPECS

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
OUT = os.path.join(REPO, "docs", "METRICS.md")

BASE_LABELS = "`slice`, `host`, `worker`, `accelerator`"

IDENTITY = [
    (name, desc, ", ".join(f"`{l}`" for l in labels))
    for name, (desc, labels) in IDENTITY_FAMILIES.items()
]
SELF = [(name, typ, desc) for name, (typ, desc) in SELF_FAMILIES.items()]
WORKLOAD = list(WORKLOAD_FAMILIES.items())


def render() -> str:
    lines = [
        "# tpumon metrics reference",
        "",
        "Generated from `tpumon/schema.py` by `python -m tpumon.tools.gen_metrics_doc`",
        "— do not edit by hand (a test regenerates and compares).",
        "",
        f"Every `accelerator_*` sample carries the host-identity base labels: {BASE_LABELS}.",
        "",
        "## Device metrics (unified `accelerator_*` schema)",
        "",
        "One vendor-neutral family per device-library metric; the libtpu column",
        "is the source on TPU nodes, the NVML-compat backend feeds the same",
        "families on GPU nodes of a mixed pool. **Absent ≠ zero**: when no",
        "runtime is attached, the family is absent for that scrape.",
        "",
        "| Prometheus family | libtpu source | extra labels | description |",
        "|---|---|---|---|",
    ]
    for spec in LIBTPU_SPECS:
        labels = ", ".join(f"`{l}`" for l in spec.labels) or "—"
        lines.append(
            f"| `{spec.family}` | `{spec.source}` | {labels} | {spec.help} |"
        )

    lines += [
        "",
        "Percentile families carry `stat` ∈ {mean, p50, p90, p95, p999}.",
        "",
        "## Utilization distributions (cumulative 1 Hz histograms)",
        "",
        "Every poll observes the current per-chip/per-core utilization into",
        "cumulative Prometheus histograms, so the distribution of the 1 Hz",
        "series is recoverable from any scrape interval",
        "(`histogram_quantile` over `rate(..._bucket[...])`) — recovering",
        "what the gauges alias away between scrapes. Enabled by default;",
        "`TPUMON_HISTOGRAMS=0` disables.",
        "",
        "| Prometheus family | extra labels | description |",
        "|---|---|---|",
    ]
    for name, (desc, labels) in sorted(distribution_family_rows().items()):
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {label_s} | {desc} |")

    lines += [
        "",
        "## Identity & attribution",
        "",
        "| family | description | extra labels |",
        "|---|---|---|",
    ]
    for name, desc, labels in IDENTITY:
        lines.append(f"| `{name}` | {desc} | {labels or '—'} |")

    lines += [
        "",
        "## Derived device health (dcgmi `health -c` analogue)",
        "",
        "Computed by the exporter each poll from the device families above",
        "(thresholds in `tpumon/health.py`); the same verdicts back",
        "`/health/devices`, `tpumon doctor`, and `tpumon smi`.",
        "",
        "| family | description | extra labels |",
        "|---|---|---|",
    ]
    for name, (desc, labels) in HEALTH_FAMILIES.items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {desc} | {label_s} |")

    lines += [
        "",
        "## Streaming anomaly detection (`tpumon.anomaly`)",
        "",
        "Streaming detectors (EWMA z-score, CUSUM drift, link-flap burst,",
        "queue-stall pairing) fed by the 1 Hz poll loop — no extra device",
        "queries. Events carry onset/clear timestamps and a 1 Hz sample",
        "window, served via `GET /anomalies` (`?since=` replay). Enabled by",
        "default; `TPUMON_ANOMALY=0` disables, `TPUMON_ANOMALY_<FIELD>`",
        "tunes thresholds (`tpumon/anomaly/detectors.py`).",
        "",
        "| family | description | extra labels |",
        "|---|---|---|",
    ]
    for name, (desc, labels) in ANOMALY_FAMILIES.items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {desc} | {label_s} |")

    lines += [
        "",
        "## Host correlation & straggler attribution (`tpumon/hostcorr`)",
        "",
        "Non-instrumented host signals (cgroup PSI, per-pod sched delay,",
        "net/disk byte rates, page-cache pressure) sampled from",
        "procfs/cgroupfs at the same 1 Hz cadence as the device poll —",
        "zero device queries, zero workload instrumentation — and joined",
        "with each cycle's device snapshot into a per-slice straggler",
        "verdict (cause ∈ `device` / `host-cpu` / `host-mem` / `host-io` /",
        "`unknown`). Time-aligned records replay via `GET /hostcorr`",
        "(`?since=`); host_straggler/host_stall events ride `/anomalies`.",
        "Enabled by default; `TPUMON_HOSTCORR=0` disables,",
        "`TPUMON_HOSTCORR_<FIELD>` tunes thresholds",
        "(`tpumon/hostcorr/detectors.py`). On kernels without",
        "PSI/schedstat the plane reports `tpu_hostcorr_available 0` and",
        "verdicts degrade to device-only attribution.",
        "",
        "| family | type | description | extra labels |",
        "|---|---|---|---|",
    ]
    for name, (kind, desc, labels) in HOSTCORR_FAMILIES.items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {kind} | {desc} | {label_s} |")

    lines += [
        "",
        "## Workload-lifecycle robustness plane (`tpumon/lifecycle`)",
        "",
        "The exporter probes the workload harness's metrics port",
        "(`tpu_step_*` families below) once per poll cycle — zero device",
        "queries — and classifies preemption / elastic-resize /",
        "checkpoint-restore transitions from the joined",
        "step+device+membership signals. A recognized clean transition",
        "opens a suppression window: straggler/stall/regression verdicts",
        "are counted into `tpu_anomaly_suppressed_total` instead of raised,",
        "and regressions persisting past the window fire normally.",
        "Time-aligned records replay via `GET /lifecycle` (`?since=`);",
        "step_regression / collective_wait / lifecycle events ride",
        "`/anomalies`. Enabled by default; `TPUMON_LIFECYCLE=0` disables,",
        "`TPUMON_LIFECYCLE_STEP_URLS` names the workload feeds,",
        "`TPUMON_LIFECYCLE_<FIELD>` tunes thresholds",
        "(`tpumon/lifecycle/detectors.py`).",
        "",
        "| family | type | description | extra labels |",
        "|---|---|---|---|",
    ]
    for name, (kind, desc, labels) in LIFECYCLE_FAMILIES.items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {kind} | {desc} | {label_s} |")

    lines += [
        "",
        "## Energy & cost plane (`tpumon/energy`)",
        "",
        "Per-chip power/energy with explicit provenance: `source=measured`",
        "when the device library reported watts (`accelerator_power_watts`),",
        "`source=modeled` when estimated from duty cycle × the accelerator",
        "generation's TDP envelope, HBM-activity adjusted",
        "(`tpumon/energy/model.py` — table override via",
        "`TPUMON_ENERGY_TDP_W`). Joules counters integrate at poll cadence",
        "with gap honesty (`TPUMON_ENERGY_MAX_GAP_S`), pod energy rides the",
        "`accelerator_pod_info` attribution join, and the step-efficiency",
        "families join the lifecycle plane's `tpu_step_*` feeds. The",
        "`efficiency_regression` detector baselines tokens/joule per",
        "workload preset and rides `/anomalies`",
        "(lifecycle-suppression aware). Enabled by default;",
        "`TPUMON_ENERGY=0` disables, `TPUMON_ENERGY_<FIELD>` tunes",
        "(incl. `TPUMON_ENERGY_DOLLARS_PER_KWH` for the cost family).",
        "",
        "| family | type | description | extra labels |",
        "|---|---|---|---|",
    ]
    for name, (kind, desc, labels) in ENERGY_FAMILIES.items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {kind} | {desc} | {label_s} |")

    from tpumon.families import host_family_rows

    lines += [
        "",
        "## Host context (accelerator-diagnosis companion signals)",
        "",
        "psutil-backed; absent when psutil is unavailable or",
        "`TPUMON_HOST_METRICS=0`. Same base labels as the device families so",
        "one PromQL join correlates host and chip symptoms.",
        "",
        "| family | type | description | extra labels |",
        "|---|---|---|---|",
    ]
    for name, (kind, desc, labels) in host_family_rows().items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {kind} | {desc} | {label_s} |")

    lines += [
        "",
        "## Exporter self-telemetry",
        "",
        "| family | type | description |",
        "|---|---|---|",
    ]
    for name, typ, desc in SELF:
        lines.append(f"| `{name}` | {typ} | {desc} |")

    lines += [
        "",
        "## Fleet aggregation tier (`tpumon/fleet`, aggregator `/metrics`)",
        "",
        "Pre-aggregated node→slice→pool→fleet rollups served by the",
        "shardable aggregator (`python -m tpumon.fleet`) — fleet dashboards",
        "and alerts query this tier, not the DaemonSets, and per-node",
        "series are never re-exported through it. Rollup families carry a",
        "`scope` label (`slice` / `pool` / `fleet`; `pool` is the",
        "accelerator-type label, `slice` the slice label — empty at wider",
        "scopes). Configured via `TPUMON_FLEET_*` (see",
        "docs/OPERATIONS.md).",
        "",
        "| family | type | description | labels |",
        "|---|---|---|---|",
    ]
    for name, (kind, desc, labels) in FLEET_FAMILIES.items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {kind} | {desc} | {label_s} |")

    lines += [
        "",
        "## Fleet efficiency ledger (`tpumon/ledger`, aggregator `/metrics` + `GET /ledger`)",
        "",
        "Long-horizon tiered storage (1 s → 10 s → 5 min) over the curated",
        "rollup family set plus per-job goodput chip-second accounting,",
        "inside the aggregator. `tpu_fleet_goodput_chip_seconds_total`",
        "conserves by construction: per job, buckets sum to observed",
        "wall-clock × chips, with invisible windows (stale/dark nodes,",
        "aggregator restarts) landing in `bucket=\"unaccounted\"` — never",
        "silently in idle. Range queries over any curated family at any",
        "scope are served by `GET /ledger` from the correct tier (see",
        "docs/OPERATIONS.md for knobs, remote-write setup, and the",
        "goodput triage runbook).",
        "",
        "| family | type | description | labels |",
        "|---|---|---|---|",
    ]
    for name, (kind, desc, labels) in LEDGER_FAMILIES.items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {kind} | {desc} | {label_s} |")

    lines += [
        "",
        "## Ledger analytics & capacity forecasting (`tpumon/ledger/analytics.py` + `forecast.py`)",
        "",
        "The ledger's read side for capacity planners: top-k waste",
        "ranking, per-workload-class efficiency percentiles, and",
        "linear-trend saturation forecasts, all computed off the tiered",
        "fold (raw per-node series never cross the surface) and served",
        "both on `GET /ledger` (`view=waste|percentiles|forecast`,",
        "`whatif=dollars_per_kwh:<v>`) and as the exposition families",
        "below. Forecast families are honest by construction: a pool",
        "below the minimum-history gate emits",
        "`tpu_fleet_forecast_insufficient_history=1` and NO",
        "`days_to_saturation` — absent, never a fabricated date (see",
        "docs/OPERATIONS.md for the capacity-planning runbook and the",
        "query grammar).",
        "",
        "| family | type | description | labels |",
        "|---|---|---|---|",
    ]
    for name, (kind, desc, labels) in ANALYTICS_FAMILIES.items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {kind} | {desc} | {label_s} |")

    lines += [
        "",
        "## Actuation plane (`tpumon/actuate`, aggregator `/metrics` + External Metrics API)",
        "",
        "The closed-loop tier: per-slice serving rollups, the",
        "placement-hint engine (headroom scores with band hysteresis,",
        "served as annotation patches on `GET /hints`), and the",
        "Kubernetes External Metrics API",
        "(`/apis/external.metrics.k8s.io/v1beta1/...`) answered straight",
        "from the collect cycle's read model — an HPA query touches no",
        "raw per-node series. Stale rollups are served with",
        "`metricLabels[\"tpumon_stale\"]=\"true\"` and the producing",
        "cycle's timestamp, never re-stamped as current. Enabled by",
        "default; `TPUMON_FLEET_ACTUATE=0` disables (see",
        "docs/OPERATIONS.md for the HPA wiring runbook).",
        "",
        "| family | type | description | labels |",
        "|---|---|---|---|",
    ]
    for name, (kind, desc, labels) in ACTUATE_FAMILIES.items():
        label_s = ", ".join(f"`{l}`" for l in labels) or "—"
        lines.append(f"| `{name}` | {kind} | {desc} | {label_s} |")

    lines += [
        "",
        "## Workload-side counters (harness `--metrics-port`)",
        "",
        "| family | description |",
        "|---|---|",
    ]
    for name, desc in WORKLOAD:
        lines.append(f"| `{name}` | {desc} |")

    lines += [
        "",
        "## Inference serving telemetry (harness `--serve`, `tpu_serve_*`)",
        "",
        "Exported by the workload harness's serving preset",
        "(`tpumon/workload/serve.py`; `--serve --serve-slo-ms <ms>`",
        "alongside `--metrics-port`) and lifted by the exporter's",
        "lifecycle plane into `tpu_lifecycle_serve_*`, from which the",
        "fleet tier rolls up `tpu_fleet_serve_*` per slice — the full",
        "path an HPA scale signal travels. Families are absent until",
        "the first stats window completes (absent ≠ zero).",
        "",
        "| family | description |",
        "|---|---|",
    ]
    for name, desc in SERVE_FAMILIES.items():
        lines.append(f"| `{name}` | {desc} |")

    lines += [
        "",
        "## Per-step phase telemetry (harness `--metrics-port`, `tpu_step_*`)",
        "",
        "Exported by the workload harness itself",
        "(`tpumon/workload/stats.py`) and consumed by the exporter's",
        "lifecycle plane — the monitor↔trainer loop. Phase timings need",
        "`--phase-stats` (one instrumented step per stats window);",
        "`tpu_step_terminating` flips inside the SIGTERM grace window",
        "(`TPUMON_STEP_TERM_GRACE_S`).",
        "",
        "| family | description |",
        "|---|---|",
    ]
    for name, desc in STEP_FAMILIES.items():
        lines.append(f"| `{name}` | {desc} |")
    lines.append("")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--check", action="store_true")
    args = parser.parse_args(argv)
    content = render()
    if args.check:
        try:
            with open(OUT, encoding="utf-8") as fh:
                current = fh.read()
        except OSError:
            current = ""
        if current != content:
            print("docs/METRICS.md is stale; regenerate with "
                  "python -m tpumon.tools.gen_metrics_doc", file=sys.stderr)
            return 1
        return 0
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w", encoding="utf-8") as fh:
        fh.write(content)
    print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
