"""Sync the canonical dashboards into the deploy/ and Helm-chart copies.

``dashboards/`` is the single authored source (SURVEY.md §1 L6). Kustomize's
configMapGenerator and Helm's ``.Files.Glob`` each require the JSON bodies
inside their own tree and neither follows symlinks out of it, so the bundled
copies are *generated*, not hand-synced:

    python -m tpumon.tools.sync_dashboards          # regenerate copies
    python -m tpumon.tools.sync_dashboards --check  # exit 1 if any drifted

The --check mode backs tests/test_helm_chart.py's identity test, so a stale
copy fails CI with the regeneration command in the message.
"""

from __future__ import annotations

import argparse
import filecmp
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
CANON = os.path.join(REPO, "dashboards")
COPIES = (
    os.path.join(REPO, "deploy", "dashboards"),
    os.path.join(REPO, "charts", "tpumon", "dashboards"),
)


def canonical_files() -> list[str]:
    return sorted(
        n for n in os.listdir(CANON) if n.endswith(".json")
    )


def check() -> list[str]:
    """Return human-readable drift findings (empty = in sync)."""
    problems = []
    names = canonical_files()
    for copy in COPIES:
        have = sorted(
            n for n in os.listdir(copy) if n.endswith(".json")
        ) if os.path.isdir(copy) else []
        for name in names:
            src = os.path.join(CANON, name)
            dst = os.path.join(copy, name)
            if not os.path.exists(dst):
                problems.append(f"{dst}: missing")
            elif not filecmp.cmp(src, dst, shallow=False):
                problems.append(f"{dst}: differs from canonical")
        for name in set(have) - set(names):
            problems.append(f"{os.path.join(copy, name)}: orphan (no canonical source)")
    return problems


def sync() -> None:
    names = canonical_files()
    for copy in COPIES:
        os.makedirs(copy, exist_ok=True)
        for name in names:
            shutil.copyfile(os.path.join(CANON, name), os.path.join(copy, name))
        for name in os.listdir(copy):
            if name.endswith(".json") and name not in names:
                os.remove(os.path.join(copy, name))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true")
    args = parser.parse_args(argv)
    if args.check:
        problems = check()
        if problems:
            for p in problems:
                print(p, file=sys.stderr)
            print(
                "regenerate with: python -m tpumon.tools.sync_dashboards",
                file=sys.stderr,
            )
            return 1
        return 0
    sync()
    print(f"synced {len(canonical_files())} dashboards into {len(COPIES)} copies")
    return 0


if __name__ == "__main__":
    sys.exit(main())
