"""Sync the canonical dashboards into the deploy/ and Helm-chart copies.

``dashboards/`` is the single authored source (SURVEY.md §1 L6). Kustomize's
configMapGenerator and Helm's ``.Files.Glob`` each require the JSON bodies
inside their own tree and neither follows symlinks out of it, so the bundled
copies are *generated*, not hand-synced:

    python -m tpumon.tools.sync_dashboards          # regenerate copies
    python -m tpumon.tools.sync_dashboards --check  # exit 1 if any drifted

The same applies to the alert rules: ``deploy/prometheus-rules.yaml`` is the
single authored source, and the Helm chart's PrometheusRule template is
generated from its ``spec:`` block verbatim (wrapped in release metadata and
a ``prometheusRules.enabled`` gate), so chart installs alert identically to
kustomize installs.

The --check mode backs tests/test_helm_chart.py's identity test, so a stale
copy fails CI with the regeneration command in the message.
"""

from __future__ import annotations

import argparse
import filecmp
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
CANON = os.path.join(REPO, "dashboards")
COPIES = (
    os.path.join(REPO, "deploy", "dashboards"),
    os.path.join(REPO, "charts", "tpumon", "dashboards"),
)

RULES_SRC = os.path.join(REPO, "deploy", "prometheus-rules.yaml")
RULES_TEMPLATE = os.path.join(
    REPO, "charts", "tpumon", "templates", "prometheusrule.yaml"
)


def render_rules_template() -> str:
    """The chart's PrometheusRule: deploy/prometheus-rules.yaml's spec
    block verbatim under Helm-templated metadata."""
    with open(RULES_SRC, encoding="utf-8") as fh:
        text = fh.read()
    marker = "\nspec:\n"
    at = text.index(marker)
    spec_body = text[at + len(marker):]
    # The rules' own {{ $labels.x }} is PROMETHEUS templating; escape it
    # so Helm renders the braces literally instead of erroring on $labels.
    spec_body = spec_body.replace("{{", "\x00L").replace("}}", "\x00R")
    spec_body = spec_body.replace("\x00L", '{{ "{{" }}').replace(
        "\x00R", '{{ "}}" }}'
    )
    return (
        "{{- if .Values.prometheusRules.enabled }}\n"
        "# GENERATED from deploy/prometheus-rules.yaml — do not edit.\n"
        "# Regenerate with: python -m tpumon.tools.sync_dashboards\n"
        "apiVersion: monitoring.coreos.com/v1\n"
        "kind: PrometheusRule\n"
        "metadata:\n"
        "  name: {{ include \"tpumon.name\" . }}\n"
        "  labels:\n"
        "    {{- include \"tpumon.labels\" . | nindent 4 }}\n"
        "spec:\n"
        + spec_body
        + "{{- end }}\n"
    )


def canonical_files() -> list[str]:
    return sorted(
        n for n in os.listdir(CANON) if n.endswith(".json")
    )


def check() -> list[str]:
    """Return human-readable drift findings (empty = in sync)."""
    problems = []
    names = canonical_files()
    for copy in COPIES:
        have = sorted(
            n for n in os.listdir(copy) if n.endswith(".json")
        ) if os.path.isdir(copy) else []
        for name in names:
            src = os.path.join(CANON, name)
            dst = os.path.join(copy, name)
            if not os.path.exists(dst):
                problems.append(f"{dst}: missing")
            elif not filecmp.cmp(src, dst, shallow=False):
                problems.append(f"{dst}: differs from canonical")
        for name in set(have) - set(names):
            problems.append(f"{os.path.join(copy, name)}: orphan (no canonical source)")
    want = render_rules_template()
    if not os.path.exists(RULES_TEMPLATE):
        problems.append(f"{RULES_TEMPLATE}: missing")
    else:
        with open(RULES_TEMPLATE, encoding="utf-8") as fh:
            if fh.read() != want:
                problems.append(
                    f"{RULES_TEMPLATE}: differs from deploy/prometheus-rules.yaml"
                )
    return problems


def sync() -> None:
    names = canonical_files()
    for copy in COPIES:
        os.makedirs(copy, exist_ok=True)
        for name in names:
            shutil.copyfile(os.path.join(CANON, name), os.path.join(copy, name))
        for name in os.listdir(copy):
            if name.endswith(".json") and name not in names:
                os.remove(os.path.join(copy, name))
    with open(RULES_TEMPLATE, "w", encoding="utf-8") as fh:
        fh.write(render_rules_template())


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--check", action="store_true")
    args = parser.parse_args(argv)
    if args.check:
        problems = check()
        if problems:
            for p in problems:
                print(p, file=sys.stderr)
            print(
                "regenerate with: python -m tpumon.tools.sync_dashboards",
                file=sys.stderr,
            )
            return 1
        return 0
    sync()
    print(f"synced {len(canonical_files())} dashboards into {len(COPIES)} copies")
    return 0


if __name__ == "__main__":
    sys.exit(main())
