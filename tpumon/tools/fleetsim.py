"""N-port fleet simulator: realistic exporter endpoints, one process.

``tools/soak.py --fleet`` needs a 64-node fleet on a 2-core CI runner.
Sixty-four real exporter interpreters oversubscribe such a box so badly
that every measurement collapses into scheduler noise (measured: child
response p50 ~50 ms from pure process-wakeup latency) — so the fleet is
simulated instead: ONE process listens on N ports, each serving a
distinct node identity (slice/host labels rewritten per port) over a
genuine fake-backend exposition page that advances every
``node_interval`` and carries a fresh
``collector_last_poll_timestamp_seconds``. The aggregator under test
does exactly the work it would against real nodes — N fetches/s, N
parses/s, full rollup hierarchy — while the simulation costs a few
percent of one core.

Fault scripting over stdin (the fleet-chaos vocabulary,
``soak.py --fleet-chaos``):

- ``kill N`` — permanent node death: half the victims CLOSE their
  listeners (connection-refused path), half FREEZE — the listener keeps
  answering but the page (and its poll timestamp) stops advancing, the
  zombie-exporter shape the tier's data-age staleness exists to catch.
- ``partition N`` — network partition: connections are accepted then
  dropped without a byte while the victims' pages KEEP advancing (the
  nodes are healthy, the path isn't); ``heal`` restores them with fresh
  data — the mass-return shape adaptive cadence must absorb storm-free.
- ``slow N MS`` — the victims answer after an MS-millisecond stall
  (congested path / overloaded node; exercises fetch deadlines).
- ``corrupt N`` — the victims alternate hostile payloads: a snapshot
  frame whose varint length prefix claims a terabyte (the
  pre-allocation reject path) and undecodable binary garbage.
- ``flap N`` — membership flapping: the victims toggle between
  partitioned and healthy on every page tick (the churn-debounce and
  breaker-thrash shape).
- ``churn F`` — set the per-tick content-churn fraction (``--churn``):
  only F of the live nodes take new backend state each tick, the rest
  heartbeat with unchanged content — the mostly-idle fleet shape the
  delta fan-in protocol is benchmarked against.
- ``serve RPS QUEUE TTFT_MS SLO [BATCH]`` — every live node's page
  carries ``tpu_lifecycle_serve_*`` at these values from the next tick
  (``serve off`` clears) — the inference-scenario dial the actuation
  tier's External Metrics adapter is drilled against
  (``soak.py --serve-burst``).
- ``skew N S`` — wall-clock skew: the first N live nodes stamp their
  poll timestamp S seconds off true (S may be negative). Future skew
  exercises the aggregator's never-fresher-than-fetch clamp; past skew
  the 1 h staleness cap — either way the node must read STALE-FLAGGED,
  never time-travel (``soak.py --chaos-search``).
- ``creep N MS [RAMP_S]`` — slow-creep latency ramp: the first N live
  nodes' response delay ramps linearly from 0 to MS milliseconds over
  RAMP_S seconds (default 10) — the gradually-congesting-path shape
  that a fixed ``slow`` threshold drill never exercises.
- ``revive N`` — undo ``kill`` for the first N dead nodes: frozen
  pages resume advancing and closed listeners rebind on their original
  port — the node-replacement / reboot shape, and what makes long
  random fault schedules searchable (kills stop being absorbing).
- ``faults SPEC`` — wrap the shared fake backend in the resilience
  plane's :class:`FaultInjectingBackend` (``faults off`` unwraps):
  FaultSpec ``error_rate``/``latency_ms``/``hang_every``/``garbage_rate``
  degrade the CONTENT every node republishes — the whole-fleet
  telemetry-quality fault axis, orthogonal to transport faults.
- ``heal`` — clear partition/slow/creep/corrupt/flap/skew/faults
  (killed nodes stay dead; ``revive`` is the explicit undo).

Exposition: each node serves text (default), the compact snapshot
frame, or sequence-numbered delta frames (conditional GET via the
X-Tpumon-Delta-* headers) through the SAME negotiate()/DeltaHistory
code the real exporter uses — the sim cannot drift from the protocol.

Run standalone:
    python -m tpumon.tools.fleetsim --nodes 64
(prints ``PORTS p1 p2 ...`` when ready, then serves until EOF/``quit``.)
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

#: Nodes per simulated slice (8 hosts ≈ a v4-64 pod's host count).
SLICE_SIZE = 8

#: Delta-protocol header names (mirrors tpumon/exporter/encodings.py;
#: literal here so the handler class needs no per-request import).
_DELTA_SEQ_HEADER = "X-Tpumon-Delta-Seq"
_DELTA_BASE_HEADER = "X-Tpumon-Delta-Base"


def _corrupt_payload(serial: int) -> bytes:
    """Rotating hostile payloads for ``corrupt`` nodes: a snapshot
    frame whose length prefix claims ~1 TB (the aggregator must reject
    it BEFORE allocating — tpu_fleet_ingest_rejects_total{bad_frame}),
    a DELTA frame with the same terabyte length prefix (the delta
    decode path owns the identical pre-allocation cap), and undecodable
    binary garbage (…{undecodable})."""
    from tpumon.backends.reflection import _encode_varint
    from tpumon.exporter.encodings import DELTA_MAGIC, SNAPSHOT_MAGIC

    variant = serial % 3
    if variant == 0:
        return SNAPSHOT_MAGIC + _encode_varint(1 << 40) + b"\x00" * 64
    if variant == 1:
        return DELTA_MAGIC + _encode_varint(1 << 40) + b"\x00" * 64
    return b"\xff\xfe" * 128


class FleetSim:
    """The page store + N listeners. Thread model: a ticker thread
    rewrites pages; handler threads read them under the lock; stdin
    control runs on the caller's thread via :meth:`kill`/:meth:`close`."""

    def __init__(
        self, nodes: int, topology: str = "v4-8",
        node_interval: float = 1.0, addr: str = "127.0.0.1",
        churn: float = 1.0,
    ) -> None:
        from tpumon.backends.fake import FakeTpuBackend
        from tpumon.config import Config
        from tpumon.exporter.encodings import DeltaHistory

        self.nodes = nodes
        self.node_interval = node_interval
        self._backend = FakeTpuBackend.preset(topology)
        #: The unwrapped backend, kept so ``faults off`` can restore it.
        self._base_backend = self._backend
        self._cfg = Config()
        self._addr = addr
        base = self._backend.topology().base_labels()
        self._orig_slice = f'slice="{base.get("slice", "")}"'
        self._orig_host = f'host="{base.get("host", "")}"'
        self._lock = threading.Lock()
        self._pages: list[bytes] = [b""] * nodes  # guarded-by: self._lock
        self._frozen: set[int] = set()  # guarded-by: self._lock
        self._partitioned: set[int] = set()  # guarded-by: self._lock
        self._slow: dict[int, float] = {}  # guarded-by: self._lock
        #: node -> (ramp start time, ramp seconds, max delay seconds):
        #: the slow-creep latency ramp (``creep``).
        self._creep: dict[int, tuple[float, float, float]] = {}  # guarded-by: self._lock
        #: node -> wall-clock skew seconds applied to the node's poll
        #: timestamp on BOTH encodings (``skew``; negative = the past).
        self._skew: dict[int, float] = {}  # guarded-by: self._lock
        self._corrupt: set[int] = set()  # guarded-by: self._lock
        self._flap: set[int] = set()  # guarded-by: self._lock
        self._flap_phase = False  # guarded-by: self._lock
        self._corrupt_serial = 0  # guarded-by: self._lock
        #: Fraction of live nodes whose CONTENT advances per tick (the
        #: churn-rate dial the delta-fan-in soak A/Bs against). Idle
        #: nodes still refresh their poll timestamp every tick — the
        #: heartbeat — so they read fresh, just unchanged.
        self._churn = max(0.0, min(1.0, churn))  # guarded-by: self._lock
        #: Fleet-wide per-node serving profile (None = serve lines off);
        #: applied to every live node's page at the next tick.
        self._serve: dict | None = None  # guarded-by: self._lock
        self._churn_cursor = 0  # ticker thread only
        self._tick_no = 0  # ticker thread only
        #: Per-node identity-rewritten page template (no timestamp
        #: stamp); idle nodes reuse theirs across ticks. Ticker only.
        self._templates: dict[int, str] = {}
        #: Per-node rollup content (snapshot minus last_poll_ts);
        #: ticker thread only.
        self._contents: dict[int, dict] = {}
        #: Per-node delta-protocol server state (seq history + frame
        #: cache + epoch) — the same class the real exporter serves
        #: from, so the sim's wire behavior cannot drift from the
        #: protocol. Thread-safe internally.
        self._delta = [DeltaHistory() for _ in range(nodes)]
        self._stop = threading.Event()
        self.tick()  # pages exist before the first request can land

        sim = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            node_index = 0  # overridden per server subclass below

            def do_GET(self) -> None:
                if self.path != "/metrics":
                    self.send_error(404)
                    return
                i = self.node_index
                with sim._lock:
                    body = sim._pages[i]
                    partitioned = i in sim._partitioned
                    delay = sim._slow.get(i, 0.0)
                    creep = sim._creep.get(i)
                    corrupt = i in sim._corrupt
                    if corrupt:
                        sim._corrupt_serial += 1
                        serial = sim._corrupt_serial
                if creep is not None:
                    t0, ramp_s, max_s = creep
                    frac = (
                        min(1.0, (time.time() - t0) / ramp_s)
                        if ramp_s > 0 else 1.0
                    )
                    delay = max(delay, frac * max_s)
                if partitioned:
                    # Accepted, then dropped without a byte: the client
                    # sees a torn read, not a refused connect — the
                    # half-open shape a real partition produces.
                    self.close_connection = True
                    return
                if delay:
                    time.sleep(delay)
                if corrupt:
                    body = _corrupt_payload(serial)
                    self._respond(
                        body, "text/plain; version=0.0.4; charset=utf-8"
                    )
                    return
                payload, content_type, seq_header = sim._negotiated(
                    i, self.headers.get("Accept", ""),
                    self.headers.get(_DELTA_BASE_HEADER, ""),
                )
                self._respond(
                    body if payload is None else payload,
                    content_type, seq_header,
                )

            def _respond(
                self, body: bytes, content_type: str,
                seq_header: str | None = None,
            ) -> None:
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                if seq_header is not None:
                    self.send_header(_DELTA_SEQ_HEADER, seq_header)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass

        self._servers: list[ThreadingHTTPServer] = []
        #: Per-node handler classes, kept so ``revive`` can rebind a
        #: closed listener on its original port.
        self._handlers: list[type] = []
        self.ports: list[int] = []
        for i in range(nodes):
            handler = type("_H%d" % i, (_Handler,), {"node_index": i})
            server = ThreadingHTTPServer((addr, 0), handler)
            server.daemon_threads = True
            self._servers.append(server)
            self._handlers.append(handler)
            self.ports.append(server.server_address[1])
            threading.Thread(
                target=server.serve_forever, kwargs={"poll_interval": 0.5},
                name=f"fleetsim-{i}", daemon=True,
            ).start()
        self._ticker = threading.Thread(
            target=self._run, name="fleetsim-ticker", daemon=True
        )
        self._ticker.start()

    # -- page generation ---------------------------------------------------

    def tick(self) -> None:
        """Advance the fake backend one step, rewrite the CHURNING
        nodes' content, and refresh every live node's poll timestamp.

        At churn < 1.0 only a rotating fraction of live nodes takes the
        new backend state; the rest keep their previous content and
        just heartbeat — which is what a mostly-idle production fleet
        looks like to the fan-in tier, and exactly the regime where the
        delta protocol's bytes/node collapses to the heartbeat frame."""
        from tpumon._native import render_families
        from tpumon.exporter.collector import build_families
        from tpumon.exporter.encodings import encode_snapshot
        from tpumon.fleet.ingest import node_snapshot_from_text

        self._backend.advance()
        families, _stats = build_families(self._backend, self._cfg)
        template = render_families(tuple(families)).decode()
        now = time.time()
        with self._lock:
            frozen = set(self._frozen)
            churn = self._churn
            serve = dict(self._serve) if self._serve else None
            skew = dict(self._skew)
        serve_lines = ""
        if serve is not None:
            # The serving join rides the stamp (per-tick, every live
            # node) on BOTH encodings: text lines the ingest parser
            # lifts into snap["serve"], and the snapshot/delta path's
            # snap["serve"] below.
            serve_lines = "".join(
                f"# TYPE tpu_lifecycle_serve_{key} gauge\n"
                f"tpu_lifecycle_serve_{key} {value:g}\n"
                for key, value in (
                    ("requests_per_second", serve["requests_per_second"]),
                    ("queue_depth", serve["queue_depth"]),
                    ("ttft_seconds", serve["ttft_seconds"]),
                    ("slo_attainment_ratio", serve["slo_attainment_ratio"]),
                    ("batch_size", serve["batch_size"]),
                )
            )

        def _stamp(ts: float) -> str:
            return (
                "# TYPE collector_last_poll_timestamp_seconds gauge\n"
                f"collector_last_poll_timestamp_seconds {ts}\n"
                + serve_lines
            )

        stamp = _stamp(now)
        self._tick_no += 1
        live = [i for i in range(self.nodes) if i not in frozen]
        churners: set[int] = set()
        if live:
            k = int(round(churn * len(live)))
            # Rotate the churn window so partial churn spreads across
            # the fleet instead of re-mutating the same nodes forever.
            for j in range(k):
                churners.add(live[(self._churn_cursor + j) % len(live)])
            self._churn_cursor = (self._churn_cursor + k) % len(live)
        # One parse of the shared template per tick; per-node content is
        # the parse with its identity patched (equivalent to parsing the
        # node's own page — slice/host only surface via accelerator_info).
        base_content: dict | None = None
        pages = {}
        for i in live:
            if i in churners or i not in self._templates:
                self._templates[i] = template.replace(
                    self._orig_slice, f'slice="sim-{i // SLICE_SIZE}"'
                ).replace(self._orig_host, f'host="node-{i}"')
                if base_content is None:
                    base_content = node_snapshot_from_text(template)
                content = dict(base_content)
                content["identity"] = {
                    **base_content.get("identity", {}),
                    "slice": f"sim-{i // SLICE_SIZE}",
                    "host": f"node-{i}",
                }
                self._contents[i] = content
            # Skewed nodes stamp their own clock on BOTH encodings —
            # the skew rides the data timestamp, never the transport.
            node_now = now + skew.get(i, 0.0)
            pages[i] = (
                self._templates[i]
                + (stamp if i not in skew else _stamp(node_now))
            ).encode()
            snap = {**self._contents[i], "last_poll_ts": node_now}
            if serve is not None:
                snap["serve"] = serve
            self._delta[i].record(
                (self._tick_no,), snap, encode_snapshot(snap)
            )
        with self._lock:
            for i, body in pages.items():
                self._pages[i] = body
            if self._flap:
                # Membership flapping: flap nodes toggle between
                # partitioned and healthy every page tick.
                self._flap_phase = not self._flap_phase
                if self._flap_phase:
                    self._partitioned |= self._flap
                else:
                    self._partitioned -= self._flap

    def _negotiated(
        self, i: int, accept: str, base_raw: str,
    ) -> tuple[bytes | None, str, str | None]:
        """(payload, content type, seq header) for one request: delta /
        snapshot consumers get protocol frames from the node's
        DeltaHistory; everyone else gets ``(None, text type, None)`` —
        serve the text page. A frozen node's history stays frozen, so
        its delta consumers receive empty heartbeat-less patches whose
        applied snapshot AGES — the zombie shape, honest on every
        encoding."""
        from tpumon.exporter.encodings import (
            CONTENT_TYPES,
            FORMAT_DELTA,
            FORMAT_SNAPSHOT,
            FORMAT_TEXT,
            negotiate,
        )

        text_type = CONTENT_TYPES[FORMAT_TEXT]
        fmt = negotiate(
            accept, (FORMAT_TEXT, FORMAT_SNAPSHOT, FORMAT_DELTA)
        )
        if fmt not in (FORMAT_DELTA, FORMAT_SNAPSHOT):
            return None, text_type, None
        hist = self._delta[i]
        base = None
        if fmt == FORMAT_DELTA and base_raw:
            epoch_s, _, seq_s = base_raw.partition(":")
            try:
                if int(epoch_s) == hist.epoch:
                    base = int(seq_s)
            except ValueError:
                base = None
        out = hist.frame_from(base if fmt == FORMAT_DELTA else None)
        if out is None:
            return None, text_type, None  # pre-first-tick race
        payload, seq, kind = out
        return payload, CONTENT_TYPES[kind], f"{hist.epoch}:{seq}"

    def set_churn(self, fraction: float) -> list[str]:
        """Set the per-tick content-churn fraction (0.0-1.0)."""
        with self._lock:
            self._churn = max(0.0, min(1.0, fraction))
            value = self._churn
        return [f"churn set to {value:g}"]

    def serve_profile(self, spec: str) -> list[str]:
        """Set (or clear with ``off``) the fleet-wide per-node serving
        profile: ``RPS QUEUE TTFT_MS SLO [BATCH]``. Every live node's
        page carries the matching ``tpu_lifecycle_serve_*`` gauges from
        the next tick, so the aggregator's actuation plane sees a
        uniform inference workload whose intensity this dial controls
        mid-run (the ``--serve-burst`` traffic spike)."""
        if spec.strip() == "off":
            with self._lock:
                self._serve = None
            return ["serve telemetry off"]
        parts = spec.split()
        if len(parts) not in (4, 5):
            raise ValueError("serve wants RPS QUEUE TTFT_MS SLO [BATCH]")
        rps, queue, ttft_ms, slo = (float(p) for p in parts[:4])
        batch = float(parts[4]) if len(parts) == 5 else 32.0
        profile = {
            "requests_per_second": rps,
            "queue_depth": queue,
            "ttft_seconds": ttft_ms / 1e3,
            "slo_attainment_ratio": max(0.0, min(1.0, slo)),
            "batch_size": batch,
        }
        with self._lock:
            self._serve = profile
        return [
            f"serve rps={rps:g} queue={queue:g} ttft={ttft_ms:g}ms "
            f"slo={profile['slo_attainment_ratio']:g} batch={batch:g}"
        ]

    def _run(self) -> None:
        while not self._stop.wait(self.node_interval):
            self.tick()

    # -- node death --------------------------------------------------------

    def kill(self, n: int) -> list[str]:
        """Kill the first ``n`` live nodes. Every victim's page (and
        its poll timestamp) freezes — dead nodes produce no new data,
        however they die. Odd victims additionally close their
        listener (new connections refused); even ones keep answering
        with the frozen page — the zombie-exporter shape. Established
        keep-alive connections are untouched either way, exactly like a
        real half-dead node: the aggregator must detect death from
        DATA age, not transport failures."""
        out = []
        with self._lock:
            live = [i for i in range(self.nodes) if i not in self._frozen]
        for k, i in enumerate(live[:n]):
            with self._lock:
                self._frozen.add(i)
            if k % 2 == 0:
                out.append(f"froze node-{i} (zombie page)")
            else:
                server, self._servers[i] = self._servers[i], None
                if server is not None:
                    server.shutdown()
                    server.server_close()
                out.append(f"closed node-{i} (listener down, page frozen)")
        return out

    def _live(self) -> list[int]:
        with self._lock:
            return [i for i in range(self.nodes) if i not in self._frozen]

    def partition(self, n: int) -> list[str]:
        """Partition the first ``n`` live nodes: connections accepted
        then dropped, pages still advancing (healthy node, dead path)."""
        victims = self._live()[:n]
        with self._lock:
            self._partitioned.update(victims)
        return [f"partitioned node-{i}" for i in victims]

    def slow(self, n: int, delay_s: float) -> list[str]:
        """The first ``n`` live nodes answer after ``delay_s``."""
        victims = self._live()[:n]
        with self._lock:
            for i in victims:
                self._slow[i] = delay_s
        return [f"slowed node-{i} to {delay_s:g}s" for i in victims]

    def skew(self, n: int, skew_s: float) -> list[str]:
        """The first ``n`` live nodes stamp their poll timestamp
        ``skew_s`` seconds off true from the next tick (negative =
        stuck in the past). The transport stays healthy: only the DATA
        clock lies — the NTP-drift / stepped-clock shape the
        aggregator's skew clamp must stale-flag, never trust."""
        victims = self._live()[:n]
        with self._lock:
            for i in victims:
                self._skew[i] = skew_s
        return [f"skewed node-{i} by {skew_s:+g}s" for i in victims]

    def creep(
        self, n: int, max_delay_s: float, ramp_s: float = 10.0
    ) -> list[str]:
        """The first ``n`` live nodes' response delay ramps linearly
        from 0 to ``max_delay_s`` over ``ramp_s`` seconds."""
        victims = self._live()[:n]
        t0 = time.time()
        with self._lock:
            for i in victims:
                self._creep[i] = (t0, max(0.0, ramp_s), max_delay_s)
        return [
            f"creeping node-{i} to {max_delay_s:g}s over {ramp_s:g}s"
            for i in victims
        ]

    def revive(self, n: int) -> list[str]:
        """Undo ``kill`` for the first ``n`` dead nodes: the page
        resumes advancing at the next tick and a closed listener
        rebinds on its ORIGINAL port (the aggregator's target list
        never changes — a replaced node comes back at the same
        address, like a restarted pod behind a stable service)."""
        with self._lock:
            dead = sorted(self._frozen)[:n]
            self._frozen.difference_update(dead)
        out = []
        for i in dead:
            if self._servers[i] is not None:
                out.append(f"revived node-{i} (page thaws)")
                continue
            try:
                server = ThreadingHTTPServer(
                    (self._addr, self.ports[i]), self._handlers[i]
                )
            except OSError as exc:
                # Port still in TIME_WAIT against us or stolen: the
                # node stays connection-refused but its page thaws —
                # report honestly so schedules can tell the difference.
                out.append(f"revive node-{i} rebind failed: {exc}")
                continue
            server.daemon_threads = True
            self._servers[i] = server
            threading.Thread(
                target=server.serve_forever, kwargs={"poll_interval": 0.5},
                name=f"fleetsim-{i}", daemon=True,
            ).start()
            out.append(f"revived node-{i} (listener rebound)")
        return out or ["no dead nodes to revive"]

    def faults(self, spec: str) -> list[str]:
        """Wrap the shared fake backend in FaultInjectingBackend with
        the given spec (``off`` restores the clean backend). Content
        degradation is fleet-wide: every node republishes whatever the
        faulted backend produced that tick. ``hang_every`` stalls the
        ticker itself — full-fleet staleness, by design."""
        from tpumon.resilience.faults import FaultInjectingBackend, FaultSpec

        if spec.strip() == "off":
            self._backend = self._base_backend
            return ["faults off"]
        parsed = FaultSpec.parse(spec)
        self._backend = FaultInjectingBackend(self._base_backend, parsed)
        return [f"faults {parsed.describe()}"]

    def corrupt(self, n: int) -> list[str]:
        """The LAST ``n`` live nodes serve hostile payloads (from the
        tail so a script composing partition+corrupt hits disjoint
        victims — a breaker opened by the partition would otherwise
        shield the corrupt page from ever being fetched)."""
        victims = self._live()[-n:] if n > 0 else []  # [-0:] is EVERYTHING
        with self._lock:
            self._corrupt.update(victims)
        return [f"corrupting node-{i}" for i in victims]

    def flap(self, n: int) -> list[str]:
        """The first ``n`` live nodes toggle partitioned/healthy on
        every page tick (flapping membership)."""
        victims = self._live()[:n]
        with self._lock:
            self._flap.update(victims)
        return [f"flapping node-{i}" for i in victims]

    def heal(self) -> list[str]:
        """Clear every recoverable fault (killed nodes stay dead;
        ``revive`` is their explicit undo)."""
        with self._lock:
            cleared = (
                len(self._partitioned) + len(self._slow)
                + len(self._creep) + len(self._corrupt)
                + len(self._flap) + len(self._skew)
            )
            self._partitioned.clear()
            self._slow.clear()
            self._creep.clear()
            self._corrupt.clear()
            self._flap.clear()
            self._skew.clear()
        if self._backend is not self._base_backend:
            self._backend = self._base_backend
            cleared += 1
        return [f"healed {cleared} fault(s)"]

    def close(self) -> None:
        self._stop.set()
        for server in self._servers:
            if server is not None:
                server.shutdown()
                server.server_close()
        self._ticker.join(timeout=2.0)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpumon-fleetsim", description=__doc__)
    parser.add_argument("--nodes", type=int, default=64)
    parser.add_argument("--topology", default="v4-8")
    parser.add_argument("--node-interval", type=float, default=1.0,
                        help="page-advance cadence seconds")
    parser.add_argument("--addr", default="127.0.0.1")
    parser.add_argument("--churn", type=float, default=1.0,
                        help="fraction of live nodes whose content "
                        "advances per tick (idle nodes heartbeat only)")
    args = parser.parse_args(argv)
    sim = FleetSim(
        args.nodes, topology=args.topology,
        node_interval=args.node_interval, addr=args.addr,
        churn=args.churn,
    )
    print("PORTS " + " ".join(str(p) for p in sim.ports), flush=True)
    try:
        # Control protocol: "kill N" / "revive N" / "partition N" /
        # "slow N MS" / "creep N MS [RAMP_S]" / "skew N S" /
        # "corrupt N" / "flap N" / "churn F" / "serve ..." /
        # "faults SPEC" / "heal" / "quit".
        for line in sys.stdin:
            parts = line.split()
            if not parts:
                continue
            cmd = parts[0]
            if cmd == "quit":
                break
            try:
                if cmd == "kill" and len(parts) == 2:
                    out = sim.kill(int(parts[1]))
                elif cmd == "revive" and len(parts) == 2:
                    out = sim.revive(int(parts[1]))
                elif cmd == "partition" and len(parts) == 2:
                    out = sim.partition(int(parts[1]))
                elif cmd == "slow" and len(parts) == 3:
                    out = sim.slow(int(parts[1]), float(parts[2]) / 1e3)
                elif cmd == "creep" and len(parts) in (3, 4):
                    out = sim.creep(
                        int(parts[1]), float(parts[2]) / 1e3,
                        float(parts[3]) if len(parts) == 4 else 10.0,
                    )
                elif cmd == "skew" and len(parts) == 3:
                    out = sim.skew(int(parts[1]), float(parts[2]))
                elif cmd == "corrupt" and len(parts) == 2:
                    out = sim.corrupt(int(parts[1]))
                elif cmd == "flap" and len(parts) == 2:
                    out = sim.flap(int(parts[1]))
                elif cmd == "churn" and len(parts) == 2:
                    out = sim.set_churn(float(parts[1]))
                elif cmd == "serve" and len(parts) >= 2:
                    out = sim.serve_profile(" ".join(parts[1:]))
                elif cmd == "faults" and len(parts) == 2:
                    out = sim.faults(parts[1])
                elif cmd == "heal" and len(parts) == 1:
                    out = sim.heal()
                else:
                    out = [f"unknown command: {line.strip()}"]
            except ValueError as exc:
                out = [f"bad arguments ({exc}): {line.strip()}"]
            for desc in out:
                print(desc, flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        sim.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
