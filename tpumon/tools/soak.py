"""Wall-clock soak: the exporter at poll cadence + random-phase scrapes.

The scrape-latency bench (`bench.py`) fires back-to-back scrapes, so it
mostly measures the uncontended path; production Prometheus scrapes land
at a RANDOM PHASE of the poll cycle, and the ones that arrive mid-poll
contend with the poller for the GIL. This tool measures that honestly:
one persistent-connection scrape per second for ``--duration`` seconds
while the 1 Hz poller runs, reporting the latency distribution, page
integrity, collector errors, and RSS over time (a leak in the C
renderer, the C++ history engine, or the sample cache shows as
monotonic RSS growth across thousands of poll cycles).

Prints one JSON line.  Run:
    python -m tpumon.tools.soak --duration 2700
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import re
import sys
import time

from tpumon.tools.measure import PAGE_SENTINEL, quantile

#: Default --chaos spec: sustained RPC errors + periodic hangs + one
#: payload-corruption dose — the ISSUE acceptance mix, scaled so a short
#: smoke exercises every injector (tpumon/resilience/faults.py).
DEFAULT_CHAOS_SPEC = (
    "error_rate=0.3,hang_every=40,hang_s=10,garbage_rate=0.05,"
    "partial_rate=0.05,flap_start=15,flap_end=25"
)


#: --storm guard tightening: shedding and slowloris eviction must be
#: observable inside a short smoke, so the debug budget and the header
#: deadline come down while the soak's own 1 Hz scraper stays well under
#: every cap (its well-behaved scrapes are the acceptance evidence).
STORM_GUARD_CFG = dict(
    guard_debug_rps=10.0,
    guard_header_timeout_s=1.0,
    guard_idle_timeout_s=30.0,
    grpc_serve_port=0,  # ephemeral: gives the Watch hammer a target
)


def soak(
    duration_s: float,
    scrape_every_s: float = 1.0,
    topology: str = "v5p-64",
    interval: float = 1.0,
    backend: str = "fake",
    chaos: str | None = None,
    storm: bool = False,
) -> dict:
    """``backend="fake"`` soaks the synthetic v5p topology (the bench's
    configuration); any other value is a Config backend selection —
    ``auto``/``libtpu`` soak the REAL monitoring SDK on a TPU host,
    which answers even when the compute tunnel is wedged (the two
    surfaces are independent; observed live in rounds 4 and 5)."""
    from tpumon.backends.fake import FakeTpuBackend
    from tpumon.config import Config
    from tpumon.exporter.server import build_exporter

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")

    try:
        import psutil

        rss_of = psutil.Process(os.getpid()).memory_info
    except ImportError:  # RSS tracking is auxiliary; degrade like host.py
        rss_of = None

    # Everything that can fail on bad arguments happens BEFORE the
    # switch-interval mutation below, so an invalid topology/backend
    # leaves the caller's interpreter settings untouched.
    fault_backend = None
    chaos_cfg: dict = {}
    if chaos:
        from tpumon.resilience import FaultSpec

        fault_spec = FaultSpec.parse(chaos)
        # Chaos runs tighten the recovery knobs so a short soak exercises
        # breaker-open AND watchdog-recovery, not just retry.
        chaos_cfg = dict(
            watchdog_hang_s=max(2.0, interval * 2.0),
            breaker_open_s=5.0,
        )
    if storm:
        chaos_cfg.update(STORM_GUARD_CFG)
    if backend == "fake":
        cfg = Config(port=0, addr="127.0.0.1", interval=interval, **chaos_cfg)
        inner = FakeTpuBackend.preset(topology)
        if chaos:
            from tpumon.resilience import FaultInjectingBackend, RetryPolicy

            inner = fault_backend = FaultInjectingBackend(
                inner, fault_spec, retry=RetryPolicy()
            )
        exporter = build_exporter(cfg, inner)
    else:
        cfg = Config(
            port=0, addr="127.0.0.1", interval=interval, backend=backend,
            faults=chaos or "", **chaos_cfg,
        )
        exporter = build_exporter(cfg)  # create_backend resolves + wraps
        if chaos:
            fault_backend = exporter.backend

    # On a real idle host the data families are absent by design (runtime
    # detached — SURVEY §2.2), so page integrity is judged by an identity
    # family that must always be present instead. Under chaos the
    # degraded plane may be serving last-good data, but identity is
    # built fresh every cycle — it must never vanish.
    sentinel = (
        PAGE_SENTINEL
        if backend == "fake" and not chaos
        else b"accelerator_device_count"
    )
    lat_ms: list[float] = []
    rss: list[float] = []
    bad_pages = 0
    degraded_scrapes = 0
    failed_scrapes = 0
    conn = None
    # Mirror the daemon entrypoint's scrape-tail tuning, same opt-out
    # (exporter/main.py): without it the poll cycle can hold a scrape
    # thread for the default 5 ms GIL switch interval — measured p99
    # 13 ms untuned vs 6.6 ms tuned over 45-minute soaks on the v5p-64
    # fake topology. Applied here (not at import) and restored in the
    # finally below alongside exporter shutdown, so neither importers
    # nor embedding test processes keep the mutated setting even when
    # startup or the soak loop fails.
    prev_switch = sys.getswitchinterval()
    try:
        if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
            sys.setswitchinterval(min(prev_switch, 0.001))
        exporter.start()
        storm_result: dict = {}
        storm_thread = None
        if storm:
            import threading

            from tpumon.guard.stormer import Stormer

            grpc_addr = (
                f"127.0.0.1:{exporter.grpc_server.port}"
                if exporter.grpc_server is not None
                else None
            )
            stormer = Stormer(
                "127.0.0.1", exporter.server.port, grpc_addr=grpc_addr
            )
            storm_thread = threading.Thread(
                target=lambda: storm_result.update(
                    stormer.run(duration_s=duration_s)
                ),
                name="tpumon-stormer",
                daemon=True,
            )
            storm_thread.start()
        conn = http.client.HTTPConnection(
            "127.0.0.1", exporter.server.port, timeout=10
        )
        t0 = time.time()
        next_at = t0
        while time.time() - t0 < duration_s:
            s = time.perf_counter()
            try:
                conn.request("GET", "/metrics")
                body = conn.getresponse().read()
            except (OSError, http.client.HTTPException):
                # The acceptance bar is "every scrape answered": count
                # the miss (it should never happen — the scrape path is
                # device-free) and reconnect rather than aborting the
                # evidence run.
                failed_scrapes += 1
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", exporter.server.port, timeout=10
                )
            else:
                lat_ms.append((time.perf_counter() - s) * 1e3)
                # Page checks only apply to pages we actually received —
                # a connection failure is failed_scrapes, not bad_pages.
                if sentinel not in body:
                    bad_pages += 1
                if b"\ntpumon_degraded 1.0" in body:
                    degraded_scrapes += 1
            if rss_of is not None and len(lat_ms) % 300 == 1:
                rss.append(round(rss_of().rss / 1e6, 1))
            next_at += scrape_every_s
            time.sleep(max(0.0, next_at - time.time()))
        if storm_thread is not None:
            # The storm ends with the soak window; fold its final shed
            # counts into the page read below.
            storm_thread.join(timeout=30)
        try:
            conn.request("GET", "/metrics")
            page = conn.getresponse().read().decode()
        except (OSError, http.client.HTTPException):
            page = ""  # dead server: the record (failed_scrapes) is the story
        # Clocked at the same instant as the page the counters come
        # from — measuring it after exporter.close() (poller join,
        # server teardown) would understate every rate derived from it.
        elapsed_s = time.time() - t0
        # ^-anchored: the family's HELP line also starts with the name.
        polls = re.search(r"^collector_polls_total (\S+)", page, re.M)
        errors = re.findall(
            r'^collector_errors_total\{kind="(\w+)"\} (\S+)', page, re.M
        )
        recoveries = re.search(
            r"^tpumon_watchdog_recoveries_total (\S+)", page, re.M
        )
        retries = re.findall(
            r'^tpumon_retries_total\{call="([^"]+)"\} (\S+)', page, re.M
        )
        sheds = re.findall(
            r'^tpumon_shed_requests_total'
            r'\{endpoint="([^"]+)",reason="([^"]+)"\} (\S+)',
            page, re.M,
        )
        guard_state = re.search(r"^tpumon_guard_state (\S+)", page, re.M)
    finally:
        if conn is not None:
            conn.close()
        exporter.close()
        sys.setswitchinterval(prev_switch)

    lat_ms.sort()

    def _q(p: float):
        # An all-scrapes-failed run (server died at startup) must still
        # produce the evidence record — failed_scrapes is the finding.
        return round(quantile(lat_ms, p), 3) if lat_ms else None

    record = {
        # The *resolved* backend, not the requested one: --backend auto
        # can fall back to stub, and soak evidence must say which SDK it
        # actually exercised.
        "backend": exporter.backend.name,
        "scrapes": len(lat_ms),
        "duration_s": round(elapsed_s, 1),
        "p50_ms": _q(0.5),
        "p99_ms": _q(0.99),
        "p999_ms": _q(0.999),
        "max_ms": round(lat_ms[-1], 3) if lat_ms else None,
        "bad_pages": bad_pages,
        "failed_scrapes": failed_scrapes,
        "rss_mb_samples": rss,
        "poll_cycles": float(polls.group(1)) if polls else None,
        "collector_errors": {k: float(v) for k, v in errors},
    }
    if storm:
        # The ISSUE acceptance evidence: every well-behaved scrape in
        # this record's lat_ms/failed_scrapes was taken WHILE the storm
        # ran; shed/guard_state show the abusers being refused; poll_hz
        # shows the 1 Hz loop never missed a beat; max RSS stays under
        # the hard watermark (when armed).
        mem = (
            exporter.memwatch.snapshot()
            if getattr(exporter, "memwatch", None) is not None
            else {}
        )
        poll_hz = (
            record["poll_cycles"] / record["duration_s"]
            if record["poll_cycles"] and record["duration_s"]
            else None
        )
        record["storm"] = {
            "report": storm_result,
            "shed": {
                f"{ep}:{reason}": float(v) for ep, reason, v in sheds
            },
            "guard_state": (
                float(guard_state.group(1)) if guard_state else None
            ),
            "poll_hz": round(poll_hz, 3) if poll_hz else None,
            "max_rss_mb": (
                round(mem["max_rss_bytes"] / 1e6, 1)
                if mem.get("max_rss_bytes")
                else None
            ),
            "hard_watermark_mb": (
                round(mem["hard_bytes"] / 1e6, 1)
                if mem.get("hard_bytes")
                else None
            ),
        }
    if chaos:
        record["chaos"] = {
            "spec": fault_spec.describe(),
            "degraded_scrapes": degraded_scrapes,
            "watchdog_recoveries": (
                float(recoveries.group(1)) if recoveries else 0.0
            ),
            "retries": {k: float(v) for k, v in retries},
            "injected": (
                dict(fault_backend.injected)
                if fault_backend is not None
                and hasattr(fault_backend, "injected")
                else {}
            ),
            "device_calls": (
                sum(fault_backend.calls.values())
                if fault_backend is not None
                and hasattr(fault_backend, "calls")
                else None
            ),
        }
    return record


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpumon-soak")
    parser.add_argument("--duration", type=float, default=2700.0,
                        help="soak length in seconds (default 45 min)")
    parser.add_argument("--scrape-every", type=float, default=1.0)
    parser.add_argument("--topology", default="v5p-64",
                        help="fake-backend topology preset")
    parser.add_argument("--interval", type=float, default=1.0,
                        help="exporter poll interval")
    from tpumon.config import BACKEND_CHOICES

    parser.add_argument("--backend", default="fake",
                        choices=BACKEND_CHOICES,
                        help="'fake' (synthetic --topology preset) or a "
                        "real backend selection — 'auto'/'libtpu' soak "
                        "the real monitoring SDK on a TPU host")
    parser.add_argument("--chaos", nargs="?", const=DEFAULT_CHAOS_SPEC,
                        default=None, metavar="SPEC",
                        help="wrap the backend in deterministic fault "
                        "injection (tpumon/resilience/faults.py) and "
                        "report degraded-serving evidence; optional SPEC "
                        f"overrides the default ({DEFAULT_CHAOS_SPEC!r})")
    parser.add_argument("--storm", action="store_true",
                        help="run the client-side chaos generator "
                        "(tpumon/guard/stormer.py: scrape storm + "
                        "slowloris + oversized requests + Watch hammer) "
                        "against the exporter during the soak and report "
                        "shedding/guard evidence")
    args = parser.parse_args(argv)
    if args.duration <= 0:
        parser.error("--duration must be > 0")
    print(json.dumps(soak(
        args.duration, args.scrape_every, args.topology, args.interval,
        args.backend, chaos=args.chaos, storm=args.storm,
    )))
    return 0


if __name__ == "__main__":
    sys.exit(main())
