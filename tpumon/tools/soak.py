"""Wall-clock soak: the exporter at poll cadence + random-phase scrapes.

The scrape-latency bench (`bench.py`) fires back-to-back scrapes, so it
mostly measures the uncontended path; production Prometheus scrapes land
at a RANDOM PHASE of the poll cycle, and the ones that arrive mid-poll
contend with the poller for the GIL. This tool measures that honestly:
one persistent-connection scrape per second for ``--duration`` seconds
while the 1 Hz poller runs, reporting the latency distribution, page
integrity, collector errors, and RSS over time (a leak in the C
renderer, the C++ history engine, or the sample cache shows as
monotonic RSS growth across thousands of poll cycles).

Prints one JSON line.  Run:
    python -m tpumon.tools.soak --duration 2700
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import re
import shutil
import subprocess
import sys
import time

from tpumon.tools.measure import PAGE_SENTINEL, quantile

#: Default --chaos spec: sustained RPC errors + periodic hangs + one
#: payload-corruption dose — the ISSUE acceptance mix, scaled so a short
#: smoke exercises every injector (tpumon/resilience/faults.py).
DEFAULT_CHAOS_SPEC = (
    "error_rate=0.3,hang_every=40,hang_s=10,garbage_rate=0.05,"
    "partial_rate=0.05,flap_start=15,flap_end=25"
)


#: --storm guard tightening: shedding and slowloris eviction must be
#: observable inside a short smoke, so the debug budget and the header
#: deadline come down while the soak's own 1 Hz scraper stays well under
#: every cap (its well-behaved scrapes are the acceptance evidence).
STORM_GUARD_CFG = dict(
    guard_debug_rps=10.0,
    guard_header_timeout_s=1.0,
    guard_idle_timeout_s=30.0,
    grpc_serve_port=0,  # ephemeral: gives the Watch hammer a target
)


def soak(
    duration_s: float,
    scrape_every_s: float = 1.0,
    topology: str = "v5p-64",
    interval: float = 1.0,
    backend: str = "fake",
    chaos: str | None = None,
    storm: bool = False,
) -> dict:
    """``backend="fake"`` soaks the synthetic v5p topology (the bench's
    configuration); any other value is a Config backend selection —
    ``auto``/``libtpu`` soak the REAL monitoring SDK on a TPU host,
    which answers even when the compute tunnel is wedged (the two
    surfaces are independent; observed live in rounds 4 and 5)."""
    from tpumon.backends.fake import FakeTpuBackend
    from tpumon.config import Config
    from tpumon.exporter.server import build_exporter

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")

    try:
        import psutil

        rss_of = psutil.Process(os.getpid()).memory_info
    except ImportError:  # RSS tracking is auxiliary; degrade like host.py
        rss_of = None

    # Everything that can fail on bad arguments happens BEFORE the
    # switch-interval mutation below, so an invalid topology/backend
    # leaves the caller's interpreter settings untouched.
    fault_backend = None
    chaos_cfg: dict = {}
    if chaos:
        from tpumon.resilience import FaultSpec

        fault_spec = FaultSpec.parse(chaos)
        # Chaos runs tighten the recovery knobs so a short soak exercises
        # breaker-open AND watchdog-recovery, not just retry.
        chaos_cfg = dict(
            watchdog_hang_s=max(2.0, interval * 2.0),
            breaker_open_s=5.0,
        )
    if storm:
        chaos_cfg.update(STORM_GUARD_CFG)
    if backend == "fake":
        cfg = Config(port=0, addr="127.0.0.1", interval=interval, **chaos_cfg)
        inner = FakeTpuBackend.preset(topology)
        if chaos:
            from tpumon.resilience import FaultInjectingBackend, RetryPolicy

            inner = fault_backend = FaultInjectingBackend(
                inner, fault_spec, retry=RetryPolicy()
            )
        exporter = build_exporter(cfg, inner)
    else:
        cfg = Config(
            port=0, addr="127.0.0.1", interval=interval, backend=backend,
            faults=chaos or "", **chaos_cfg,
        )
        exporter = build_exporter(cfg)  # create_backend resolves + wraps
        if chaos:
            fault_backend = exporter.backend

    # On a real idle host the data families are absent by design (runtime
    # detached — SURVEY §2.2), so page integrity is judged by an identity
    # family that must always be present instead. Under chaos the
    # degraded plane may be serving last-good data, but identity is
    # built fresh every cycle — it must never vanish.
    sentinel = (
        PAGE_SENTINEL
        if backend == "fake" and not chaos
        else b"accelerator_device_count"
    )
    lat_ms: list[float] = []
    rss: list[float] = []
    bad_pages = 0
    degraded_scrapes = 0
    failed_scrapes = 0
    conn = None
    # Mirror the daemon entrypoint's scrape-tail tuning, same opt-out
    # (exporter/main.py): without it the poll cycle can hold a scrape
    # thread for the default 5 ms GIL switch interval — measured p99
    # 13 ms untuned vs 6.6 ms tuned over 45-minute soaks on the v5p-64
    # fake topology. Applied here (not at import) and restored in the
    # finally below alongside exporter shutdown, so neither importers
    # nor embedding test processes keep the mutated setting even when
    # startup or the soak loop fails.
    prev_switch = sys.getswitchinterval()
    try:
        if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
            sys.setswitchinterval(min(prev_switch, 0.001))
        exporter.start()
        storm_result: dict = {}
        storm_thread = None
        if storm:
            import threading

            from tpumon.guard.stormer import Stormer

            grpc_addr = (
                f"127.0.0.1:{exporter.grpc_server.port}"
                if exporter.grpc_server is not None
                else None
            )
            stormer = Stormer(
                "127.0.0.1", exporter.server.port, grpc_addr=grpc_addr
            )
            storm_thread = threading.Thread(
                target=lambda: storm_result.update(
                    stormer.run(duration_s=duration_s)
                ),
                name="tpumon-stormer",
                daemon=True,
            )
            storm_thread.start()
        conn = http.client.HTTPConnection(
            "127.0.0.1", exporter.server.port, timeout=10
        )
        t0 = time.time()
        next_at = t0
        while time.time() - t0 < duration_s:
            s = time.perf_counter()
            try:
                conn.request("GET", "/metrics")
                body = conn.getresponse().read()
            except (OSError, http.client.HTTPException):
                # The acceptance bar is "every scrape answered": count
                # the miss (it should never happen — the scrape path is
                # device-free) and reconnect rather than aborting the
                # evidence run.
                failed_scrapes += 1
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", exporter.server.port, timeout=10
                )
            else:
                lat_ms.append((time.perf_counter() - s) * 1e3)
                # Page checks only apply to pages we actually received —
                # a connection failure is failed_scrapes, not bad_pages.
                if sentinel not in body:
                    bad_pages += 1
                if b"\ntpumon_degraded 1.0" in body:
                    degraded_scrapes += 1
            if rss_of is not None and len(lat_ms) % 300 == 1:
                rss.append(round(rss_of().rss / 1e6, 1))
            next_at += scrape_every_s
            time.sleep(max(0.0, next_at - time.time()))
        if storm_thread is not None:
            # The storm ends with the soak window; fold its final shed
            # counts into the page read below.
            storm_thread.join(timeout=30)
        try:
            conn.request("GET", "/metrics")
            page = conn.getresponse().read().decode()
        except (OSError, http.client.HTTPException):
            page = ""  # dead server: the record (failed_scrapes) is the story
        # Clocked at the same instant as the page the counters come
        # from — measuring it after exporter.close() (poller join,
        # server teardown) would understate every rate derived from it.
        elapsed_s = time.time() - t0
        # ^-anchored: the family's HELP line also starts with the name.
        polls = re.search(r"^collector_polls_total (\S+)", page, re.M)
        errors = re.findall(
            r'^collector_errors_total\{kind="(\w+)"\} (\S+)', page, re.M
        )
        recoveries = re.search(
            r"^tpumon_watchdog_recoveries_total (\S+)", page, re.M
        )
        retries = re.findall(
            r'^tpumon_retries_total\{call="([^"]+)"\} (\S+)', page, re.M
        )
        sheds = re.findall(
            r'^tpumon_shed_requests_total'
            r'\{endpoint="([^"]+)",reason="([^"]+)"\} (\S+)',
            page, re.M,
        )
        guard_state = re.search(r"^tpumon_guard_state (\S+)", page, re.M)
    finally:
        if conn is not None:
            conn.close()
        exporter.close()
        sys.setswitchinterval(prev_switch)

    lat_ms.sort()

    def _q(p: float):
        # An all-scrapes-failed run (server died at startup) must still
        # produce the evidence record — failed_scrapes is the finding.
        return round(quantile(lat_ms, p), 3) if lat_ms else None

    record = {
        # The *resolved* backend, not the requested one: --backend auto
        # can fall back to stub, and soak evidence must say which SDK it
        # actually exercised.
        "backend": exporter.backend.name,
        "scrapes": len(lat_ms),
        "duration_s": round(elapsed_s, 1),
        "p50_ms": _q(0.5),
        "p99_ms": _q(0.99),
        "p999_ms": _q(0.999),
        "max_ms": round(lat_ms[-1], 3) if lat_ms else None,
        "bad_pages": bad_pages,
        "failed_scrapes": failed_scrapes,
        "rss_mb_samples": rss,
        "poll_cycles": float(polls.group(1)) if polls else None,
        "collector_errors": {k: float(v) for k, v in errors},
    }
    if storm:
        # The ISSUE acceptance evidence: every well-behaved scrape in
        # this record's lat_ms/failed_scrapes was taken WHILE the storm
        # ran; shed/guard_state show the abusers being refused; poll_hz
        # shows the 1 Hz loop never missed a beat; max RSS stays under
        # the hard watermark (when armed).
        mem = (
            exporter.memwatch.snapshot()
            if getattr(exporter, "memwatch", None) is not None
            else {}
        )
        poll_hz = (
            record["poll_cycles"] / record["duration_s"]
            if record["poll_cycles"] and record["duration_s"]
            else None
        )
        record["storm"] = {
            "report": storm_result,
            "shed": {
                f"{ep}:{reason}": float(v) for ep, reason, v in sheds
            },
            "guard_state": (
                float(guard_state.group(1)) if guard_state else None
            ),
            "poll_hz": round(poll_hz, 3) if poll_hz else None,
            "max_rss_mb": (
                round(mem["max_rss_bytes"] / 1e6, 1)
                if mem.get("max_rss_bytes")
                else None
            ),
            "hard_watermark_mb": (
                round(mem["hard_bytes"] / 1e6, 1)
                if mem.get("hard_bytes")
                else None
            ),
        }
    if chaos:
        record["chaos"] = {
            "spec": fault_spec.describe(),
            "degraded_scrapes": degraded_scrapes,
            "watchdog_recoveries": (
                float(recoveries.group(1)) if recoveries else 0.0
            ),
            "retries": {k: float(v) for k, v in retries},
            "injected": (
                dict(fault_backend.injected)
                if fault_backend is not None
                and hasattr(fault_backend, "injected")
                else {}
            ),
            "device_calls": (
                sum(fault_backend.calls.values())
                if fault_backend is not None
                and hasattr(fault_backend, "calls")
                else None
            ),
        }
    return record


def straggler_soak(
    duration_s: float,
    topology: str = "v4-8",
    interval: float = 0.25,
    scrape_every_s: float = 0.5,
) -> dict:
    """Host-correlation acceptance evidence (ISSUE 7): one exporter over
    a deterministic straggler backend and a scripted fake procfs tree.

    Three scripted windows: a HOST phase (chip 0 pinned slow while the
    fixture tree shows cgroup-PSI cpu pressure and a pod's sched delay
    climbing — must attribute ``host-cpu``), a quiet gap (the verdict
    must clear), and a DEVICE phase (chip 0 pinned slow AND throttled
    with the host tree silent — must attribute ``device``). The record
    captures the /hostcorr replay's causes per window, the
    host_straggler events from /anomalies, and the device-query budget:
    calls per poll cycle with the plane on vs a hostcorr-disabled
    control run — the plane must add ZERO device queries.
    """
    import tempfile
    import threading

    from tpumon.backends.fake import FakeTpuBackend
    from tpumon.config import Config
    from tpumon.exporter.server import build_exporter
    from tpumon.hostcorr.fixture import FakeProcTree, StragglerBackend

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")

    tree = FakeProcTree(tempfile.mkdtemp(prefix="tpumon-hostcorr-"))
    pod_uid = "deadbeef-0000-4000-8000-000000000001"
    tree.add_pod(pod_uid, pid=4242, run_delay_ns=0)
    backend = StragglerBackend(
        FakeTpuBackend.preset(topology, ici_flake=0.0)
    )
    cfg = Config(
        port=0, addr="127.0.0.1", interval=interval,
        hostcorr_proc_root=tree.root,
        # The replay walk below starts at since=0 and judges the HOST
        # window (earliest phase): the ring must hold the WHOLE run or
        # a long --duration would evict it and falsely report empty
        # host attribution.
        hostcorr_ring=int(duration_s / interval) + 64,
    )
    exporter = build_exporter(cfg, backend)

    host_win = (0.15 * duration_s, 0.5 * duration_s)
    gap_end = 0.6 * duration_s
    # Clearing is fast — one calm poll drops the judge's streak — so the
    # gap window only skips a few polls of clear latency. Checked up
    # front: an empty gap window would make the verdict-cleared
    # acceptance check vacuous (gap_causes == {} without examining a
    # single record), so that's a parameter error, not a green run.
    clear_s = 3 * interval
    if host_win[1] + clear_s >= gap_end:
        raise ValueError(
            f"--duration {duration_s:g} is too short for the straggler "
            f"script at --interval {interval:g}: the verdict-cleared gap "
            "window would cover no records (need duration > 30*interval)"
        )
    stop = threading.Event()
    t0_box: list[float] = []

    def mutate() -> None:
        # Scripts the scenario against the wall clock: inside the host
        # window chip 0 lags while the tree shows cpu pressure and a
        # climbing pod sched delay; inside the device window chip 0 lags
        # AND throttles while the tree is silent.
        delay_ns = 0
        while not stop.wait(interval / 2.0):
            if not t0_box:
                continue
            t = time.time() - t0_box[0]
            if host_win[0] <= t < host_win[1]:
                backend.lag_chip = 0
                backend.throttle_chip = None
                delay_ns += int(3e8 * interval / 2.0)
                tree.set_pod_delay(4242, delay_ns)
                tree.set_pressure(
                    "cpu", some_avg10=35.0, some_total_us=int(t * 3e5)
                )
            elif t < gap_end:
                backend.lag_chip = None
                backend.throttle_chip = None
                tree.set_pressure("cpu")
            else:
                backend.lag_chip = 0
                backend.throttle_chip = 0
                tree.set_pressure("cpu")

    lat_ms: list[float] = []
    bad_pages = 0
    failed_scrapes = 0
    conn = None
    mutator = threading.Thread(
        target=mutate, name="tpumon-straggler-script", daemon=True
    )
    prev_switch = sys.getswitchinterval()
    try:
        if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
            sys.setswitchinterval(min(prev_switch, 0.001))
        exporter.start()
        mutator.start()
        conn = http.client.HTTPConnection(
            "127.0.0.1", exporter.server.port, timeout=10
        )
        t0 = time.time()
        t0_box.append(t0)
        next_at = t0
        while time.time() - t0 < duration_s:
            s = time.perf_counter()
            try:
                conn.request("GET", "/metrics")
                body = conn.getresponse().read()
            except (OSError, http.client.HTTPException):
                failed_scrapes += 1
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", exporter.server.port, timeout=10
                )
            else:
                lat_ms.append((time.perf_counter() - s) * 1e3)
                if b"tpu_hostcorr_available" not in body:
                    bad_pages += 1
            next_at += scrape_every_s
            time.sleep(max(0.0, next_at - time.time()))

        def get_json(path: str) -> dict:
            conn.request("GET", path)
            return json.loads(conn.getresponse().read())

        # Walk the bounded /hostcorr replay to the end of the ring.
        records: list = []
        since = 0.0
        while True:
            doc = get_json(f"/hostcorr?since={since}")
            records.extend(doc["records"])
            if not doc.get("truncated"):
                break
            since = doc["next_since"]
        anomalies = get_json("/anomalies")
        available = doc["available"]
        events_total = doc.get("events_total", {})
    finally:
        stop.set()
        mutator.join(timeout=5)
        if conn is not None:
            conn.close()
        exporter.close()
        sys.setswitchinterval(prev_switch)
        shutil.rmtree(tree.root, ignore_errors=True)
    # Counted AFTER the poller stopped, so calls and cycles are an exact
    # pair (a mid-flight cycle would skew the per-cycle budget ratio).
    poll_cycles = exporter.telemetry.polls._value.get()

    def causes_in(window: tuple[float, float]) -> dict:
        counts: dict[str, int] = {}
        for rec in records:
            t = rec["ts"] - t0
            verdict = rec.get("straggler") or {}
            if window[0] <= t < window[1] and verdict.get("active"):
                cause = verdict.get("cause", "unknown")
                counts[cause] = counts.get(cause, 0) + 1
        return counts

    # Allow onset latency (skew_cycles polls) before judging the host
    # and device windows; the gap window starts after the (much shorter)
    # clear latency instead — onset_s here inverted the window for
    # ordinary --duration/--interval choices.
    onset_s = 8 * interval
    host_causes = causes_in((host_win[0] + onset_s, host_win[1]))
    gap_causes = causes_in((host_win[1] + clear_s, gap_end))
    device_causes = causes_in((gap_end + onset_s, duration_s))

    # Zero-additional-device-queries control: the identical exporter with
    # the plane disabled must issue the same device calls per cycle.
    calls_per_cycle = (
        sum(backend.calls.values()) / poll_cycles if poll_cycles else None
    )
    control_backend = StragglerBackend(
        FakeTpuBackend.preset(topology, ici_flake=0.0)
    )
    control = build_exporter(
        Config(port=0, addr="127.0.0.1", interval=interval, hostcorr=False),
        control_backend,
    )
    try:
        control.start()
        time.sleep(max(3.0, 12 * interval))
    finally:
        control.close()
    control_polls = control.telemetry.polls._value.get()
    control_per_cycle = (
        sum(control_backend.calls.values()) / control_polls
        if control_polls
        else None
    )

    lat_ms.sort()

    def _q(p: float):
        return round(quantile(lat_ms, p), 3) if lat_ms else None

    host_events = [
        {
            k: e.get(k)
            for k in ("detector", "device", "severity", "message",
                      "onset_ts", "clear_ts")
        }
        for e in anomalies.get("events", [])
        if e.get("detector") in ("host_straggler", "host_stall")
    ]
    return {
        "mode": "straggler",
        "topology": topology,
        "interval_s": interval,
        "duration_s": round(duration_s, 1),
        "hostcorr_available": available,
        "poll_cycles": poll_cycles,
        "scrapes": len(lat_ms),
        "p50_ms": _q(0.5),
        "p99_ms": _q(0.99),
        "bad_pages": bad_pages,
        "failed_scrapes": failed_scrapes,
        #: Active-verdict cause tallies inside each scripted window —
        #: the host window must read host-*, the device window device,
        #: and the gap must be empty (the verdict cleared).
        "host_phase_causes": host_causes,
        "gap_causes": gap_causes,
        "device_phase_causes": device_causes,
        "straggler_events_total": events_total,
        "host_events": host_events[:16],
        #: The zero-additional-device-queries proof: identical per-cycle
        #: device-call budget with the plane on and off.
        "device_calls_per_cycle": (
            round(calls_per_cycle, 4) if calls_per_cycle else None
        ),
        "control_calls_per_cycle": (
            round(control_per_cycle, 4) if control_per_cycle else None
        ),
    }


#: Detectors whose events inside a CLEAN lifecycle window count as
#: false positives in the lifecycle soak evidence (the suppressible
#: roster minus nothing: during a clean transition none of these should
#: produce a retained event).
_LC_FALSE_SET = (
    "duty_ewma", "hbm_ewma", "ici_flap", "bw_cusum", "queue_stall",
    "host_straggler", "host_stall", "step_regression", "collective_wait",
    "efficiency_regression",
)

#: Tightened lifecycle thresholds for short soak windows: the classifier
#: and step detectors must arm, fire, and close inside tens of seconds.
_LC_ENV = {
    "TPUMON_LIFECYCLE_SUPPRESS_S": None,  # filled per run from interval
    "TPUMON_LIFECYCLE_STEADY_CYCLES": "6",
    "TPUMON_LIFECYCLE_LOST_CYCLES": "2",
    "TPUMON_LIFECYCLE_STEP_WARMUP": "6",
    "TPUMON_LIFECYCLE_WAIT_WARMUP": "6",
}


def _lc_env(interval: float) -> dict:
    env = dict(_LC_ENV)
    env["TPUMON_LIFECYCLE_SUPPRESS_S"] = f"{max(3.0, 8 * interval):g}"
    return env


class _EnvPatch:
    """Scoped os.environ patch (the soak runs in-process; thresholds
    are env-cached and re-parsed on change, so this is the supported
    way to tighten them for a short run)."""

    def __init__(self, env: dict) -> None:
        self._env = env
        self._saved: dict = {}

    def __enter__(self):
        for key, value in self._env.items():
            self._saved[key] = os.environ.get(key)
            os.environ[key] = value
        return self

    def __exit__(self, *exc):
        for key, old in self._saved.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


def _lc_control_calls_per_cycle(topology: str, interval: float) -> float | None:
    """Zero-additional-device-queries control: the identical exporter
    with the lifecycle plane disabled must issue the same device calls
    per poll cycle."""
    from tpumon.backends.fake import FakeTpuBackend
    from tpumon.config import Config
    from tpumon.exporter.server import build_exporter
    from tpumon.lifecycle.fixture import LifecycleBackend

    backend = LifecycleBackend(FakeTpuBackend.preset(topology, ici_flake=0.0))
    control = build_exporter(
        Config(port=0, addr="127.0.0.1", interval=interval, lifecycle=False),
        backend,
    )
    try:
        control.start()
        time.sleep(max(3.0, 12 * interval))
    finally:
        control.close()
    polls = control.telemetry.polls._value.get()
    return (
        sum(backend.calls.values()) / polls if polls else None
    )


def _lc_events(anomalies: dict, detectors, window=None) -> list[dict]:
    """Events from ``detectors`` (optionally onset inside ``window``,
    run-relative seconds with t0 at index 2 of the tuple)."""
    out = []
    for e in anomalies.get("events", []):
        if e.get("detector") not in detectors:
            continue
        if window is not None:
            lo, hi, t0 = window
            t = e.get("onset_ts", 0.0) - t0
            if not (lo <= t < hi):
                continue
        out.append(e)
    return out


def _lc_scaffold(topology: str, interval: float, feeds: int,
                 cfg_extra: dict | None = None):
    """Common lifecycle-soak scaffolding: N scripted workload feeds +
    one exporter over a LifecycleBackend probing them."""
    from tpumon.backends.fake import FakeTpuBackend
    from tpumon.config import Config
    from tpumon.exporter.server import build_exporter
    from tpumon.lifecycle.fixture import LifecycleBackend, ScriptedWorkload

    workloads = [ScriptedWorkload() for _ in range(feeds)]
    for wl in workloads:
        wl.start()
    backend = LifecycleBackend(FakeTpuBackend.preset(topology, ici_flake=0.0))
    cfg = Config(
        port=0, addr="127.0.0.1", interval=interval,
        lifecycle_step_urls=",".join(wl.url for wl in workloads),
        **(cfg_extra or {}),
    )
    exporter = build_exporter(cfg, backend)
    return workloads, backend, exporter


def _lc_run(exporter, workloads, duration_s, scrape_every_s, script):
    """Drive one lifecycle scenario: scrape at cadence while ``script(t)``
    mutates the fixtures; returns (lat_ms, failed, t0, elapsed)."""
    lat_ms: list[float] = []
    failed = 0
    conn = http.client.HTTPConnection(
        "127.0.0.1", exporter.server.port, timeout=10
    )
    t0 = time.time()
    next_at = t0
    try:
        while time.time() - t0 < duration_s:
            script(time.time() - t0)
            s = time.perf_counter()
            try:
                conn.request("GET", "/metrics")
                conn.getresponse().read()
            except (OSError, http.client.HTTPException):
                failed += 1
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", exporter.server.port, timeout=10
                )
            else:
                lat_ms.append((time.perf_counter() - s) * 1e3)
            next_at += scrape_every_s
            time.sleep(max(0.0, next_at - time.time()))
        return lat_ms, failed, t0, time.time() - t0, conn
    except BaseException:
        conn.close()
        raise


def _lc_harvest(port: int) -> tuple[dict, dict]:
    """(/lifecycle full replay walk, /anomalies) off one exporter."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        def get_json(path: str) -> dict:
            conn.request("GET", path)
            return json.loads(conn.getresponse().read())

        records: list = []
        since = 0.0
        while True:
            doc = get_json(f"/lifecycle?since={since}")
            records.extend(doc["records"])
            if not doc.get("truncated"):
                break
            since = doc["next_since"]
        doc["records"] = records
        return doc, get_json("/anomalies")
    finally:
        conn.close()


def preempt_soak(
    duration_s: float,
    topology: str = "v4-8",
    interval: float = 0.25,
    scrape_every_s: float = 0.5,
) -> dict:
    """``--preempt`` (ISSUE 10): slice preemption + elastic resize +
    checkpoint restore mid-run, then a GENUINE step-time regression.

    Script (fractions of --duration): steady → SIGTERM + duty collapse
    + feed loss (preemption) → half the chips disappear (elastic
    resize; exporter re-enumerates) → the feed returns on the same port
    reporting a restore span and a mesh-adjusted step rate → steady →
    step time doubles with NO lifecycle signals (real regression). The
    evidence is the robustness contract: zero false straggler/stall/
    duty/regression events during the clean transition window,
    all three lifecycle kinds recognized, the post-window regression
    detected, and zero added device queries vs a lifecycle-off control.
    """
    from tpumon.lifecycle.fixture import ScriptedWorkload

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")
    if duration_s < 80 * interval:
        raise ValueError(
            f"--duration {duration_s:g} too short for the preempt script "
            f"at --interval {interval:g} (need > 80*interval: warmup, the "
            "transition, the suppression window, and the regression phase "
            "each span several poll cycles)"
        )

    script_at = {
        "preempt": 0.20 * duration_s,
        "lose_feed": 0.26 * duration_s,
        "resize": 0.34 * duration_s,
        "restore": 0.42 * duration_s,
        "regress": 0.72 * duration_s,
    }
    suppress_s = max(3.0, 8 * interval)
    # The clean window: transition start until the last lifecycle signal
    # plus the suppression budget (generous on the early side too: the
    # EWMA baselines formed during warmup).
    clean_win = (script_at["preempt"] - 1.0, script_at["restore"] + suppress_s)

    workloads, backend, exporter = _lc_scaffold(topology, interval, feeds=1)
    feed = workloads[0]
    feed_port = feed.server.port if hasattr(feed.server, "port") else 0
    state = {"feed": feed, "done": set(), "rate": 2.0}

    def script(t: float) -> None:
        done = state["done"]
        # Keep the live feed publishing windows so steps_per_second ages
        # honestly (a real harness records every stats_every steps).
        if state["feed"] is not None and "lose_feed" not in done:
            state["feed"].set_rate(state["rate"])
        elif state["feed"] is not None and "restore" in done:
            state["feed"].set_rate(state["rate"])
        if t >= script_at["preempt"] and "preempt" not in done:
            done.add("preempt")
            state["feed"].mark_terminating()
            backend.duty_zero = True
        if t >= script_at["lose_feed"] and "lose_feed" not in done:
            done.add("lose_feed")
            state["feed"].close()
            state["feed"] = None
        if t >= script_at["resize"] and "resize" not in done:
            done.add("resize")
            backend.visible_chips = max(
                1, len(backend._inner.topology().chips) // 2
            )
            backend.duty_zero = False
        if t >= script_at["restore"] and "restore" not in done:
            done.add("restore")
            wl = ScriptedWorkload(port=feed_port)
            wl.record_checkpoint("restore", 2.5)
            wl.stats.set_start_step(64)
            wl.start()
            state["feed"] = wl
            state["rate"] = 1.6  # mesh shrank; the new normal
        if t >= script_at["regress"] and "regress" not in done:
            done.add("regress")
            state["rate"] = 0.8  # step time doubles, no lifecycle signal

    with _EnvPatch(_lc_env(interval)):
        try:
            exporter.start()
            lat_ms, failed, t0, elapsed, conn = _lc_run(
                exporter, workloads, duration_s, scrape_every_s, script
            )
            conn.close()
            lifecycle_doc, anomalies = _lc_harvest(exporter.server.port)
        finally:
            exporter.close()
            if state["feed"] is not None:
                state["feed"].close()
    poll_cycles = exporter.telemetry.polls._value.get()
    calls_per_cycle = (
        sum(backend.calls.values()) / poll_cycles if poll_cycles else None
    )
    control = _lc_control_calls_per_cycle(topology, interval)

    false_positives = _lc_events(
        anomalies, _LC_FALSE_SET, (clean_win[0], clean_win[1], t0)
    )
    regressions = _lc_events(
        anomalies, ("step_regression",),
        (script_at["regress"], duration_s + 60.0, t0),
    )
    lat_ms.sort()
    return {
        "mode": "preempt",
        "topology": topology,
        "interval_s": interval,
        "duration_s": round(elapsed, 1),
        "script_s": {k: round(v, 1) for k, v in script_at.items()},
        "scrapes": len(lat_ms),
        "failed_scrapes": failed,
        "p50_ms": round(quantile(lat_ms, 0.5), 3) if lat_ms else None,
        "p99_ms": round(quantile(lat_ms, 0.99), 3) if lat_ms else None,
        "lifecycle_events_total": lifecycle_doc.get("events_total", {}),
        "suppressed": anomalies.get("suppressed", 0),
        #: Zero is the acceptance bar: no false straggler/stall/duty/
        #: regression event may onset inside the clean window.
        "false_positives": len(false_positives),
        "false_positive_events": [
            {k: e.get(k) for k in ("detector", "device", "message")}
            for e in false_positives[:8]
        ],
        #: >= 1 is the bar: the genuine post-window regression fired.
        "regression_detected": len(regressions) > 0,
        "regression_events": [
            {k: e.get(k) for k in ("detector", "device", "message")}
            for e in regressions[:4]
        ],
        "false_negatives": 0 if regressions else 1,
        "device_calls_per_cycle": (
            round(calls_per_cycle, 4) if calls_per_cycle else None
        ),
        "control_calls_per_cycle": (
            round(control, 4) if control else None
        ),
    }


def _energy_control_calls_per_cycle(
    topology: str, interval: float, duty_constant: float
) -> float | None:
    """Zero-additional-device-queries control for the energy plane: the
    identical exporter (lifecycle ON — its probe is localhost HTTP, not
    a device call) with ONLY the energy plane disabled must issue the
    same device calls per poll cycle."""
    from tpumon.backends.fake import FakeTpuBackend
    from tpumon.config import Config
    from tpumon.exporter.server import build_exporter
    from tpumon.lifecycle.fixture import LifecycleBackend

    backend = LifecycleBackend(FakeTpuBackend.preset(topology, ici_flake=0.0))
    backend.duty_constant = duty_constant
    control = build_exporter(
        Config(port=0, addr="127.0.0.1", interval=interval, energy=False),
        backend,
    )
    try:
        control.start()
        time.sleep(max(3.0, 12 * interval))
    finally:
        control.close()
    polls = control.telemetry.polls._value.get()
    return sum(backend.calls.values()) / polls if polls else None


#: Exporter-page families the energy plane owns: every present one
#: must carry an explicit source=measured|modeled label (the ISSUE 12
#: honesty bar — a dashboard can never read a model as a meter).
_ENERGY_FAMILY_PREFIXES = (
    "tpu_energy_power_watts", "tpu_energy_joules_total",
    "tpu_pod_energy_joules_total", "tpu_step_energy_joules",
    "tpu_step_tokens_per_joule", "tpu_step_cost_dollars",
)


def efficiency_soak(
    duration_s: float,
    topology: str = "v4-8",
    interval: float = 0.25,
    scrape_every_s: float = 0.5,
    factor: float = 0.7,
) -> dict:
    """``--efficiency`` (ISSUE 12): a steady preset suddenly pays more
    energy for the same training progress.

    Script: a workload feed publishes a CONSTANT step/token rate over a
    steady pinned duty cycle (the baseline the tokens/J EWMA warms on);
    at the injection point the same step rate starts costing
    ``1/factor``× the duty — so modeled watts rise and tokens/joule
    drops to ``factor``× its baseline — with NO lifecycle signal. The
    bars: zero false verdicts in the clean (pre-injection) window, the
    efficiency_regression event fires after injection, every present
    energy family carries a ``source`` label, and the per-cycle device
    call budget equals an energy-off control (the plane adds zero
    device queries).
    """
    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")
    if duration_s < 60 * interval:
        raise ValueError(
            f"--duration {duration_s:g} too short for the efficiency "
            f"script at --interval {interval:g} (need > 60*interval: "
            "EWMA warmup plus a detection phase each span many cycles)"
        )
    if not 0.0 < factor < 1.0:
        raise ValueError(f"--efficiency factor must be in (0, 1), got {factor}")

    inject_at = 0.55 * duration_s
    duty_constant = 60.0
    workloads, backend, exporter = _lc_scaffold(topology, interval, feeds=1)
    backend.duty_constant = duty_constant
    state = {"injected": False}

    def script(t: float) -> None:
        # Constant step rate throughout — the regression is pure
        # energy-per-progress, never a throughput change.
        workloads[0].set_rate(2.0)
        if t >= inject_at and not state["injected"]:
            state["injected"] = True
            backend.duty_scale = 1.0 / factor

    env = _lc_env(interval)
    env.update(
        {
            # The tokens/J EWMA must arm inside a short smoke, and the
            # cost family must be on the page so the source-label sweep
            # covers all the step joins.
            "TPUMON_ENERGY_EFF_WARMUP": "8",
            "TPUMON_ENERGY_DOLLARS_PER_KWH": "0.12",
        }
    )
    with _EnvPatch(env):
        try:
            exporter.start()
            lat_ms, failed, t0, elapsed, conn = _lc_run(
                exporter, workloads, duration_s, scrape_every_s, script
            )
            try:
                conn.request("GET", "/metrics")
                page = conn.getresponse().read().decode()
            finally:
                conn.close()
            _, anomalies = _lc_harvest(exporter.server.port)
            energy_vars = None
            vconn = http.client.HTTPConnection(
                "127.0.0.1", exporter.server.port, timeout=10
            )
            try:
                vconn.request("GET", "/debug/vars")
                energy_vars = json.loads(vconn.getresponse().read()).get(
                    "energy"
                )
            finally:
                vconn.close()
        finally:
            exporter.close()
            for wl in workloads:
                wl.close()
    poll_cycles = exporter.telemetry.polls._value.get()
    calls_per_cycle = (
        sum(backend.calls.values()) / poll_cycles if poll_cycles else None
    )
    control = _energy_control_calls_per_cycle(
        topology, interval, duty_constant
    )

    # Clean window: start of run (EWMA warmups included) to injection.
    false_positives = _lc_events(
        anomalies, _LC_FALSE_SET, (0.0, inject_at - 1.0, t0)
    )
    regressions = _lc_events(
        anomalies, ("efficiency_regression",),
        (inject_at, duration_s + 60.0, t0),
    )

    # Source-label honesty sweep over the final page: every present
    # energy family line must carry source=.
    families_present: set[str] = set()
    unlabeled: list[str] = []
    for line in page.splitlines():
        if not line or line[0] == "#":
            continue
        for prefix in _ENERGY_FAMILY_PREFIXES:
            if line.startswith(prefix) and line[len(prefix):len(prefix) + 1] in ("{", " "):
                families_present.add(prefix)
                if 'source="' not in line:
                    unlabeled.append(line[:120])

    lat_ms.sort()
    return {
        "mode": "efficiency",
        "topology": topology,
        "interval_s": interval,
        "duration_s": round(elapsed, 1),
        "inject_at_s": round(inject_at, 1),
        #: tokens/J drops to this fraction of baseline at injection
        #: (implemented as the same step rate costing 1/factor× duty).
        "injected_efficiency_factor": factor,
        "duty_constant_pct": duty_constant,
        "scrapes": len(lat_ms),
        "failed_scrapes": failed,
        "p50_ms": round(quantile(lat_ms, 0.5), 3) if lat_ms else None,
        "p99_ms": round(quantile(lat_ms, 0.99), 3) if lat_ms else None,
        #: Zero is the bar: no detector verdict may onset before the
        #: injection (the steady preset IS steady).
        "false_positives": len(false_positives),
        "false_positive_events": [
            {k: e.get(k) for k in ("detector", "device", "message")}
            for e in false_positives[:8]
        ],
        #: >= 1 is the bar: the post-injection regression fired.
        "regression_detected": len(regressions) > 0,
        "regression_events": [
            {k: e.get(k) for k in ("detector", "device", "message")}
            for e in regressions[:4]
        ],
        "false_negatives": 0 if regressions else 1,
        "suppressed": anomalies.get("suppressed", 0),
        #: Every present energy family carried source= (empty = pass);
        #: pod energy is absent off-cluster (no kubelet) and that's fine
        #: — the sweep covers what the page actually served.
        "energy_families_present": sorted(families_present),
        "unlabeled_energy_lines": unlabeled[:8],
        "all_energy_families_source_labeled": not unlabeled,
        "energy_debug_vars": energy_vars,
        #: The zero-additional-device-queries proof: identical per-cycle
        #: device-call budget with the plane on and off.
        "device_calls_per_cycle": (
            round(calls_per_cycle, 4) if calls_per_cycle else None
        ),
        "control_calls_per_cycle": (
            round(control, 4) if control else None
        ),
    }


def interfere_soak(
    duration_s: float,
    topology: str = "v4-8",
    interval: float = 0.25,
    scrape_every_s: float = 0.5,
) -> dict:
    """``--interfere`` (ISSUE 10): two workload presets on one pool.

    Both feeds' collective-wait fraction climbs while every chip stays
    busy and neither lags the slice median — fabric contention. The
    detector must attribute ICI contention (collective_wait events) and
    must NOT flag either workload as a straggler (zero straggler/stall
    events is the acceptance bar).
    """
    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")
    if duration_s < 60 * interval:
        raise ValueError(
            f"--duration {duration_s:g} too short for the interfere script "
            f"at --interval {interval:g} (need > 60*interval)"
        )

    contend_at = 0.35 * duration_s
    workloads, backend, exporter = _lc_scaffold(topology, interval, feeds=2)
    state = {"contending": False}

    def script(t: float) -> None:
        contending = t >= contend_at
        state["contending"] = contending
        for i, wl in enumerate(workloads):
            # Two presets: different baseline rates; under contention
            # both slow down and both wait on the fabric.
            base = 2.0 if i == 0 else 3.0
            if contending:
                wl.set_rate(base * 0.6)
                wl.set_collective_wait(0.55)
            else:
                wl.set_rate(base)
                wl.set_collective_wait(0.05)

    with _EnvPatch(_lc_env(interval)):
        try:
            exporter.start()
            lat_ms, failed, t0, elapsed, conn = _lc_run(
                exporter, workloads, duration_s, scrape_every_s, script
            )
            conn.close()
            lifecycle_doc, anomalies = _lc_harvest(exporter.server.port)
        finally:
            exporter.close()
            for wl in workloads:
                wl.close()
    poll_cycles = exporter.telemetry.polls._value.get()
    calls_per_cycle = (
        sum(backend.calls.values()) / poll_cycles if poll_cycles else None
    )
    control = _lc_control_calls_per_cycle(topology, interval)

    contention = _lc_events(anomalies, ("collective_wait",))
    #: Straggler-shaped verdicts during the interference: the failure
    #: mode this scenario exists to rule out.
    stragglers = _lc_events(
        anomalies,
        ("host_straggler", "host_stall", "duty_ewma", "queue_stall"),
        (contend_at, duration_s + 60.0, t0),
    )
    lat_ms.sort()
    return {
        "mode": "interfere",
        "topology": topology,
        "interval_s": interval,
        "duration_s": round(elapsed, 1),
        "contend_at_s": round(contend_at, 1),
        "scrapes": len(lat_ms),
        "failed_scrapes": failed,
        "p50_ms": round(quantile(lat_ms, 0.5), 3) if lat_ms else None,
        "p99_ms": round(quantile(lat_ms, 0.99), 3) if lat_ms else None,
        #: >= 1 is the bar: contention attributed as contention.
        "contention_events": len(contention),
        "contention_messages": [
            e.get("message") for e in contention[:4]
        ],
        #: Zero is the bar: neither workload flagged as a straggler.
        "false_straggler_events": len(stragglers),
        "false_straggler_detail": [
            {k: e.get(k) for k in ("detector", "device", "message")}
            for e in stragglers[:8]
        ],
        "false_negatives": 0 if contention else 1,
        "lifecycle_events_total": lifecycle_doc.get("events_total", {}),
        "device_calls_per_cycle": (
            round(calls_per_cycle, 4) if calls_per_cycle else None
        ),
        "control_calls_per_cycle": (
            round(control, 4) if control else None
        ),
    }


def restore_storm_soak(
    duration_s: float,
    topology: str = "v4-8",
    interval: float = 0.25,
    scrape_every_s: float = 0.5,
    pods: int = 6,
) -> dict:
    """``--restore-storm`` (ISSUE 10): N pods checkpoint-restore
    simultaneously while debug traffic hammers the exporter and a fleet
    aggregator watches it.

    The bars: the storm classifies as ONE restore transition (not N
    anomaly storms), zero false verdicts during it, the guard sheds the
    debug burst gracefully (503s counted, /metrics unharmed), and the
    aggregator's ``tpu_fleet_visibility_ratio`` stays honest — the
    exporter's scrape path is device-free and keeps serving, so
    visibility holds 1.0; any dip must come flagged, never silent.
    """
    import threading

    from tpumon.fleet.config import FleetConfig
    from tpumon.fleet.server import build_aggregator

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")
    if duration_s < 60 * interval:
        raise ValueError(
            f"--duration {duration_s:g} too short for the restore-storm "
            f"script at --interval {interval:g} (need > 60*interval)"
        )

    storm_win = (0.25 * duration_s, 0.55 * duration_s)
    suppress_s = max(3.0, 8 * interval)
    workloads, backend, exporter = _lc_scaffold(
        topology, interval, feeds=pods,
        cfg_extra=dict(guard_debug_rps=5.0),
    )
    state = {"done": set()}

    def script(t: float) -> None:
        in_storm = storm_win[0] <= t < storm_win[1]
        if t >= storm_win[0] and "restore" not in state["done"]:
            state["done"].add("restore")
            for wl in workloads:
                wl.record_checkpoint("restore", 4.0)
        for wl in workloads:
            # During the storm every pod replays its checkpoint: step
            # progress stalls; after it, normal cadence resumes.
            wl.set_rate(0.2 if in_storm else 2.0)

    shed_probe = {"requests": 0, "shed": 0}
    stop_burst = threading.Event()

    def debug_burst(port: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        while not stop_burst.wait(0.01):
            try:
                conn.request("GET", "/anomalies")
                resp = conn.getresponse()
                resp.read()
                shed_probe["requests"] += 1
                if resp.status == 503:
                    shed_probe["shed"] += 1
            except (OSError, http.client.HTTPException):
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=5
                )
        conn.close()

    min_visibility = 1.0
    warmed = False  # visibility tracked only after the first full view
    stale_flagged = 0
    aggregator = None
    burst = None
    with _EnvPatch(_lc_env(interval)):
        try:
            exporter.start()
            aggregator = build_aggregator(
                FleetConfig(
                    port=0, addr="127.0.0.1",
                    targets=f"127.0.0.1:{exporter.server.port}",
                    interval=max(0.5, interval),
                    stale_s=max(2.0, 4 * interval),
                    evict_s=max(duration_s, 60.0),
                    history_window=0.0,
                )
            )
            aggregator.start()
            burst = threading.Thread(
                target=debug_burst, args=(exporter.server.port,),
                name="tpumon-lc-burst", daemon=True,
            )
            burst.start()
            agg_conn = http.client.HTTPConnection(
                "127.0.0.1", aggregator.server.port, timeout=10
            )

            base_script = script

            def script_with_agg(t: float) -> None:
                nonlocal min_visibility, stale_flagged, warmed
                base_script(t)
                try:
                    agg_conn.request("GET", "/metrics")
                    body = agg_conn.getresponse().read()
                except (OSError, http.client.HTTPException):
                    agg_conn.close()
                    return
                stats = _page_stats(body)
                vis = stats["visibility"]
                if vis is not None:
                    if vis >= 1.0:
                        # Warm-up gate: a cold aggregator's first cycles
                        # legitimately read 0 — the honesty claim is
                        # about the storm, not the boot.
                        warmed = True
                    if warmed:
                        min_visibility = min(min_visibility, vis)
                if stats["stale_flag"] == 1.0:
                    stale_flagged += 1

            lat_ms, failed, t0, elapsed, conn = _lc_run(
                exporter, workloads, duration_s, scrape_every_s,
                script_with_agg,
            )
            conn.close()
            agg_conn.close()
            # The burst drained the debug-class token bucket (that shed
            # IS the evidence); stop it and let the bucket refill before
            # the harvest uses the same debug-class endpoints.
            stop_burst.set()
            if burst is not None:
                burst.join(timeout=5)
                burst = None
            for attempt in range(6):
                try:
                    lifecycle_doc, anomalies = _lc_harvest(
                        exporter.server.port
                    )
                    break
                except ValueError:
                    if attempt == 5:
                        raise
                    time.sleep(1.0)
        finally:
            stop_burst.set()
            if burst is not None:
                burst.join(timeout=5)
            if aggregator is not None:
                aggregator.close()
            exporter.close()
            for wl in workloads:
                wl.close()
    poll_cycles = exporter.telemetry.polls._value.get()
    calls_per_cycle = (
        sum(backend.calls.values()) / poll_cycles if poll_cycles else None
    )
    control = _lc_control_calls_per_cycle(topology, interval)

    false_positives = _lc_events(
        anomalies, _LC_FALSE_SET,
        (storm_win[0] - 1.0, storm_win[1] + suppress_s, t0),
    )
    restores = lifecycle_doc.get("events_total", {}).get("restore", 0)
    lat_ms.sort()
    return {
        "mode": "restore-storm",
        "topology": topology,
        "pods": pods,
        "interval_s": interval,
        "duration_s": round(elapsed, 1),
        "storm_window_s": [round(storm_win[0], 1), round(storm_win[1], 1)],
        "scrapes": len(lat_ms),
        "failed_scrapes": failed,
        "p50_ms": round(quantile(lat_ms, 0.5), 3) if lat_ms else None,
        "p99_ms": round(quantile(lat_ms, 0.99), 3) if lat_ms else None,
        #: One restore transition for the whole storm is the bar (the N
        #: simultaneous restores land inside one suppression window).
        "restore_events": restores,
        "lifecycle_events_total": lifecycle_doc.get("events_total", {}),
        "false_positives": len(false_positives),
        "false_positive_events": [
            {k: e.get(k) for k in ("detector", "device", "message")}
            for e in false_positives[:8]
        ],
        "suppressed": anomalies.get("suppressed", 0),
        #: Guard-plane shedding evidence: the debug burst was refused
        #: gracefully while every well-behaved /metrics scrape in
        #: lat_ms/failed_scrapes was answered.
        "debug_burst": dict(shed_probe),
        #: Fleet honesty: the exporter kept serving through the storm,
        #: so visibility must hold 1.0; any dip arrives stale-flagged.
        "fleet_min_visibility": round(min_visibility, 3),
        "fleet_stale_flagged_scrapes": stale_flagged,
        "device_calls_per_cycle": (
            round(calls_per_cycle, 4) if calls_per_cycle else None
        ),
        "control_calls_per_cycle": (
            round(control, 4) if control else None
        ),
    }


def _spawn_fleetsim(
    nodes: int, topology: str, node_interval: float,
    churn: float | None = None,
):
    """One ``tools/fleetsim.py`` subprocess simulating ``nodes`` exporter
    endpoints. A separate process (own GIL) so simulation work never
    shares the aggregator's interpreter; a SINGLE process because N real
    exporter interpreters oversubscribe a small runner into scheduler
    noise (measured: upstream response p50 ~50 ms of pure process-wakeup
    latency with 64 children on 2 cores — the tier under test was idle).
    Returns (proc, urls)."""
    cmd = [
        sys.executable, "-m", "tpumon.tools.fleetsim",
        "--nodes", str(nodes), "--topology", topology,
        "--node-interval", str(node_interval),
    ]
    if churn is not None:
        cmd += ["--churn", str(churn)]
    proc = subprocess.Popen(
        cmd,
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    # The sim prints PORTS as soon as its listeners exist (sub-second).
    line = proc.stdout.readline()  # deadline: fleetsim prints PORTS immediately on startup or dies (outer `timeout` bounds the CI job)
    if not line.startswith("PORTS "):
        proc.kill()
        raise RuntimeError(f"fleetsim failed to start: {line!r}")
    ports = [int(p) for p in line.split()[1:]]
    return proc, [f"http://127.0.0.1:{port}" for port in ports]


def fleet_soak(
    duration_s: float,
    nodes: int = 16,
    kill: int = 8,
    topology: str = "v4-8",
    scrape_every_s: float = 1.0,
    interval: float = 1.0,
    node_interval: float | None = None,
) -> dict:
    """Fleet-tier soak (ISSUE 6 acceptance evidence): ``nodes``
    simulated exporter endpoints (tools/fleetsim.py — one subprocess,
    N ports, genuine fake-backend pages with per-node identities)
    behind one aggregator shard, scraped at ``scrape_every_s`` for
    ``duration_s``; at half time ``kill`` nodes die mid-run (half
    freeze into zombie pages, half refuse connections). The record
    captures the aggregator's scrape latency distribution over the
    PRE-AGGREGATED page, rollup freshness, the stale-flagged (never
    absent) degradation while nodes are dark, and proof that per-node
    series are not re-exported through the tier.
    """
    from tpumon.fleet.config import FleetConfig
    from tpumon.fleet.server import build_aggregator

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")
    kill = max(0, min(kill, nodes))
    if node_interval is None:
        node_interval = interval

    sim_proc = None
    lat_ms: list[float] = []
    bad_pages = 0
    failed_scrapes = 0
    leaked_series = 0
    stale_seen = 0
    dark_seen = 0
    fresh_scrapes = 0
    warm_s = None
    aggregator = None
    conn = None
    prev_switch = sys.getswitchinterval()
    try:
        if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
            # Finer than the exporter soak's 1 ms: the aggregator hosts
            # N fetch/parse threads next to serving, and shorter GIL
            # quanta shave the scrape tail (measured p99 6.4 → 5.2 ms
            # at 64 nodes).
            sys.setswitchinterval(min(prev_switch, 0.0005))
        sim_proc, urls = _spawn_fleetsim(nodes, topology, node_interval)
        aggregator = build_aggregator(
            FleetConfig(
                port=0, addr="127.0.0.1", targets=",".join(urls),
                interval=interval,
                # Stale fast enough to observe inside the soak window
                # (but safely above the node poll cadence the data
                # timestamps follow); eviction deliberately beyond it so
                # the record shows stale-flagged rollups, not an
                # instant disappearance.
                stale_s=max(2.0, 3.0 * interval, 2.5 * node_interval),
                evict_s=max(duration_s, 60.0),
            )
        )
        aggregator.start()

        conn = http.client.HTTPConnection(
            "127.0.0.1", aggregator.server.port, timeout=10
        )

        def fleet_doc() -> dict:
            # The public /fleet API — the soak observes the tier the way
            # operators do, never through aggregator internals.
            conn.request("GET", "/fleet")
            return json.loads(conn.getresponse().read())

        # Warm-up gate: measurement starts once every node has reported
        # (a cold fleet is not evidence about the tier).
        warm_t0 = time.time()
        warm_deadline = warm_t0 + max(60.0, 2.0 * nodes)
        while time.time() < warm_deadline:
            if fleet_doc()["fleet"].get("hosts", {}).get("up", 0) >= nodes:
                break
            time.sleep(0.25)
        warm_s = round(time.time() - warm_t0, 1)
        t0 = time.time()
        next_at = t0
        killed = False
        while time.time() - t0 < duration_s:
            if not killed and kill and time.time() - t0 >= duration_s / 2:
                sim_proc.stdin.write(f"kill {kill}\n")
                sim_proc.stdin.flush()
                killed = True
            s = time.perf_counter()
            try:
                conn.request("GET", "/metrics")
                body = conn.getresponse().read()
            except (OSError, http.client.HTTPException):
                failed_scrapes += 1
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", aggregator.server.port, timeout=10
                )
            else:
                lat_ms.append((time.perf_counter() - s) * 1e3)
                if b"tpu_fleet_hosts{" not in body:
                    bad_pages += 1
                if b"accelerator_duty_cycle_percent" in body:
                    leaked_series += 1  # per-node series must NOT re-export
                up = re.search(
                    rb'tpu_fleet_hosts\{pool="",scope="fleet",slice="",'
                    rb'state="up"\} (\S+)', body,
                )
                stale = re.search(
                    rb'tpu_fleet_hosts\{pool="",scope="fleet",slice="",'
                    rb'state="stale"\} (\S+)', body,
                )
                dark = re.search(
                    rb'tpu_fleet_hosts\{pool="",scope="fleet",slice="",'
                    rb'state="dark"\} (\S+)', body,
                )
                expected_up = nodes - (kill if killed else 0)
                if up and float(up.group(1)) >= min(expected_up, nodes):
                    fresh_scrapes += 1
                if stale and float(stale.group(1)) > 0:
                    stale_seen += 1
                if dark and float(dark.group(1)) > 0:
                    dark_seen += 1
            next_at += scrape_every_s
            time.sleep(max(0.0, next_at - time.time()))
        elapsed_s = time.time() - t0
        final = fleet_doc()
        final_hosts = dict(final["fleet"].get("hosts", {}))
        conn.request("GET", "/debug/vars")
        cycles = json.loads(conn.getresponse().read()).get("cycles")
    finally:
        if conn is not None:
            conn.close()
        if aggregator is not None:
            aggregator.close()
        if sim_proc is not None:
            sim_proc.terminate()
            try:
                sim_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                sim_proc.kill()
        sys.setswitchinterval(prev_switch)

    lat_ms.sort()

    def _q(p: float):
        return round(quantile(lat_ms, p), 3) if lat_ms else None

    return {
        "mode": "fleet",
        "nodes": nodes,
        "killed_mid_run": kill,
        "topology": topology,
        "node_interval_s": node_interval,
        "warmup_s": warm_s,
        "scrapes": len(lat_ms),
        "duration_s": round(elapsed_s, 1),
        "p50_ms": _q(0.5),
        "p99_ms": _q(0.99),
        "max_ms": round(lat_ms[-1], 3) if lat_ms else None,
        "bad_pages": bad_pages,
        "failed_scrapes": failed_scrapes,
        #: Scrapes whose page re-exported a per-node device family —
        #: must be 0 (the tier serves rollups, never raw fan-through).
        "per_node_series_leaks": leaked_series,
        #: Scrapes whose fleet-level up-host count matched the live
        #: node count — rollup freshness through the kill event.
        "fresh_scrapes": fresh_scrapes,
        #: Scrapes observing stale-flagged (not absent) rollups while
        #: killed nodes aged toward eviction.
        "stale_flagged_scrapes": stale_seen,
        "dark_flagged_scrapes": dark_seen,
        "collect_cycles": cycles,
        "final_hosts": final_hosts,
    }


def fleet_delta_soak(
    duration_s: float,
    nodes: int = 640,
    topology: str = "v4-8",
    interval: float = 1.0,
    scrape_every_s: float = 1.0,
    churn: float = 0.02,
    churn_high: float = 0.5,
    kill: int = 32,
    node_interval: float | None = None,
    controls: bool = True,
    check_leaks: bool = False,
    mode: str = "fleet-delta",
) -> dict:
    """Delta fan-in acceptance soak (ROADMAP item 3, ISSUE 13): ``nodes``
    simulated exporters (10× the PR 6 64-node evidence at the default
    640) behind one aggregator shard negotiating the delta protocol.

    Phases:

    1. **idle** — content churn ``churn`` (default 2%): steady-state
       fan-in bytes/node/cycle and collect-cycle CPU with the fleet
       mostly heartbeating.
    2. **churn** — the sim dials content churn to ``churn_high``: the
       same measurements, so the record shows CPU/bytes tracking change
       rate on the same box, same fleet size.
    3. **honesty** — ``kill`` nodes die (half zombie, half
       listener-down), then a partition+heal wave forces mid-stream
       reconnects and pruned-base resyncs: every scrape is checked for
       fabricated freshness (up-count above truly-live is a violation),
       and the kill must land as stale/dark flags.
    4. **controls** (after the main aggregator closes): a delta-on
       shard over a quarter-size subset (same churn — the
       flat-as-idle-fleet-grows evidence) and a delta-OFF shard over
       the full fleet (the full-snapshot-per-fetch baseline the ≤10%
       bytes gate divides against).

    ``controls=False`` is the FLEET-SCALE shape (``--fleet-scale``,
    ISSUE 15: thousands of nodes on one box): the quarter-size control
    is skipped and the delta-off baseline runs over a small subset
    instead of the full fleet — snapshot bytes/node/cycle is
    size-independent, so the ratio stays honest while the box is
    spared a second full-fleet warmup. ``check_leaks=True`` scans every
    scrape for re-exported per-node device families (the
    ``per_node_series_leaks == 0`` acceptance gate).
    """
    from tpumon.fleet.config import FleetConfig
    from tpumon.fleet.server import build_aggregator
    from tpumon.tools.measure import fanin_stats, fanin_window

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")
    if node_interval is None:
        node_interval = interval
    kill = max(0, min(kill, nodes // 2))
    stale_s = max(2.0, 3.0 * interval, 2.5 * node_interval)

    sim_proc = None
    aggs: list = []
    lat_ms: list[float] = []
    failed_scrapes = 0
    honesty_violations = 0
    leaked_series = 0
    prev_switch = sys.getswitchinterval()

    def mk_agg(targets: list[str], delta: bool = True):
        agg = build_aggregator(
            FleetConfig(
                port=0, addr="127.0.0.1", targets=",".join(targets),
                interval=interval, stale_s=stale_s,
                evict_s=max(duration_s * 2, 240.0), delta=delta,
            )
        )
        agg.start()
        aggs.append(agg)
        return agg

    def close_agg(agg) -> None:
        agg.close()
        aggs.remove(agg)

    def scrape(agg) -> str | None:
        nonlocal failed_scrapes, leaked_series
        conn = http.client.HTTPConnection(
            "127.0.0.1", agg.server.port, timeout=10
        )
        try:
            t0 = time.perf_counter()
            conn.request("GET", "/metrics")
            body = conn.getresponse().read()
            lat_ms.append((time.perf_counter() - t0) * 1e3)
            if check_leaks and b"accelerator_duty_cycle_percent" in body:
                leaked_series += 1  # per-node series must NOT re-export
            return body.decode()
        except (OSError, http.client.HTTPException):
            failed_scrapes += 1
            return None
        finally:
            conn.close()

    def hosts_of(page: str) -> dict:
        stats = _page_stats(page.encode())
        return {
            "up": stats["up"] or 0.0,
            "stale": stats["stale"] or 0.0,
            "dark": stats["dark"] or 0.0,
            "visibility": stats["visibility"],
        }

    def warm(agg, want_up: int, deadline_s: float) -> float:
        t0 = time.time()
        while time.time() - t0 < deadline_s:
            page = scrape(agg)
            if page and (hosts_of(page)["up"] or 0) >= want_up:
                return round(time.time() - t0, 1)
            time.sleep(max(0.25, interval / 2))
        return round(time.time() - t0, 1)

    def stats_retrying(agg, attempts: int = 3) -> dict:
        """fanin_stats off a page that actually parsed: a transient
        failed scrape at a window boundary must not zero (or overstate)
        a phase's byte/counter deltas."""
        for _ in range(attempts):
            page = scrape(agg)
            if page:
                return fanin_stats(page)
            time.sleep(scrape_every_s)
        return fanin_stats("")

    def measure(agg, window_s: float, live: int, check_honesty=False):
        nonlocal honesty_violations
        before = stats_retrying(agg)
        after = before
        dirty_samples: list[float] = []
        t0 = time.time()
        next_at = t0
        while time.time() - t0 < window_s:
            next_at += scrape_every_s
            time.sleep(max(0.0, next_at - time.time()))
            page = scrape(agg)
            if not page:
                continue
            after = fanin_stats(page)  # last SUCCESSFUL read wins
            m = re.search(
                r"^tpu_fleet_rollup_dirty_nodes (\S+)", page, re.M
            )
            if m:
                dirty_samples.append(float(m.group(1)))
            if check_honesty:
                h = hosts_of(page)
                if h["up"] > live:
                    honesty_violations += 1  # fabricated freshness
        window = fanin_window(before, after)
        elapsed = max(0.001, time.time() - t0)
        cycles = elapsed / interval
        total_bytes = sum(window["bytes"].values())
        window["bytes_per_node_cycle"] = (
            round(total_bytes / (max(1, live) * cycles), 1)
        )
        frames = window["frames"]
        delta_frames = sum(
            v for k, v in frames.items() if k.endswith("/delta")
        )
        window["delta_frame_share"] = (
            round(delta_frames / sum(frames.values()), 4)
            if frames else None
        )
        # Deterministic churn signal: feeds whose rollup-relevant
        # content changed per cycle (the collect wall-clock means above
        # are scheduler-sensitive on small shared boxes; this is not).
        window["dirty_nodes_mean"] = (
            round(sum(dirty_samples) / len(dirty_samples), 1)
            if dirty_samples else None
        )
        return window

    def sim_cmd(command: str, expect_lines: int) -> None:
        sim_proc.stdin.write(command + "\n")
        sim_proc.stdin.flush()
        for _ in range(expect_lines):
            sim_proc.stdout.readline()  # deadline: fleetsim answers each control line immediately (outer `timeout` bounds the job)

    try:
        if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
            sys.setswitchinterval(min(prev_switch, 0.0005))
        sim_proc, urls = _spawn_fleetsim(
            nodes, topology, node_interval, churn=churn,
        )
        agg = mk_agg(urls, delta=True)
        warmup_s = warm(agg, nodes, max(90.0, nodes * 0.2))

        phase_idle = measure(agg, duration_s * 0.3, nodes)
        sim_cmd(f"churn {churn_high}", 1)
        time.sleep(2 * node_interval)  # let the new churn rate land
        phase_churn = measure(agg, duration_s * 0.3, nodes)
        sim_cmd(f"churn {churn}", 1)

        # -- honesty: kills, then partition + heal (reconnect/resync) --
        kill_t0 = time.time()
        sim_cmd(f"kill {kill}", kill)
        live = nodes - kill
        settle = stale_s + 2 * interval + 2 * node_interval + 2.0
        deadline = time.time() + max(settle * 3, duration_s * 0.2)
        flagged = None
        while time.time() < deadline:
            time.sleep(scrape_every_s)
            page = scrape(agg)
            if not page:
                continue
            h = hosts_of(page)
            # Dead nodes legitimately read "up" until their last-good
            # data ages past stale_s; fabricated freshness is an
            # up-count above truly-live AFTER the settle window.
            if time.time() - kill_t0 >= settle and h["up"] > live:
                honesty_violations += 1
            if h["stale"] + h["dark"] >= kill and h["up"] <= live:
                flagged = h
                break
        kill_flags_correct = flagged is not None
        partition = max(1, min(64, live // 8))
        sim_cmd(f"partition {partition}", partition)
        time.sleep(settle)
        page = scrape(agg)
        partition_visibility = (
            hosts_of(page)["visibility"] if page else None
        )
        resync_before = stats_retrying(agg)["resyncs"]
        resync_after = resync_before
        sim_cmd("heal", 1)
        recovered = False
        # The recovery envelope must cover the adaptive backoff the
        # partition escalated (jittered, doubling toward the cap):
        # mass return is DESIGNED to spread, not to storm back at once.
        deadline = time.time() + max(settle * 3, duration_s * 0.2) + 60.0
        while time.time() < deadline:
            time.sleep(scrape_every_s)
            page = scrape(agg)
            if not page:
                continue
            resync_after = fanin_stats(page)["resyncs"]
            h = hosts_of(page)
            if h["up"] > live:
                honesty_violations += 1
            if h["up"] >= live:
                recovered = True
                break
        recovery_resyncs = {
            reason: resync_after.get(reason, 0.0)
            - resync_before.get(reason, 0.0)
            for reason in resync_after
        }
        close_agg(agg)

        # -- controls: quarter-size subset (delta) + snapshot baseline --
        control_s = min(30.0, max(10 * interval, duration_s * 0.25))
        if controls:
            subset = urls[-max(nodes // 4, 1):]
            agg_sub = mk_agg(subset, delta=True)
            warm(agg_sub, len(subset), max(60.0, len(subset) * 0.2))
            control_subset = measure(agg_sub, control_s, len(subset))
            close_agg(agg_sub)

            snap_targets = urls
            snap_live = live
            agg_snap = mk_agg(snap_targets, delta=False)
            warm(agg_snap, snap_live, max(90.0, nodes * 0.2))
            control_snapshot = measure(agg_snap, control_s, snap_live)
            close_agg(agg_snap)
        else:
            # Fleet-scale shape: snapshot bytes/node/cycle is
            # size-independent, so a small live-node subset gives the
            # same baseline without a second full-fleet warmup.
            control_subset = None
            # Kill victims came from the list head — pick live nodes.
            snap_targets = urls[kill: kill + max(1, min(64, nodes // 8))]
            snap_live = len(snap_targets)
            agg_snap = mk_agg(snap_targets, delta=False)
            warm(agg_snap, snap_live, max(60.0, snap_live * 0.5))
            control_snapshot = measure(agg_snap, control_s, snap_live)
            close_agg(agg_snap)
    finally:
        for agg in list(aggs):
            try:
                agg.close()
            except Exception:
                pass
        if sim_proc is not None:
            sim_proc.terminate()
            try:
                sim_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                sim_proc.kill()
        sys.setswitchinterval(prev_switch)

    lat_ms.sort()

    def _q(p: float):
        return round(quantile(lat_ms, p), 3) if lat_ms else None

    delta_bpnc = phase_idle["bytes_per_node_cycle"]
    snap_bpnc = control_snapshot["bytes_per_node_cycle"]
    idle_ms = phase_idle["collect_ms_per_cycle"]
    churn_ms = phase_churn["collect_ms_per_cycle"]
    subset_ms = (
        control_subset["collect_ms_per_cycle"]
        if control_subset is not None else None
    )
    return {
        "mode": mode,
        "nodes": nodes,
        "topology": topology,
        "node_interval_s": node_interval,
        "churn_low": churn,
        "churn_high": churn_high,
        "killed": kill,
        "warmup_s": warmup_s,
        "phases": {
            "idle": phase_idle,
            "churn": phase_churn,
            "subset_idle": control_subset,
            "snapshot_idle": control_snapshot,
        },
        "snapshot_baseline_nodes": snap_live,
        "fanin": {
            #: Steady-state wire cost per node per collect cycle, delta
            #: protocol at low churn vs the full-snapshot baseline —
            #: the ≤10% acceptance gate.
            "delta_idle_bytes_per_node_cycle": delta_bpnc,
            "snapshot_bytes_per_node_cycle": snap_bpnc,
            "delta_vs_snapshot_ratio": (
                round(delta_bpnc / snap_bpnc, 4) if snap_bpnc else None
            ),
            "delta_frame_share_idle": phase_idle["delta_frame_share"],
        },
        "cpu": {
            #: Collect-cycle mean ms per phase: churn scaling on one
            #: box (churn/idle should be >1) and fleet-size scaling at
            #: constant churn (full/subset should be << size ratio —
            #: "flat as idle node count grows").
            "idle_ms_per_cycle": idle_ms,
            "churn_ms_per_cycle": churn_ms,
            "subset_idle_ms_per_cycle": subset_ms,
            "snapshot_idle_ms_per_cycle": (
                control_snapshot["collect_ms_per_cycle"]
            ),
            "churn_scaling": (
                round(churn_ms / idle_ms, 2)
                if churn_ms and idle_ms else None
            ),
            "size_scaling_vs_4x_nodes": (
                round(idle_ms / subset_ms, 2)
                if idle_ms and subset_ms else None
            ),
        },
        "honesty": {
            "violations": honesty_violations,
            "kill_flags_correct": kill_flags_correct,
            "final_kill_flags": flagged,
            "partition_visibility": partition_visibility,
            "healed_recovered": recovered,
            "recovery_resyncs": recovery_resyncs,
        },
        "scrapes": len(lat_ms),
        "failed_scrapes": failed_scrapes,
        #: Scrapes whose page re-exported a per-node device family —
        #: must be 0 (None when leak scanning was not requested).
        "per_node_series_leaks": leaked_series if check_leaks else None,
        "p50_ms": _q(0.5),
        "p99_ms": _q(0.99),
    }


class _ScriptedLedgerNode:
    """One scripted exporter endpoint for the ledger soak: a real HTTP
    /metrics server whose exposition text follows the phase script
    (duty, step rate, lifecycle transitions, checkpoint counters) —
    the aggregator's ingest path sees genuine pages, the goodput
    ledger sees genuine signals, and ``dead`` makes the endpoint
    answer 503 so the feed ages to stale exactly like a killed pod."""

    def __init__(self, slice_name: str, host: str, chips: int = 4,
                 pool: str = "v5p-16") -> None:
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.slice_name = slice_name
        self.host = host
        self.chips = chips
        self.pool = pool
        self.state = {
            "duty": 70.0,
            "step_rate": 2.0,
            "transition": 0.0,
            "events": {"preemption": 0.0, "resize": 0.0, "restore": 0.0},
            "ckpt_saves": 0.0,
            "wait": 0.05,
            "dead": False,
        }
        node = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                if node.state["dead"]:
                    self.send_response(503)
                    self.send_header("Connection", "close")
                    self.end_headers()
                    return
                body = node.page().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_port
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def page(self) -> str:
        s = self.state
        lines = []
        for chip in range(self.chips):
            lines.append(
                f'accelerator_info{{chip="{chip}",coords="{chip},0,0",'
                f'accelerator="{self.pool}",slice="{self.slice_name}",'
                f'host="{self.host}"}} 1.0'
            )
            lines.append(
                f'accelerator_duty_cycle_percent{{chip="{chip}"}} '
                f"{s['duty']}"
            )
        lines.append(f"accelerator_device_count {self.chips}")
        lines.append(
            f"collector_last_poll_timestamp_seconds {time.time()}"
        )
        lines.append(f"tpu_lifecycle_state {s['transition']}")
        for kind, count in s["events"].items():
            lines.append(
                f'tpu_lifecycle_events_total{{kind="{kind}"}} {count}'
            )
        lines.append(
            f'tpu_lifecycle_checkpoints_total{{op="save"}} '
            f"{s['ckpt_saves']}"
        )
        lines.append(f"tpu_lifecycle_step_rate {s['step_rate']}")
        lines.append(
            f"tpu_lifecycle_collective_wait_fraction {s['wait']}"
        )
        return "\n".join(lines) + "\n"

    def close(self) -> None:
        self.server.shutdown()
        self.server.server_close()


def ledger_soak(
    duration_s: float,
    nodes: int = 4,
    interval: float = 0.25,
    scrape_every_s: float = 1.0,
    spool_dir: str | None = None,
) -> dict:
    """Ledger acceptance soak (ISSUE 14): a scripted fleet walks
    through productive → checkpoint → preemption → restore → idle →
    KILL → recovery phases behind a ledger-enabled aggregator (with a
    warm restart between idle and the kill window), and the record
    carries the asserted evidence: per-phase bucket accrual, the
    conservation invariant (buckets sum == observed wall × chips, per
    job AND against an independent wall-clock expectation), honesty
    (the kill window lands in unaccounted, idle accrues ZERO), spool
    restore, and a range query answered from the store."""
    import shutil as _shutil
    import tempfile
    import urllib.request

    from tpumon.fleet.config import FleetConfig
    from tpumon.fleet.server import build_aggregator

    if duration_s < 20 * interval:
        raise ValueError(
            "ledger soak needs >= 20 intervals to walk its phases"
        )
    own_spool = spool_dir is None
    if own_spool:
        spool_dir = tempfile.mkdtemp(prefix="tpumon-ledger-soak-")
    sim = [
        _ScriptedLedgerNode(
            f"job-{'ab'[i % 2]}", f"n{i}",
        )
        for i in range(nodes)
    ]
    chips_total = sum(n.chips for n in sim)

    def build(stale_s: float) -> object:
        cfg = FleetConfig(
            port=0, addr="127.0.0.1",
            targets=",".join(n.url for n in sim),
            interval=interval, stale_s=stale_s, evict_s=3600.0,
            guard=False, trace=False,
            # Short recovery: dead feeds re-probe within ~8 intervals
            # so the post-kill phase demonstrably returns to productive
            # inside the soak window (the default 60 s ceiling is sized
            # for real fleets).
            poll_backoff_max_s=max(1.0, 8 * interval),
            ledger_spool_dir=spool_dir, ledger_spool_every_s=1.0,
        )
        agg = build_aggregator(cfg)
        agg.start()
        return agg

    stale_s = 3.0 * interval
    agg = build(stale_s)
    failed_scrapes = 0
    scrapes = 0

    def goodput_doc() -> dict:
        with urllib.request.urlopen(
            agg.url + "/ledger?view=goodput", timeout=5
        ) as resp:
            return json.loads(resp.read())

    def totals() -> dict:
        doc = goodput_doc()
        out = dict(doc["totals"])
        out["_jobs"] = doc["jobs"]
        out["_gap"] = doc["gap_seconds"]
        return out

    def delta(a: dict, b: dict) -> dict:
        return {
            k: round(b[k] - a[k], 3)
            for k in b
            if not k.startswith("_") and b[k] - a[k] > 1e-9
        }

    #: (name, end-fraction, state mutation applied at phase START).
    def enter_productive():
        for n in sim:
            n.state.update(duty=70.0, step_rate=2.0, transition=0.0)

    def enter_checkpoint():
        for n in sim:
            n.state.update(duty=15.0, step_rate=0.0, transition=0.0)

    def enter_preempt():
        for n in sim:
            n.state["events"]["preemption"] += 1
            n.state.update(transition=1.0, duty=5.0, step_rate=0.0)

    def enter_restore():
        for n in sim:
            n.state["events"]["restore"] += 1
            n.state.update(transition=1.0, duty=5.0, step_rate=0.0)

    def enter_idle():
        for n in sim:
            n.state.update(duty=1.0, step_rate=0.0, transition=0.0)

    def enter_kill():
        for n in sim:
            n.state["dead"] = True

    def enter_recover():
        for n in sim:
            n.state["dead"] = False
            n.state.update(duty=70.0, step_rate=2.0, transition=0.0)

    phases = [
        ("productive", 0.22, enter_productive),
        ("checkpoint", 0.38, enter_checkpoint),
        ("preempted", 0.50, enter_preempt),
        ("restore", 0.62, enter_restore),
        ("idle", 0.72, enter_idle),
        ("kill", 0.88, enter_kill),
        ("recovery", 1.00, enter_recover),
    ]
    t0 = time.time()
    time.sleep(3 * interval)  # first accounting windows land
    t_first = time.time()
    phase_records: dict[str, dict] = {}
    restart_info: dict = {}
    try:
        before = totals()
        for name, end_frac, enter in phases:
            if name == "kill":
                # Warm restart between idle and the kill window: the
                # restart must restore every tier and ledger its gap.
                agg.close()
                gap_target = max(1.0, 4 * interval)
                time.sleep(gap_target)
                agg = build(stale_s)
                time.sleep(2 * interval)
                with urllib.request.urlopen(
                    agg.url + "/ledger", timeout=5
                ) as resp:
                    index = json.loads(resp.read())
                restart_info = {
                    "restored": index.get("restored"),
                    "gap_seconds": round(index.get("gap_seconds", 0.0), 3),
                    "gap_target": gap_target,
                }
                before = totals()  # re-anchor (gap charged at load)
            enter()
            if name == "checkpoint":
                # Advance the save counter every half interval so EVERY
                # accounting window inside the phase sees an advance.
                deadline = t0 + end_frac * duration_s
                while time.time() < deadline:
                    for n in sim:
                        n.state["ckpt_saves"] += 1
                    time.sleep(interval / 2.0)
            else:
                while time.time() < t0 + end_frac * duration_s:
                    time.sleep(scrape_every_s)
                    scrapes += 1
                    try:
                        with urllib.request.urlopen(
                            agg.url + "/metrics", timeout=5
                        ) as resp:
                            if resp.status != 200:
                                failed_scrapes += 1
                            resp.read()
                    except OSError:
                        failed_scrapes += 1
            # Give the last windows of the phase one cycle to land.
            time.sleep(2 * interval)
            after = totals()
            phase_records[name] = delta(before, after)
            before = after
        t_end = time.time()
        final = goodput_doc()
        # Conservation, two ways. Exact: per job, buckets sum to the
        # reported chip-seconds (identity by construction — pinned so a
        # refactor cannot quietly break it). Independent: summed
        # chip-seconds match wall-clock × chips (the soak's own clock),
        # downtime included because the gap charge covers it.
        worst_exact = 0.0
        total_chip_seconds = 0.0
        for job in final["jobs"]:
            worst_exact = max(
                worst_exact,
                abs(sum(job["buckets"].values()) - job["chip_seconds"]),
            )
            total_chip_seconds += job["chip_seconds"]
        expected = chips_total * (t_end - t_first)
        tolerance = chips_total * (6 * interval + 2.0)
        conservation_ratio = (
            total_chip_seconds / expected if expected > 0 else None
        )
        # Honesty: once the kill window crosses the stale threshold,
        # accrual must land in unaccounted and NEVER in idle — a
        # partition reading as an idle fleet is the lie this ledger
        # exists to not tell. The pre-stale tail (last-good data still
        # inside the freshness budget, honestly classified from the
        # frozen page) is the one allowed idle contribution.
        kill = phase_records.get("kill", {})
        idle_tail_allowance = chips_total * (stale_s + 2 * interval)
        violations = 0
        if kill.get("idle", 0.0) > idle_tail_allowance:
            violations += 1
        if kill.get("unaccounted", 0.0) <= 0.0:
            violations += 1
        # Range query over the whole soak from the store.
        with urllib.request.urlopen(
            agg.url + "/ledger?family=tpu_fleet_duty_cycle_percent"
            f"&scope=fleet&start={t0:.3f}&end={time.time():.3f}",
            timeout=5,
        ) as resp:
            rq = json.loads(resp.read())
        record = {
            "mode": "ledger",
            "duration_s": round(time.time() - t0, 1),
            "nodes": nodes,
            "chips_total": chips_total,
            "interval": interval,
            "phases": phase_records,
            "overall_buckets": {
                k: round(v, 3) for k, v in final["totals"].items()
            },
            "gap_seconds": round(final["gap_seconds"], 3),
            "conservation_exact_worst_abs": round(worst_exact, 9),
            "conservation_ratio": (
                round(conservation_ratio, 4)
                if conservation_ratio is not None else None
            ),
            "conservation_tolerance_ratio": round(
                tolerance / expected, 4
            ) if expected else None,
            "honesty_violations": violations,
            "kill_idle_tail_allowance": round(idle_tail_allowance, 3),
            "restart": restart_info,
            "query": {
                "tier": rq.get("tier"),
                "series": len(rq.get("series", [])),
                "points": sum(
                    len(s["points"]) for s in rq.get("series", [])
                ),
            },
            "scrapes": scrapes,
            "failed_scrapes": failed_scrapes,
        }
        return record
    finally:
        agg.close()
        for n in sim:
            n.close()
        if own_spool:
            _shutil.rmtree(spool_dir, ignore_errors=True)


def capacity_soak(
    duration_s: float,
    interval: float = 0.25,
    scrape_every_s: float = 1.0,
) -> dict:
    """Capacity-forecast acceptance soak (ISSUE 17): a scripted fleet
    whose duty ramps LINEARLY at a known rate behind a forecast-enabled
    aggregator, plus a sparse pool that comes alive too late to clear
    the history gate. The record carries the asserted evidence:

    - the forecast's days-to-saturation against the script's own
      ground-truth ETA (the ramp rate is ours, so the truth is exact);
    - the sparse pool answering ``insufficient_history`` and NEVER a
      date;
    - the top-k waste ranking's conservation block (sum over groups ==
      pinned total chip-seconds, exact);
    - a bounded grouped range query walked to completion via its
      ``next_start`` cursors equaling the unbounded fold, point for
      point.
    """
    import urllib.request

    from tpumon.fleet.config import FleetConfig
    from tpumon.fleet.server import build_aggregator

    if duration_s < 40 * interval:
        raise ValueError(
            "capacity soak needs >= 40 intervals for a fittable ramp"
        )
    # Ramp pool: duty climbs 50% -> 86% over the soak, so saturation
    # (95%) sits a known distance PAST the end — the forecast must
    # extrapolate, not read it off. job-b carries collective-wait
    # contention so the waste ranking has a real top entry.
    duty0 = 50.0
    rate = 36.0 / duration_s  # percent per second
    ramp = [
        _ScriptedLedgerNode("job-a", "n0", pool="v5p-16"),
        _ScriptedLedgerNode("job-b", "n1", pool="v5p-16"),
    ]
    ramp[1].state["wait"] = 0.45
    # Sparse pool: dead until 75% of the soak; its history can never
    # reach the gate below, so a served date would be a fabrication.
    sparse = _ScriptedLedgerNode("job-sparse", "n2", pool="v4-8")
    sparse.state["dead"] = True
    sim = ramp + [sparse]
    for n in sim:
        n.state.update(duty=duty0, step_rate=2.0)
    min_history_s = 0.45 * duration_s

    cfg = FleetConfig(
        port=0, addr="127.0.0.1",
        targets=",".join(n.url for n in sim),
        interval=interval, stale_s=3.0 * interval, evict_s=3600.0,
        guard=False, trace=False,
        poll_backoff_max_s=max(1.0, 8 * interval),
        ledger_forecast_min_history_s=min_history_s,
        ledger_forecast_every_s=interval,
    )
    agg = build_aggregator(cfg)
    agg.start()

    def fetch(path: str) -> dict:
        with urllib.request.urlopen(agg.url + path, timeout=5) as resp:
            return json.loads(resp.read())

    t0 = time.time()
    time.sleep(3 * interval)  # first accounting windows land
    t_first = time.time()
    sparse_alive_frac = 0.75
    try:
        last_scrape = 0.0
        scrapes = failed_scrapes = 0
        while True:
            now = time.time()
            if now >= t0 + duration_s:
                break
            duty = min(95.0, duty0 + rate * (now - t0))
            for n in ramp:
                n.state["duty"] = duty
            if sparse.state["dead"] and now >= t0 + sparse_alive_frac * duration_s:
                sparse.state.update(dead=False, duty=60.0)
            if now - last_scrape >= scrape_every_s:
                last_scrape = now
                scrapes += 1
                try:
                    with urllib.request.urlopen(
                        agg.url + "/metrics", timeout=5
                    ) as resp:
                        if resp.status != 200:
                            failed_scrapes += 1
                        resp.read()
                except OSError:
                    failed_scrapes += 1
            time.sleep(interval / 4.0)
        time.sleep(2 * interval)  # last windows + a forecast recompute
        t_end = time.time()

        # --- Forecast vs scripted ground truth -----------------------
        fdoc = fetch("/ledger?view=forecast")
        computed_at = fdoc.get("computed_at", t_end)
        pools = fdoc.get("pools", {})
        ramp_verdict = pools.get("v5p-16", {})
        forecast_days = ramp_verdict.get("days_to_saturation")
        duty_at_compute = min(95.0, duty0 + rate * (computed_at - t0))
        truth_days = (95.0 - duty_at_compute) / rate / 86400.0
        err_ratio = (
            abs(forecast_days - truth_days) / truth_days
            if forecast_days is not None and truth_days > 0 else None
        )
        sparse_verdict = pools.get("v4-8", {})
        sparse_honest = (
            sparse_verdict.get("status") == "insufficient_history"
            and sparse_verdict.get("days_to_saturation") is None
        )

        # --- Waste ranking conservation ------------------------------
        waste = fetch(
            "/ledger?view=waste&group_by=job&rank=topk:10"
            "&whatif=dollars_per_kwh:0.12"
        )
        cons = waste.get("conservation", {})
        cons_err = abs(
            cons.get("sum_groups_chip_seconds", 0.0)
            - cons.get("total_chip_seconds", -1.0)
        )
        pct = fetch("/ledger?view=percentiles")

        # --- Bounded grouped walk == unbounded fold ------------------
        base = (
            "/ledger?family=tpu_fleet_duty_cycle_percent&scope=slice"
            f"&agg=mean&by=pool&start={t_first:.3f}&end={t_end:.3f}"
        )
        unbounded = fetch(base)
        assert "next_start" not in unbounded, "control fold truncated"

        def groups_of(doc: dict) -> dict:
            return {
                (row["pool"], row["slice"]): list(row["points"])
                for row in doc.get("series", [])
            }

        walked: dict = {}
        pages = 0
        start = t_first
        while pages < 1000:
            page = fetch(
                "/ledger?family=tpu_fleet_duty_cycle_percent"
                "&scope=slice&agg=mean&by=pool"
                f"&start={start:.3f}&end={t_end:.3f}&max_points=7"
            )
            pages += 1
            for group, points in groups_of(page).items():
                walked.setdefault(group, []).extend(points)
            if "next_start" not in page:
                break
            start = page["next_start"]
        walk_equal = walked == groups_of(unbounded)

        return {
            "mode": "capacity",
            "duration_s": round(t_end - t0, 1),
            "interval": interval,
            "ramp": {"duty0": duty0, "rate_pct_per_s": round(rate, 6),
                     "saturation_pct": 95.0},
            "forecast": {
                "status": ramp_verdict.get("status"),
                "leading_signal": ramp_verdict.get("leading_signal"),
                "days_to_saturation": forecast_days,
                "days_lo": ramp_verdict.get("days_lo"),
                "days_hi": ramp_verdict.get("days_hi"),
                "truth_days": truth_days,
                "err_ratio": err_ratio,
                "min_history_s": round(min_history_s, 3),
            },
            "sparse_pool": {
                "status": sparse_verdict.get("status"),
                "honest": sparse_honest,
            },
            "waste": {
                "rows": len(waste.get("rows", [])),
                "top": (waste.get("rows") or [{}])[0].get("key"),
                "conservation_abs_err": cons_err,
                "whatif": waste.get("whatif"),
            },
            "percentile_classes": sorted(pct.get("classes", {})),
            "walk": {"equal": walk_equal, "pages": pages,
                     "groups": len(walked)},
            "scrapes": scrapes,
            "failed_scrapes": failed_scrapes,
        }
    finally:
        agg.close()
        for n in sim:
            n.close()


def _free_port() -> int:
    """An ephemeral port the OS just handed out (racy by nature, fine
    for a soak: the fleet-chaos shards need KNOWN ports up front so the
    peers CSV and the restart can name them)."""
    import socket

    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _page_stats(body: bytes) -> dict:
    """The fleet-scope honesty numbers off one /metrics page."""
    def g(name: str, labels: bytes) -> float | None:
        m = re.search(
            rb"^" + name.encode() + rb"\{" + labels + rb"\} (\S+)",
            body, re.M,
        )
        return float(m.group(1)) if m else None

    fleet = rb'pool="",scope="fleet",slice=""'
    out = {
        "up": g("tpu_fleet_hosts", fleet + rb',state="up"'),
        "stale": g("tpu_fleet_hosts", fleet + rb',state="stale"'),
        "dark": g("tpu_fleet_hosts", fleet + rb',state="dark"'),
        "visibility": g("tpu_fleet_visibility_ratio", fleet),
        "stale_flag": g("tpu_fleet_stale_rollup", fleet),
    }
    m = re.search(rb"^tpu_fleet_shard_targets (\S+)", body, re.M)
    out["targets"] = float(m.group(1)) if m else None
    m = re.search(
        rb'^tpu_fleet_visibility_ratio\{pool="",scope="global",slice=""\} (\S+)',
        body, re.M,
    )
    out["global_visibility"] = float(m.group(1)) if m else None
    return out


def _reject_counts(body: bytes) -> dict[str, float]:
    return {
        reason.decode(): float(value)
        for reason, value in re.findall(
            rb'^tpu_fleet_ingest_rejects_total\{reason="([^"]+)"\} (\S+)',
            body, re.M,
        )
    }


def fleet_chaos_soak(
    duration_s: float,
    nodes: int = 12,
    topology: str = "v4-8",
    interval: float = 0.5,
    scrape_every_s: float = 0.5,
    takeover_s: float | None = None,
) -> dict:
    """Fleet fault-tolerance acceptance evidence (ISSUE 9): two
    aggregator shards (peer-probing each other, warm-restart spools on)
    over a scripted tools/fleetsim.py fleet, driven through the full
    fault vocabulary:

    - **partition** a quarter of the nodes → the owning shards'
      ``tpu_fleet_visibility_ratio`` must drop and rollups must flag
      stale/partial (honesty: no scrape may report missing hosts at
      full visibility with no stale flag); **heal** → full cadence and
      visibility restored (recovery latency recorded — adaptive
      backoff's storm-free mass return).
    - **corrupt** two nodes (hostile varint length prefix + binary
      garbage) → ``tpu_fleet_ingest_rejects_total`` ticks, both shards
      keep serving.
    - **kill shard 1** → shard 0 must adopt the orphaned targets within
      two takeover windows (latency recorded), with
      ``tpu_fleet_takeovers_total`` counting the adoption and shard 0's
      original targets untouched (minimal movement).
    - **restart shard 0** (same port, same spool dir) → its first
      serving cycle must already cover its targets from journaled
      last-good snapshots (restored count + time-to-first-scrape
      recorded).
    """
    import tempfile

    from tpumon.fleet.config import FleetConfig
    from tpumon.fleet.server import build_aggregator

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")
    if duration_s < 40 * interval:
        raise ValueError(
            f"--duration {duration_s:g} is too short for the fleet-chaos "
            f"script at --interval {interval:g} (need > 40*interval: the "
            "partition/kill/restart windows each span several collect "
            "cycles)"
        )
    if takeover_s is None:
        takeover_s = max(2.0, 4 * interval)

    ports = [_free_port(), _free_port()]
    peers = ",".join(f"http://127.0.0.1:{p}" for p in ports)
    spools = [
        tempfile.mkdtemp(prefix="tpumon-fleet-spool-0-"),
        tempfile.mkdtemp(prefix="tpumon-fleet-spool-1-"),
    ]

    def shard_cfg(index: int, urls: list[str]) -> "FleetConfig":
        return FleetConfig(
            port=ports[index], addr="127.0.0.1",
            targets=",".join(urls),
            shard_index=index, shard_count=2,
            interval=interval,
            stale_s=max(2.0, 3.0 * interval),
            evict_s=max(duration_s * 2, 120.0),
            peers=peers,
            probe_interval=max(0.25, takeover_s / 4.0),
            takeover_s=takeover_s,
            spool_dir=spools[index],
            spool_every_s=interval,
            poll_backoff_max_s=5.0,
            history_window=0.0,
        )

    sim_proc = None
    shards: list = [None, None]
    conns: dict[int, http.client.HTTPConnection] = {}
    lat_ms: list[float] = []
    failed_scrapes = 0
    honesty_violations = 0
    min_visibility = {0: 1.0, 1: 1.0}
    min_global_visibility = 1.0
    stale_flagged = 0
    partial_flagged = 0
    rejects_accum: dict[str, float] = {}
    shard1_rejects: dict[str, float] = {}
    record: dict = {
        "mode": "fleet-chaos",
        "nodes": nodes,
        "shards": 2,
        "topology": topology,
        "interval_s": interval,
        "takeover_s": takeover_s,
    }
    prev_switch = sys.getswitchinterval()

    def scrape(index: int) -> bytes | None:
        nonlocal failed_scrapes
        conn = conns.get(index)
        if conn is None:
            conn = conns[index] = http.client.HTTPConnection(
                "127.0.0.1", ports[index], timeout=10
            )
        start = time.perf_counter()
        try:
            conn.request("GET", "/metrics")
            body = conn.getresponse().read()
        except (OSError, http.client.HTTPException):
            failed_scrapes += 1
            conn.close()
            conns.pop(index, None)
            return None
        lat_ms.append((time.perf_counter() - start) * 1e3)
        return body

    def observe(index: int) -> dict | None:
        nonlocal honesty_violations, stale_flagged, partial_flagged
        nonlocal min_global_visibility
        body = scrape(index)
        if body is None:
            return None
        stats = _page_stats(body)
        vis = stats["visibility"]
        if vis is not None:
            min_visibility[index] = min(min_visibility[index], vis)
            if vis < 1.0:
                partial_flagged += 1
        if stats["global_visibility"] is not None:
            min_global_visibility = min(
                min_global_visibility, stats["global_visibility"]
            )
        if stats["stale_flag"] == 1.0:
            stale_flagged += 1
        # The honesty invariant: hosts missing from the up count must
        # surface as a stale flag or reduced visibility on the SAME
        # page — never a silently smaller (or renormalized) rollup.
        if (
            stats["up"] is not None
            and stats["targets"] is not None
            and stats["up"] < stats["targets"]
            and stats["stale_flag"] == 0.0
            and (vis is None or vis >= 1.0)
        ):
            honesty_violations += 1
        return stats

    def fleet_doc(index: int) -> dict:
        conn = http.client.HTTPConnection(
            "127.0.0.1", ports[index], timeout=10
        )
        try:
            conn.request("GET", "/fleet")
            return json.loads(conn.getresponse().read())
        finally:
            conn.close()

    def covered(index: int) -> float:
        doc = fleet_doc(index)
        hosts = doc["fleet"].get("hosts", {})
        return hosts.get("up", 0) + hosts.get("stale", 0)

    sim_log: list[str] = []

    def sim_cmd(command: str, expect_lines: int) -> None:
        # Read the ack lines back: confirms the command landed (the
        # evidence record carries them) and keeps the stdout pipe
        # drained.
        sim_proc.stdin.write(command + "\n")
        sim_proc.stdin.flush()
        for _ in range(expect_lines):
            line = sim_proc.stdout.readline()  # deadline: fleetsim acks every command immediately or died (outer CI timeout bounds the run)
            if not line:
                sim_log.append(f"{command}: sim died mid-ack")
                return
            sim_log.append(line.strip())

    try:
        if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
            sys.setswitchinterval(min(prev_switch, 0.0005))
        sim_proc, urls = _spawn_fleetsim(nodes, topology, interval)
        shards[0] = build_aggregator(shard_cfg(0, urls))
        shards[1] = build_aggregator(shard_cfg(1, urls))
        shards[0].start()
        shards[1].start()
        record["shard_targets"] = [len(s.targets) for s in shards]
        owned0_before = set(shards[0].targets)

        # Warm-up gate: both shards fully fed before the script starts.
        warm_deadline = time.time() + max(60.0, 2.0 * nodes)
        while time.time() < warm_deadline:
            if all(
                fleet_doc(i)["fleet"].get("hosts", {}).get("up", 0)
                >= len(shards[i].targets)
                for i in range(2)
            ):
                break
            time.sleep(0.25)

        t0 = time.time()
        partitioned = max(2, nodes // 4)
        # Recovery is measured in the heal→corrupt gap: it must be wide
        # enough for the worst-case adaptive backoff (the shards run
        # poll_backoff_max_s=5, jitter ×1.25) or the corrupt phase's
        # own staleness would pollute the partition-recovery number.
        script = {
            "partition_at": 0.10 * duration_s,
            "heal_at": 0.25 * duration_s,
            "corrupt_at": 0.45 * duration_s,
            "kill_at": 0.60 * duration_s,
            "restart_at": 0.80 * duration_s,
        }
        record["script"] = {k: round(v, 1) for k, v in script.items()}
        done: set[str] = set()
        recovery_from = None
        recovery_s = None
        takeover = None
        next_at = t0

        while time.time() - t0 < duration_s:
            t = time.time() - t0
            if t >= script["partition_at"] and "partition" not in done:
                done.add("partition")
                sim_cmd(f"partition {partitioned}", partitioned)
            if t >= script["heal_at"] and "heal" not in done:
                done.add("heal")
                sim_cmd("heal", 1)
                recovery_from = time.time()
            if t >= script["corrupt_at"] and "corrupt" not in done:
                done.add("corrupt")
                # Close the recovery measurement window: past this
                # point staleness belongs to the corrupt phase.
                recovery_from = None
                sim_cmd("corrupt 2", 2)
            if t >= script["kill_at"] and "kill" not in done:
                done.add("kill")
                sim_cmd("heal", 1)  # corruption dose delivered; clean fleet
                # Harvest the victim's counters first: its ingest
                # rejects die with the process.
                body = scrape(1)
                if body is not None:
                    shard1_rejects = _reject_counts(body)
                    for reason, count in shard1_rejects.items():
                        rejects_accum[reason] = (
                            rejects_accum.get(reason, 0.0) + count
                        )
                kill_t = time.time()
                shards[1].close()
                shards[1] = None
                conns.pop(1, None)
            if t >= script["restart_at"] and "restart" not in done:
                done.add("restart")
                if takeover is None:
                    takeover = {"latency_s": None, "windows": None}
                # Harvest shard 0's counters first — the restart wipes
                # its in-memory registry.
                body = scrape(0)
                if body is not None:
                    for reason, count in _reject_counts(body).items():
                        rejects_accum[reason] = (
                            rejects_accum.get(reason, 0.0) + count
                        )
                restart_t = time.time()
                shards[0].close()
                shards[0] = build_aggregator(shard_cfg(0, urls))
                shards[0].start()
                conns.pop(0, None)
                first = None
                first_deadline = time.time() + max(10.0, 10 * interval)
                while time.time() < first_deadline and first is None:
                    first = observe(0)
                    if first is None:
                        time.sleep(0.1)
                debug = {}
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", ports[0], timeout=10
                    )
                    conn.request("GET", "/debug/vars")
                    debug = json.loads(conn.getresponse().read())
                    conn.close()
                except (OSError, http.client.HTTPException, ValueError):
                    pass
                record["restart"] = {
                    "first_scrape_s": round(time.time() - restart_t, 3),
                    "restored_nodes": debug.get("spool", {}).get(
                        "restored_nodes"
                    ),
                    "first_page": first,
                    #: One fan-in cycle: served_within counts collect
                    #: intervals from start to the first good page.
                    "intervals_to_first_page": round(
                        (time.time() - restart_t) / interval, 2
                    ),
                }
            # Takeover progress: after the kill, watch shard 0 adopt.
            if "kill" in done and takeover is None:
                cover = None
                try:
                    cover = covered(0)
                except (OSError, ValueError, http.client.HTTPException):
                    pass
                if cover is not None and cover >= nodes - 0.5:
                    latency = time.time() - kill_t
                    takeover = {
                        "latency_s": round(latency, 2),
                        "windows": round(latency / takeover_s, 2),
                        "orphans_adopted": len(
                            set(shards[0].targets) - owned0_before
                        ),
                        "minimal_movement": owned0_before
                        <= set(shards[0].targets),
                    }
            # Partition recovery: both live shards back at visibility 1.
            if recovery_from is not None and recovery_s is None:
                views = [
                    observe(i) for i in range(2) if shards[i] is not None
                ]
                if views and all(
                    v is not None and v["visibility"] == 1.0 for v in views
                ):
                    recovery_s = round(time.time() - recovery_from, 2)
            else:
                for i in range(2):
                    if shards[i] is not None:
                        observe(i)
            next_at += scrape_every_s
            time.sleep(max(0.0, next_at - time.time()))

        final_pages = {
            i: observe(i) for i in range(2) if shards[i] is not None
        }
        body = scrape(0)
        takeovers_total = 0.0
        if body is not None:
            for reason, count in _reject_counts(body).items():
                rejects_accum[reason] = (
                    rejects_accum.get(reason, 0.0) + count
                )
            m = re.search(rb"^tpu_fleet_takeovers_total (\S+)", body, re.M)
            takeovers_total = float(m.group(1)) if m else 0.0
    finally:
        for conn in conns.values():
            conn.close()
        for shard in shards:
            if shard is not None:
                shard.close()
        if sim_proc is not None:
            sim_proc.terminate()
            try:
                sim_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                sim_proc.kill()
        for spool_dir in spools:
            shutil.rmtree(spool_dir, ignore_errors=True)
        sys.setswitchinterval(prev_switch)

    lat_ms.sort()

    def _q(p: float):
        return round(quantile(lat_ms, p), 3) if lat_ms else None

    record.update(
        {
            "duration_s": round(duration_s, 1),
            "scrapes": len(lat_ms),
            "failed_scrapes": failed_scrapes,
            "p50_ms": _q(0.5),
            "p99_ms": _q(0.99),
            "partition": {
                "partitioned": partitioned,
                "min_visibility": {
                    str(i): round(v, 3) for i, v in min_visibility.items()
                },
                "min_global_visibility": round(min_global_visibility, 3),
                "stale_flagged_scrapes": stale_flagged,
                "partial_flagged_scrapes": partial_flagged,
                "honesty_violations": honesty_violations,
                "recovery_s": recovery_s,
            },
            "corrupt": {
                "rejects": rejects_accum,
                "shard1_rejects": shard1_rejects,
            },
            "sim_log": sim_log,
            "takeover": takeover
            or {"latency_s": None, "windows": None},
            "takeovers_total": takeovers_total,
            "final_pages": final_pages,
        }
    )
    return record


def serve_burst_soak(
    duration_s: float,
    nodes: int = 12,
    scale_out: int = 4,
    topology: str = "v4-8",
    interval: float = 0.5,
    scrape_every_s: float = 0.5,
    queue_threshold: float | None = None,
) -> dict:
    """Inference serving drill (ISSUE 16 acceptance evidence): the
    actuation loop end-to-end against a simulated serving fleet.

    ``nodes`` fleetsim exporters publish ``tpu_lifecycle_serve_*`` at a
    calm baseline behind one actuate-enabled aggregator; ``scale_out``
    extra nodes start partitioned — capacity that has not scaled up
    yet. The script then:

    - **burst**: every node's serving profile spikes (queue depth 16×,
      TTFT past the SLO) → the HPA-shaped External Metrics query
      (``/apis/external.metrics.k8s.io/v1beta1/.../
      tpumon_serve_queue_depth?labelSelector=pool=...``) must cross
      ``queue_threshold`` within ~one rollup interval of the spike
      reaching a node page (latency recorded in intervals);
    - **scale-out**: the partitioned nodes heal — new capacity joining
      mid-burst. Through the join, NO scrape may show a fleet straggler
      verdict (the mass-return must not be misread as laggards) and
      the honesty invariant holds (missing hosts always flagged);
    - **cooldown**: the profile relaxes → the metric must fall back
      under the threshold (the scale signal clears, hysteresis keeps
      hint bands from flapping — transition count recorded).

    Every External Metrics answer comes off the aggregator's published
    rollup read model; the page scan additionally proves no per-node
    ``tpu_serve_*`` series re-exports through the tier.
    """
    import urllib.parse

    from tpumon.fleet.config import FleetConfig
    from tpumon.fleet.server import build_aggregator

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")
    if duration_s < 40 * interval:
        raise ValueError(
            f"--duration {duration_s:g} is too short for the serve-burst "
            f"script at --interval {interval:g} (need > 40*interval: the "
            "burst/scale-out/cooldown windows each span several collect "
            "cycles)"
        )
    scale_out = max(0, scale_out)
    total_nodes = nodes + scale_out
    if queue_threshold is None:
        # Between baseline (1/node) and burst (16/node) pool sums, in
        # units of the SERVING node count.
        queue_threshold = 4.0 * nodes

    sim_proc = None
    aggregator = None
    conn = None
    lat_ms: list[float] = []
    failed_scrapes = 0
    honesty_violations = 0
    false_straggler_scrapes = 0
    serve_leaks = 0
    em_queries = 0
    em_ok = 0
    record: dict = {
        "mode": "serve-burst",
        "nodes": nodes,
        "scale_out": scale_out,
        "topology": topology,
        "interval_s": interval,
        "queue_threshold": queue_threshold,
    }
    sim_log: list[str] = []
    prev_switch = sys.getswitchinterval()

    def sim_cmd(command: str, expect_lines: int) -> None:
        sim_proc.stdin.write(command + "\n")
        sim_proc.stdin.flush()
        for _ in range(expect_lines):
            line = sim_proc.stdout.readline()  # deadline: fleetsim acks every command immediately or died (outer CI timeout bounds the run)
            if not line:
                sim_log.append(f"{command}: sim died mid-ack")
                return
            sim_log.append(line.strip())

    def get(path: str) -> bytes | None:
        nonlocal failed_scrapes, conn
        start = time.perf_counter()
        try:
            conn.request("GET", path)
            body = conn.getresponse().read()
        except (OSError, http.client.HTTPException):
            failed_scrapes += 1
            conn.close()
            conn = http.client.HTTPConnection(
                "127.0.0.1", aggregator.server.port, timeout=10
            )
            return None
        lat_ms.append((time.perf_counter() - start) * 1e3)
        return body

    def _quantity(raw: str) -> float:
        return (
            float(raw[:-1]) / 1e3 if raw.endswith("m") else float(raw)
        )

    def _json_or_none(body: bytes | None):
        # A shed answer (guard 503) is plain text, not JSON — skip it.
        if body is None:
            return None
        try:
            return json.loads(body)
        except ValueError:
            return None

    def hpa_value(metric: str, selector: str) -> float | None:
        """One HPA-shaped External Metrics query: the summed value over
        the matching items (what an HPA's Value target consumes)."""
        nonlocal em_queries, em_ok
        em_queries += 1
        body = get(
            "/apis/external.metrics.k8s.io/v1beta1/namespaces/default/"
            f"{metric}?labelSelector={urllib.parse.quote(selector)}"
        )
        if body is None:
            return None
        try:
            doc = json.loads(body)
        except ValueError:
            return None
        items = doc.get("items") or []
        if not items:
            return None
        em_ok += 1
        return sum(_quantity(item["value"]) for item in items)

    def observe() -> None:
        """Honesty + false-straggler + leak scan off one /metrics page."""
        nonlocal honesty_violations, false_straggler_scrapes, serve_leaks
        body = get("/metrics")
        if body is None:
            return
        stats = _page_stats(body)
        if (
            stats["up"] is not None
            and stats["targets"] is not None
            and stats["up"] < stats["targets"]
            and stats["stale_flag"] == 0.0
            and (stats["visibility"] is None or stats["visibility"] >= 1.0)
        ):
            honesty_violations += 1
        if any(
            float(v) > 0
            for v in re.findall(
                rb"^tpu_fleet_stragglers\{[^}]*\} (\S+)", body, re.M
            )
        ):
            false_straggler_scrapes += 1
        if re.search(rb"^tpu_serve_", body, re.M):
            serve_leaks += 1

    try:
        if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
            sys.setswitchinterval(min(prev_switch, 0.0005))
        sim_proc, urls = _spawn_fleetsim(total_nodes, topology, interval)
        # The to-be-scaled-out capacity starts dark: partition the first
        # scale_out nodes before the aggregator ever reaches them.
        if scale_out:
            sim_cmd(f"partition {scale_out}", scale_out)
        sim_cmd("serve 8 1 120 1.0", 1)  # calm baseline profile
        aggregator = build_aggregator(
            FleetConfig(
                port=0, addr="127.0.0.1", targets=",".join(urls),
                interval=interval,
                stale_s=max(2.0, 3.0 * interval),
                evict_s=max(duration_s * 2, 120.0),
                poll_backoff_max_s=2.0,  # mass return inside the drill
                history_window=0.0,
            )
        )
        aggregator.start()
        conn = http.client.HTTPConnection(
            "127.0.0.1", aggregator.server.port, timeout=10
        )

        # Warm-up gate: every SERVING node reporting (the partitioned
        # scale-out capacity stays dark by design).
        warm_t0 = time.time()
        warm_deadline = warm_t0 + max(60.0, 2.0 * total_nodes)
        pool = None
        while time.time() < warm_deadline:
            doc = _json_or_none(get("/fleet"))
            if doc is not None:
                if doc.get("fleet", {}).get("hosts", {}).get("up", 0) >= nodes:
                    # The serving pool: the identity-bearing pool row
                    # with the most live hosts ("unknown" is the
                    # placeholder pool of the still-dark capacity).
                    rows = [
                        row for row in doc.get("pools") or []
                        if isinstance(row, dict)
                        and row.get("pool") not in (None, "", "unknown")
                        and row.get("hosts", {}).get("up", 0) > 0
                    ]
                    if rows:
                        pool = max(
                            rows,
                            key=lambda r: r["hosts"].get("up", 0),
                        )["pool"]
                        break
            time.sleep(0.25)
        record["warmup_s"] = round(time.time() - warm_t0, 1)
        record["pool"] = pool
        selector = f"pool={pool}" if pool else ""
        metric = "tpumon_serve_queue_depth"

        # Discovery: the APIService registration paths an HPA's
        # metrics client walks before its first query.
        disco = get("/apis/external.metrics.k8s.io/v1beta1")
        record["discovery_ok"] = bool(
            disco and b"ExternalMetricValueList" in disco
        )

        t0 = time.time()
        script = {
            "burst_at": 0.25 * duration_s,
            "scale_out_at": 0.50 * duration_s,
            "cooldown_at": 0.75 * duration_s,
        }
        record["script"] = {k: round(v, 1) for k, v in script.items()}
        done: set[str] = set()
        signal: dict = {"fired": False, "latency_s": None,
                        "intervals": None, "value": None}
        clear: dict = {"cleared": False, "latency_s": None}
        scale_event: dict = {"healed": scale_out, "completed_s": None,
                             "up_after": None}
        heal_t = None
        next_at = t0

        def rapid_poll(crossed) -> tuple[float, float] | None:
            """Poll the HPA query sub-interval until ``crossed(value)``;
            (latency_s, value) or None on timeout."""
            poll_t0 = time.time()
            deadline = poll_t0 + max(10.0, 10 * interval)
            while time.time() < deadline:
                value = hpa_value(metric, selector)
                if value is not None and crossed(value):
                    return time.time() - poll_t0, value
                time.sleep(max(0.05, interval / 5.0))
            return None

        while time.time() - t0 < duration_s:
            t = time.time() - t0
            if t >= script["burst_at"] and "burst" not in done:
                done.add("burst")
                sim_cmd("serve 80 16 900 0.55", 1)
                # The spike exists once a node page carries it: one sim
                # tick. Signal latency is measured from there — the
                # rollup path (fetch → parse → actuate cycle → adapter)
                # is what the one-interval acceptance bounds.
                time.sleep(interval)
                hit = rapid_poll(lambda v: v > queue_threshold)
                if hit is not None:
                    signal = {
                        "fired": True,
                        "latency_s": round(hit[0], 3),
                        "intervals": round(hit[0] / interval, 2),
                        "value": round(hit[1], 1),
                    }
            if t >= script["scale_out_at"] and "scale_out" not in done:
                done.add("scale_out")
                sim_cmd("heal", 1)
                heal_t = time.time()
            if t >= script["cooldown_at"] and "cooldown" not in done:
                done.add("cooldown")
                sim_cmd("serve 8 2 150 1.0", 1)
                time.sleep(interval)
                hit = rapid_poll(lambda v: v <= queue_threshold)
                if hit is not None:
                    clear = {
                        "cleared": True,
                        "latency_s": round(hit[0], 3),
                    }
            if heal_t is not None and scale_event["completed_s"] is None:
                doc = _json_or_none(get("/fleet"))
                if doc is not None:
                    up = doc.get("fleet", {}).get("hosts", {}).get("up", 0)
                    if up >= total_nodes:
                        scale_event["completed_s"] = round(
                            time.time() - heal_t, 2
                        )
                        scale_event["up_after"] = up
            observe()
            hpa_value(metric, selector)  # the HPA's steady poll
            next_at += scrape_every_s
            time.sleep(max(0.0, next_at - time.time()))

        # Final harvest: hint hysteresis + adapter funnel telemetry.
        body = get("/metrics")
        transitions = 0.0
        em_by_result: dict[str, float] = {}
        if body is not None:
            transitions = sum(
                float(v)
                for v in re.findall(
                    rb"^tpu_fleet_hint_transitions_total\{[^}]*\} (\S+)",
                    body, re.M,
                )
            )
            for m_label, result, value in re.findall(
                rb'^tpu_fleet_external_metrics_requests_total\{'
                rb'metric="([^"]*)",result="([^"]*)"\} (\S+)',
                body, re.M,
            ):
                key = f"{m_label.decode()}:{result.decode()}"
                em_by_result[key] = float(value)
        hints_doc = _json_or_none(get("/hints")) or {}
        bands: dict[str, int] = {}
        for row in hints_doc.get("slices", []):
            bands[row.get("band") or "none"] = (
                bands.get(row.get("band") or "none", 0) + 1
            )
    finally:
        if conn is not None:
            conn.close()
        if aggregator is not None:
            aggregator.close()
        if sim_proc is not None:
            sim_proc.terminate()
            try:
                sim_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                sim_proc.kill()
        sys.setswitchinterval(prev_switch)

    lat_ms.sort()

    def _q(p: float):
        return round(quantile(lat_ms, p), 3) if lat_ms else None

    record.update(
        {
            "duration_s": round(duration_s, 1),
            "requests": len(lat_ms),
            "failed_requests": failed_scrapes,
            "p50_ms": _q(0.5),
            "p99_ms": _q(0.99),
            "scale_signal": signal,
            "signal_clear": clear,
            "scale_out_event": scale_event,
            "false_straggler_scrapes": false_straggler_scrapes,
            "honesty_violations": honesty_violations,
            "per_node_serve_leaks": serve_leaks,
            "external_metrics": {
                "queries": em_queries,
                "answered": em_ok,
                "by_result": em_by_result,
            },
            "hints": {
                "transitions_total": transitions,
                "bands": bands,
            },
            "sim_log": sim_log,
        }
    )
    return record


def actuate_chaos_soak(
    duration_s: float,
    nodes: int = 12,
    topology: str = "v4-8",
    interval: float = 0.5,
    scrape_every_s: float = 0.5,
    takeover_s: float | None = None,
) -> dict:
    """Do-no-harm actuation drill (ISSUE 18 acceptance evidence): a
    scripted HPA simulator consumes the External Metrics adapter off
    two peer-probing aggregator shards while fleetsim walks the fleet
    through partition → full-fleet staleness → shard kill → warm
    restart. The hard invariant, checked per decision: the simulated
    replica count and the published hint bands change ONLY on real
    load (the scripted serving-profile steps), never because telemetry
    degraded.

    - **trust gate**: every degraded scope must answer ABSENT (a
      withheld row yields no item), never a stale or fabricated value;
      the deliberately naive HPA sim holds on absent or partial
      answers, so any replica change inside a degraded window convicts
      the telemetry layer, not the sim.
    - **split brain**: killing shard 1 makes shard 0 adopt its targets
      under a fresh ownership epoch; restarting shard 1 from its spool
      re-claims them strictly newer — the contested double-answer
      window must tick ``tpu_actuate_epoch_conflicts_total`` and
      resolve newest-epoch-wins (the older claim withholds, the newer
      serves).
    - **recovery**: after the full-fleet staleness heals, trusted
      complete answers must return within ~2 rollup intervals of
      visibility returning (recorded, not asserted here — CI gates on
      the violation counters).
    """
    import tempfile
    import urllib.parse

    from tpumon.fleet.config import FleetConfig
    from tpumon.fleet.server import build_aggregator

    if duration_s <= 0:
        raise ValueError(f"duration must be > 0 seconds, got {duration_s}")
    if duration_s < 60 * interval:
        raise ValueError(
            f"--duration {duration_s:g} is too short for the actuate-chaos "
            f"script at --interval {interval:g} (need > 60*interval: the "
            "burst/partition/stale/kill/restart windows each span several "
            "collect cycles)"
        )
    if takeover_s is None:
        takeover_s = max(2.0, 4 * interval)

    ports = [_free_port(), _free_port()]
    peers = ",".join(f"http://127.0.0.1:{p}" for p in ports)
    spools = [
        tempfile.mkdtemp(prefix="tpumon-actuate-spool-0-"),
        tempfile.mkdtemp(prefix="tpumon-actuate-spool-1-"),
    ]

    def shard_cfg(index: int, urls: list[str]) -> "FleetConfig":
        return FleetConfig(
            port=ports[index], addr="127.0.0.1",
            targets=",".join(urls),
            shard_index=index, shard_count=2,
            interval=interval,
            stale_s=max(2.0, 3.0 * interval),
            evict_s=max(duration_s * 2, 120.0),
            peers=peers,
            probe_interval=max(0.25, takeover_s / 4.0),
            takeover_s=takeover_s,
            spool_dir=spools[index],
            spool_every_s=interval,
            poll_backoff_max_s=2.0,  # mass return inside the drill
            # Freeze-decay must not fire inside the drill: a frozen
            # band decaying to neutral is designed behavior, and the
            # band do-no-harm check would misread it as a violation.
            hint_decay_s=max(duration_s * 2, 300.0),
            history_window=0.0,
        )

    sim_proc = None
    shards: list = [None, None]
    conns: dict[int, http.client.HTTPConnection] = {}
    lat_ms: list[float] = []
    failed_scrapes = 0
    honesty_violations = 0
    queue_threshold = 4.0 * nodes
    metric = "tpumon_serve_queue_depth"
    selector = ""
    record: dict = {
        "mode": "actuate-chaos",
        "nodes": nodes,
        "shards": 2,
        "topology": topology,
        "interval_s": interval,
        "takeover_s": takeover_s,
        "queue_threshold": queue_threshold,
    }
    sim_log: list[str] = []
    prev_switch = sys.getswitchinterval()

    def sim_cmd(command: str, expect_lines: int) -> None:
        sim_proc.stdin.write(command + "\n")
        sim_proc.stdin.flush()
        for _ in range(expect_lines):
            line = sim_proc.stdout.readline()  # deadline: fleetsim acks every command immediately or died (outer CI timeout bounds the run)
            if not line:
                sim_log.append(f"{command}: sim died mid-ack")
                return
            sim_log.append(line.strip())

    def get(index: int, path: str) -> bytes | None:
        nonlocal failed_scrapes
        if shards[index] is None:
            return None
        conn = conns.get(index)
        if conn is None:
            conn = conns[index] = http.client.HTTPConnection(
                "127.0.0.1", ports[index], timeout=10
            )
        start = time.perf_counter()
        try:
            conn.request("GET", path)
            body = conn.getresponse().read()
        except (OSError, http.client.HTTPException):
            failed_scrapes += 1
            conn.close()
            conns.pop(index, None)
            return None
        lat_ms.append((time.perf_counter() - start) * 1e3)
        return body

    def _json_or_none(body: bytes | None):
        if body is None:
            return None
        try:
            return json.loads(body)
        except ValueError:
            return None

    def _quantity(raw: str) -> float:
        return (
            float(raw[:-1]) / 1e3 if raw.endswith("m") else float(raw)
        )

    def em_items(index: int) -> list | None:
        """One shard's External Metrics answer: the item list, or None
        when the shard is down/unreachable (≠ an empty answer)."""
        doc = _json_or_none(get(
            index,
            "/apis/external.metrics.k8s.io/v1beta1/namespaces/default/"
            f"{metric}?labelSelector={urllib.parse.quote(selector)}",
        ))
        if doc is None:
            return None
        items = doc.get("items")
        return items if isinstance(items, list) else []

    def fleet_doc(index: int) -> dict | None:
        return _json_or_none(get(index, "/fleet"))

    def covered(index: int) -> float | None:
        doc = fleet_doc(index)
        if doc is None:
            return None
        hosts = doc.get("fleet", {}).get("hosts", {})
        return hosts.get("up", 0) + hosts.get("stale", 0)

    def counter_total(body: bytes, name: str) -> float:
        pat = re.compile(
            rb"^" + name.encode() + rb"(?:\{[^}]*\})? (\S+)", re.M
        )
        return sum(float(v) for v in pat.findall(body))

    #: Per-process-life running maxima of the monotonic actuation
    #: counters: a shard restart zeroes its registry, so each life is
    #: harvested separately and summed at the end.
    counter_lives: dict[str, dict[str, float]] = {}

    def note_counters(life: str, body: bytes) -> None:
        d = counter_lives.setdefault(life, {})
        for name in (
            "tpu_actuate_epoch_conflicts_total",
            "tpu_actuate_withheld_total",
            "tpu_fleet_takeovers_total",
        ):
            total = counter_total(body, name)
            if total > d.get(name, 0.0):
                d[name] = total

    # HPA simulator + do-no-harm ledgers.
    replicas = 1
    expected_items = 0
    replica_changes: list[dict] = []
    replica_violations = 0
    band_violations = 0
    withheld_served_violations = 0
    polls = acted = hold_absent = hold_partial = 0
    withheld_observations = 0
    frozen_observations = 0
    withheld_reasons: dict[str, int] = {}
    #: (shard, pool, slice) -> (band, withheld) from the last /hints
    #: snapshot; a band changing while the row is (or just was)
    #: withheld is degraded telemetry moving a hint — the violation.
    last_bands: dict[tuple, tuple] = {}
    prev_withheld: dict[int, set] = {0: set(), 1: set()}

    try:
        if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
            sys.setswitchinterval(min(prev_switch, 0.0005))
        sim_proc, urls = _spawn_fleetsim(nodes, topology, interval)
        sim_cmd("serve 8 1 120 1.0", 1)  # calm baseline profile
        shards[0] = build_aggregator(shard_cfg(0, urls))
        shards[1] = build_aggregator(shard_cfg(1, urls))
        shards[0].start()
        shards[1].start()
        record["shard_targets"] = [len(s.targets) for s in shards]

        # Warm-up gate: both shards fully fed, serving pool discovered.
        pool = None
        warm_deadline = time.time() + max(60.0, 2.0 * nodes)
        while time.time() < warm_deadline:
            docs = [fleet_doc(i) for i in range(2)]
            if all(
                d is not None
                and d.get("fleet", {}).get("hosts", {}).get("up", 0)
                >= len(shards[i].targets)
                for i, d in enumerate(docs)
            ):
                rows = [
                    row for row in docs[0].get("pools") or []
                    if isinstance(row, dict)
                    and row.get("pool") not in (None, "", "unknown")
                    and row.get("hosts", {}).get("up", 0) > 0
                ]
                if rows:
                    pool = max(
                        rows, key=lambda r: r["hosts"].get("up", 0)
                    )["pool"]
                    break
            time.sleep(0.25)
        record["pool"] = pool
        selector = f"pool={pool}" if pool else ""

        # The sim's completeness baseline: the stable item count of a
        # fully-trusted clean answer summed over both shards. Anything
        # smaller later is a partial answer — hold, never scale.
        settle_deadline = time.time() + max(15.0, 20 * interval)
        prev_count = None
        while time.time() < settle_deadline:
            per_shard = [em_items(i) for i in range(2)]
            if all(items is not None for items in per_shard):
                count = sum(len(items) for items in per_shard)
                if count and count == prev_count:
                    expected_items = count
                    break
                prev_count = count
            time.sleep(max(0.2, interval / 2.0))
        record["expected_items"] = expected_items

        t0 = time.time()
        partitioned = max(2, nodes // 4)
        script = {
            "burst_on_at": 0.10 * duration_s,
            "partition_at": 0.26 * duration_s,
            "heal_partition_at": 0.36 * duration_s,
            "stale_at": 0.46 * duration_s,
            "heal_stale_at": 0.55 * duration_s,
            "kill_at": 0.64 * duration_s,
            "restart_at": 0.78 * duration_s,
            "burst_off_at": 0.90 * duration_s,
        }
        record["script"] = {k: round(v, 1) for k, v in script.items()}
        done: set[str] = set()
        #: Replica changes are legitimate only in the grace window
        #: after a REAL load step (the serve-profile changes). The
        #: profile is constant through every degraded window, so any
        #: change outside these windows is harm.
        grace = max(6.0, 12 * interval)
        allowed_until = -1.0
        takeover = None
        kill_t = None
        recovery: dict = {
            "heal_t_s": None, "visibility_restored_s": None,
            "trusted_s": None, "intervals_after_visibility": None,
        }
        heal2_t = None
        vis_restored_t = None
        signal_latency_s = None
        burst_on_t = None
        next_at = t0

        while time.time() - t0 < duration_s:
            t = time.time() - t0
            if t >= script["burst_on_at"] and "burst_on" not in done:
                done.add("burst_on")
                sim_cmd("serve 80 16 900 0.55", 1)
                burst_on_t = time.time()
                allowed_until = t + grace
            if t >= script["partition_at"] and "partition" not in done:
                done.add("partition")
                sim_cmd(f"partition {partitioned}", partitioned)
            if (
                t >= script["heal_partition_at"]
                and "heal_partition" not in done
            ):
                done.add("heal_partition")
                sim_cmd("heal", 1)
            if t >= script["stale_at"] and "stale" not in done:
                done.add("stale")
                sim_cmd(f"partition {nodes}", nodes)
            if t >= script["heal_stale_at"] and "heal_stale" not in done:
                done.add("heal_stale")
                sim_cmd("heal", 1)
                heal2_t = time.time()
                recovery["heal_t_s"] = round(t, 2)
            if t >= script["kill_at"] and "kill" not in done:
                done.add("kill")
                # Harvest the victim's monotonic counters first — they
                # die with the process.
                body = get(1, "/metrics")
                if body is not None:
                    note_counters("shard1", body)
                counter_lives["shard1_prekill"] = counter_lives.pop(
                    "shard1", {}
                )
                kill_t = time.time()
                shards[1].close()
                shards[1] = None
                conns.pop(1, None)
                heal2_t = None  # recovery window closed by the kill
            if t >= script["restart_at"] and "restart" not in done:
                done.add("restart")
                if takeover is None:
                    takeover = {"latency_s": None, "windows": None}
                shards[1] = build_aggregator(shard_cfg(1, urls))
                shards[1].start()
                conns.pop(1, None)
            if t >= script["burst_off_at"] and "burst_off" not in done:
                done.add("burst_off")
                sim_cmd("serve 8 1 120 1.0", 1)
                allowed_until = t + grace

            # Takeover progress: after the kill, watch shard 0 adopt.
            if "kill" in done and takeover is None:
                cover = covered(0)
                if cover is not None and cover >= nodes - 0.5:
                    latency = time.time() - kill_t
                    takeover = {
                        "latency_s": round(latency, 2),
                        "windows": round(latency / takeover_s, 2),
                    }

            # Page scan: honesty + monotonic counter harvest.
            for i in range(2):
                body = get(i, "/metrics")
                if body is None:
                    continue
                note_counters(f"shard{i}", body)
                stats = _page_stats(body)
                if (
                    stats["up"] is not None
                    and stats["targets"] is not None
                    and stats["up"] < stats["targets"]
                    and stats["stale_flag"] == 0.0
                    and (
                        stats["visibility"] is None
                        or stats["visibility"] >= 1.0
                    )
                ):
                    honesty_violations += 1

            # Hint-band do-no-harm scan + withheld bookkeeping.
            withheld_now: dict[int, set] = {0: set(), 1: set()}
            any_withheld_row = False
            for i in range(2):
                doc = _json_or_none(get(i, "/hints"))
                if doc is None:
                    continue
                for row in doc.get("slices") or []:
                    key = (i, row.get("pool"), row.get("slice"))
                    band = row.get("band")
                    wh = bool(row.get("withheld"))
                    if wh:
                        any_withheld_row = True
                        withheld_observations += 1
                        withheld_now[i].add(
                            (row.get("pool"), row.get("slice"))
                        )
                        reason = row.get("withheld_reason") or "untrusted"
                        withheld_reasons[reason] = (
                            withheld_reasons.get(reason, 0) + 1
                        )
                    if row.get("frozen"):
                        frozen_observations += 1
                    prev = last_bands.get(key)
                    if (
                        prev is not None
                        and band != prev[0]
                        and (wh or prev[1])
                    ):
                        band_violations += 1
                    last_bands[key] = (band, wh)

            # The HPA decision: sum the answer over both shards; hold
            # on absent or partial — the trust gate is what makes
            # degraded scopes LOOK partial instead of feeding stale
            # values into a complete-looking answer.
            polls += 1
            n_items = 0
            total = 0.0
            partial = False
            for i in range(2):
                items = em_items(i)
                if items is None:
                    partial = True
                    continue
                n_items += len(items)
                for item in items:
                    total += _quantity(item["value"])
                    labels = item.get("metricLabels") or {}
                    scope = (labels.get("pool"), labels.get("slice"))
                    # A scope withheld across two consecutive /hints
                    # snapshots must not appear as an item: withheld
                    # means ABSENT, never a value.
                    if (
                        scope in withheld_now[i]
                        and scope in prev_withheld[i]
                    ):
                        withheld_served_violations += 1
            prev_withheld = withheld_now
            if n_items == 0:
                hold_absent += 1
            elif partial or n_items < expected_items:
                hold_partial += 1
            else:
                acted += 1
                desired = 2 if total > queue_threshold else 1
                if desired != replicas:
                    in_allowed = t <= allowed_until
                    replica_changes.append({
                        "t_s": round(t, 2),
                        "from": replicas,
                        "to": desired,
                        "value": round(total, 1),
                        "allowed": in_allowed,
                    })
                    if not in_allowed:
                        replica_violations += 1
                    if (
                        desired > replicas
                        and burst_on_t is not None
                        and signal_latency_s is None
                    ):
                        signal_latency_s = round(
                            time.time() - burst_on_t, 2
                        )
                    replicas = desired

            # Post-heal recovery: visibility back first, then the
            # first fully-trusted complete answer.
            if heal2_t is not None:
                if vis_restored_t is None:
                    views = [
                        _page_stats(b) for b in
                        (get(i, "/metrics") for i in range(2))
                        if b is not None
                    ]
                    if views and all(
                        v["visibility"] is not None
                        and v["visibility"] >= 1.0
                        for v in views
                    ):
                        vis_restored_t = time.time()
                        recovery["visibility_restored_s"] = round(
                            vis_restored_t - heal2_t, 2
                        )
                elif (
                    recovery["trusted_s"] is None
                    and not any_withheld_row
                    and not partial
                    and n_items >= expected_items
                ):
                    recovery["trusted_s"] = round(
                        time.time() - heal2_t, 2
                    )
                    recovery["intervals_after_visibility"] = round(
                        (time.time() - vis_restored_t) / interval, 2
                    )

            next_at += scrape_every_s
            time.sleep(max(0.0, next_at - time.time()))

        # Final harvest: counters, takeovers, debug views.
        final_debug: dict = {}
        for i in range(2):
            body = get(i, "/metrics")
            if body is not None:
                note_counters(f"shard{i}", body)
            debug = _json_or_none(get(i, "/debug/vars")) or {}
            final_debug[f"shard{i}"] = debug.get("actuate")
    finally:
        for conn in conns.values():
            conn.close()
        for shard in shards:
            if shard is not None:
                shard.close()
        if sim_proc is not None:
            sim_proc.terminate()
            try:
                sim_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                sim_proc.kill()
        for spool_dir in spools:
            shutil.rmtree(spool_dir, ignore_errors=True)
        sys.setswitchinterval(prev_switch)

    lat_ms.sort()

    def _q(p: float):
        return round(quantile(lat_ms, p), 3) if lat_ms else None

    def _life_total(name: str) -> float:
        return sum(d.get(name, 0.0) for d in counter_lives.values())

    record.update(
        {
            "duration_s": round(duration_s, 1),
            "requests": len(lat_ms),
            "failed_requests": failed_scrapes,
            "p50_ms": _q(0.5),
            "p99_ms": _q(0.99),
            "hpa": {
                "polls": polls,
                "acted": acted,
                "hold_absent": hold_absent,
                "hold_partial": hold_partial,
                "final_replicas": replicas,
                "replica_changes": replica_changes,
                "signal_latency_s": signal_latency_s,
            },
            "do_no_harm": {
                "replica_violations": replica_violations,
                "band_violations": band_violations,
                "withheld_served_violations": withheld_served_violations,
                "grace_s": grace,
            },
            "trust": {
                "withheld_observations": withheld_observations,
                "frozen_observations": frozen_observations,
                "withheld_reasons": withheld_reasons,
                "withheld_total_counter": _life_total(
                    "tpu_actuate_withheld_total"
                ),
            },
            "epoch_conflicts_total": _life_total(
                "tpu_actuate_epoch_conflicts_total"
            ),
            "epoch_conflicts_by_life": {
                life: d.get("tpu_actuate_epoch_conflicts_total", 0.0)
                for life, d in counter_lives.items()
            },
            "takeover": takeover or {"latency_s": None, "windows": None},
            "takeovers_total": _life_total("tpu_fleet_takeovers_total"),
            "recovery": recovery,
            "honesty_violations": honesty_violations,
            "final_actuate_debug": final_debug,
            "sim_log": sim_log,
        }
    )
    return record


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpumon-soak")
    parser.add_argument("--duration", type=float, default=2700.0,
                        help="soak length in seconds (default 45 min)")
    parser.add_argument("--scrape-every", type=float, default=1.0)
    parser.add_argument("--topology", default="v5p-64",
                        help="fake-backend topology preset")
    parser.add_argument("--interval", type=float, default=1.0,
                        help="exporter poll interval")
    from tpumon.config import BACKEND_CHOICES

    parser.add_argument("--backend", default="fake",
                        choices=BACKEND_CHOICES,
                        help="'fake' (synthetic --topology preset) or a "
                        "real backend selection — 'auto'/'libtpu' soak "
                        "the real monitoring SDK on a TPU host")
    parser.add_argument("--chaos", nargs="?", const=DEFAULT_CHAOS_SPEC,
                        default=None, metavar="SPEC",
                        help="wrap the backend in deterministic fault "
                        "injection (tpumon/resilience/faults.py) and "
                        "report degraded-serving evidence; optional SPEC "
                        f"overrides the default ({DEFAULT_CHAOS_SPEC!r})")
    parser.add_argument("--storm", action="store_true",
                        help="run the client-side chaos generator "
                        "(tpumon/guard/stormer.py: scrape storm + "
                        "slowloris + oversized requests + Watch hammer) "
                        "against the exporter during the soak and report "
                        "shedding/guard evidence")
    parser.add_argument("--straggler", action="store_true",
                        help="host-correlation acceptance soak "
                        "(tpumon/hostcorr): scripted host-stall and "
                        "device-fault windows over a fixture procfs "
                        "tree; reports per-window cause attribution, "
                        "host_straggler events, and the "
                        "zero-additional-device-queries budget proof")
    parser.add_argument("--preempt", action="store_true",
                        help="workload-lifecycle acceptance soak "
                        "(tpumon/lifecycle): scripted slice preemption + "
                        "elastic resize + checkpoint restore, then a "
                        "genuine step-time regression; reports false-"
                        "positive/false-negative counts, lifecycle "
                        "events, suppression, and the zero-added-device-"
                        "queries budget proof")
    parser.add_argument("--interfere", action="store_true",
                        help="two workload presets on one pool: "
                        "collective-wait climbs on both while all chips "
                        "stay busy — must attribute ICI contention, must "
                        "NOT flag either workload as a straggler")
    parser.add_argument("--restore-storm", action="store_true",
                        help="N pods checkpoint-restore simultaneously "
                        "under a debug-request burst with a fleet "
                        "aggregator watching: one classified restore "
                        "window, zero false verdicts, graceful guard "
                        "shedding, honest fleet visibility")
    parser.add_argument("--pods", type=int, default=6,
                        help="simultaneous restoring workload feeds for "
                        "--restore-storm")
    parser.add_argument("--efficiency", action="store_true",
                        help="energy-plane scenario (tpumon/energy): a "
                        "steady preset's tokens/joule drops to "
                        "--efficiency-factor of baseline at constant "
                        "step rate (duty inflation); the regression "
                        "event must fire, the clean window must carry "
                        "zero false verdicts, every energy family must "
                        "be source-labeled, and the device-call budget "
                        "must equal an energy-off control")
    parser.add_argument("--efficiency-factor", type=float, default=0.7,
                        help="post-injection tokens/joule as a fraction "
                        "of baseline for --efficiency")
    parser.add_argument("--fleet", action="store_true",
                        help="soak the fleet aggregation tier instead of "
                        "one exporter: --fleet-nodes fake exporters "
                        "behind one aggregator shard, --fleet-kill of "
                        "them dying mid-run; reports rollup freshness, "
                        "stale-flagged degradation, and the aggregator's "
                        "scrape latency over the pre-aggregated page")
    parser.add_argument("--fleet-chaos", action="store_true",
                        help="fleet fault-tolerance acceptance soak "
                        "(tpumon/fleet failover plane): two peer-probing "
                        "aggregator shards with warm-restart spools over "
                        "a scripted fleetsim fleet — partition/heal, "
                        "corrupt payloads, shard kill (takeover latency), "
                        "aggregator restart (spool warm start); reports "
                        "visibility honesty, takeover windows, ingest "
                        "rejects, and restart latency")
    parser.add_argument("--ledger", action="store_true",
                        help="fleet efficiency ledger acceptance soak "
                        "(tpumon/ledger): a scripted fleet walks "
                        "productive/checkpoint/preemption/restore/idle"
                        "/kill/recovery phases behind a ledger-enabled "
                        "aggregator (warm restart included); reports "
                        "per-phase goodput bucket accrual, the "
                        "conservation invariant, kill-window honesty "
                        "(unaccounted, never idle), spool restore, and "
                        "a served range query")
    parser.add_argument("--capacity", action="store_true",
                        help="capacity-forecast acceptance soak "
                        "(ISSUE 17): a scripted linear duty ramp "
                        "behind a forecast-enabled aggregator plus a "
                        "history-gated sparse pool; reports the "
                        "forecast's days-to-saturation against the "
                        "script's ground truth, the sparse pool's "
                        "insufficient-history honesty, the top-k "
                        "waste ranking's conservation block, and a "
                        "bounded grouped query walked to completion "
                        "vs its unbounded fold")
    parser.add_argument("--fleet-delta", action="store_true",
                        help="delta fan-in acceptance soak (ISSUE 13): "
                        "--fleet-nodes simulated exporters behind one "
                        "delta-negotiating shard; idle vs churn phases, "
                        "kill + partition/heal honesty checks, then a "
                        "quarter-size and a delta-off control — reports "
                        "fan-in bytes/node/cycle, delta-vs-snapshot "
                        "ratio, collect-CPU churn/size scaling, and "
                        "resync accounting")
    parser.add_argument("--fleet-scale", action="store_true",
                        help="fleet-scale soak (ISSUE 15): the "
                        "--fleet-delta scenario at thousands of nodes "
                        "— striped ingest + native rollup under 2048+ "
                        "simulated exporters — with per-node-series "
                        "leak scanning, the quarter-size control "
                        "skipped, and the delta-off baseline over a "
                        "live subset (snapshot bytes/node is "
                        "size-independent)")
    parser.add_argument("--serve-burst", action="store_true",
                        help="inference serving drill (ISSUE 16): a "
                        "fleetsim fleet publishing serving telemetry "
                        "behind an actuate-enabled aggregator — traffic "
                        "spike, HPA-shaped External Metrics query "
                        "crossing its threshold within ~one rollup "
                        "interval, scale-out (partitioned capacity "
                        "healing) with zero false stragglers and zero "
                        "honesty violations, cooldown clearing the "
                        "signal; reports signal latency, hint "
                        "hysteresis transitions, and per-node serve-"
                        "series leak scans")
    parser.add_argument("--serve-scale-out", type=int, default=4,
                        help="extra capacity nodes that join mid-burst "
                        "for --serve-burst")
    parser.add_argument("--actuate-chaos", action="store_true",
                        help="fail-safe actuation drill (ISSUE 18): a "
                        "scripted HPA simulator consumes the External "
                        "Metrics adapter off two peer-probing shards "
                        "while fleetsim runs partition → full-fleet "
                        "staleness → shard kill → warm restart; "
                        "reports do-no-harm violation counts (replica/"
                        "band changes caused by degraded telemetry), "
                        "withheld-scope absence, epoch-conflict "
                        "resolution (newest wins), takeover windows, "
                        "and post-heal recovery latency")
    parser.add_argument("--fleet-churn", type=float, default=0.02,
                        help="steady-state content churn fraction for "
                        "--fleet-delta's idle phases")
    parser.add_argument("--fleet-churn-high", type=float, default=0.5,
                        help="churn fraction for --fleet-delta's "
                        "high-churn phase")
    parser.add_argument("--fleet-takeover-s", type=float, default=None,
                        help="peer takeover deadline for --fleet-chaos "
                        "(default: max(2, 4*interval))")
    parser.add_argument("--fleet-nodes", type=int, default=16,
                        help="simulated fleet size for --fleet/--fleet-chaos")
    parser.add_argument("--fleet-kill", type=int, default=8,
                        help="exporters killed at half time for --fleet")
    parser.add_argument("--fleet-node-interval", type=float, default=None,
                        help="page-advance cadence of the simulated "
                        "node endpoints (tools/fleetsim.py); default: "
                        "--interval")
    parser.add_argument("--chaos-search", action="store_true",
                        help="property-based chaos search (ISSUE 19, "
                        "tpumon/chaos): generate --chaos-schedules "
                        "seeded random fault schedules, run each "
                        "against a fresh 2-shard fleet under the "
                        "invariant checker, and shrink any failure to "
                        "a 1-minimal replayable reproducer "
                        "(--chaos-out). TPUMON_CHAOS_MUTATE plants the "
                        "CI mutation canary the search must catch")
    parser.add_argument("--chaos-replay", default=None, metavar="FILE",
                        help="replay one persisted failing-schedule "
                        "artifact (or bare schedule JSON) against a "
                        "fresh fleet and report")
    parser.add_argument("--chaos-schedules", type=int, default=20,
                        help="seeded schedules to search")
    parser.add_argument("--chaos-seed0", type=int, default=1,
                        help="first seed (seeds are contiguous)")
    parser.add_argument("--chaos-duration", type=float, default=20.0,
                        help="per-schedule fleet runtime in seconds")
    parser.add_argument("--chaos-jobs", type=int, default=1,
                        help="concurrent trials (each owns its own "
                        "fleetsim + shards + spools)")
    parser.add_argument("--chaos-out", default=None, metavar="DIR",
                        help="directory for failing-schedule JSON "
                        "artifacts (CI uploads these)")
    args = parser.parse_args(argv)
    if args.duration <= 0:
        parser.error("--duration must be > 0")
    if args.chaos_search:
        from tpumon.chaos.search import chaos_search

        record = chaos_search(
            schedules=args.chaos_schedules, seed0=args.chaos_seed0,
            nodes=args.fleet_nodes, duration_s=args.chaos_duration,
            node_interval=args.fleet_node_interval,
            jobs=args.chaos_jobs, out_dir=args.chaos_out,
        )
        print(json.dumps(record))
        return 0 if record["ok"] else 1
    if args.chaos_replay:
        from tpumon.chaos.search import chaos_replay

        record = chaos_replay(
            args.chaos_replay, node_interval=args.fleet_node_interval
        )
        print(json.dumps(record))
        return 0 if not record["failed"] else 1
    if args.preempt:
        record = preempt_soak(
            args.duration, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
        )
    elif args.interfere:
        record = interfere_soak(
            args.duration, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
        )
    elif args.restore_storm:
        record = restore_storm_soak(
            args.duration, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
            pods=args.pods,
        )
    elif args.efficiency:
        record = efficiency_soak(
            args.duration, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
            factor=args.efficiency_factor,
        )
    elif args.straggler:
        record = straggler_soak(
            args.duration, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
        )
    elif args.capacity:
        record = capacity_soak(
            args.duration,
            interval=args.interval, scrape_every_s=args.scrape_every,
        )
    elif args.ledger:
        record = ledger_soak(
            args.duration, nodes=args.fleet_nodes,
            interval=args.interval, scrape_every_s=args.scrape_every,
        )
    elif args.fleet_delta:
        record = fleet_delta_soak(
            args.duration, nodes=args.fleet_nodes, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
            churn=args.fleet_churn, churn_high=args.fleet_churn_high,
            kill=args.fleet_kill, node_interval=args.fleet_node_interval,
        )
    elif args.fleet_scale:
        record = fleet_delta_soak(
            args.duration, nodes=args.fleet_nodes, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
            churn=args.fleet_churn, churn_high=args.fleet_churn_high,
            kill=args.fleet_kill, node_interval=args.fleet_node_interval,
            controls=False, check_leaks=True, mode="fleet-scale",
        )
    elif args.actuate_chaos:
        record = actuate_chaos_soak(
            args.duration, nodes=args.fleet_nodes, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
            takeover_s=args.fleet_takeover_s,
        )
    elif args.serve_burst:
        record = serve_burst_soak(
            args.duration, nodes=args.fleet_nodes,
            scale_out=args.serve_scale_out, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
        )
    elif args.fleet_chaos:
        record = fleet_chaos_soak(
            args.duration, nodes=args.fleet_nodes, topology=args.topology,
            interval=args.interval, scrape_every_s=args.scrape_every,
            takeover_s=args.fleet_takeover_s,
        )
    elif args.fleet:
        record = fleet_soak(
            args.duration, nodes=args.fleet_nodes, kill=args.fleet_kill,
            topology=args.topology, scrape_every_s=args.scrape_every,
            interval=args.interval, node_interval=args.fleet_node_interval,
        )
    else:
        record = soak(
            args.duration, args.scrape_every, args.topology, args.interval,
            args.backend, chaos=args.chaos, storm=args.storm,
        )
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
