"""Shared measurement helpers for the latency bench and the soak tool.

One definition of the quantile formula and the page-sanity sentinel so
BENCH_r*.json and soak records stay directly comparable (two drifting
copies would make their p99 figures subtly different statistics).
"""

from __future__ import annotations

#: A family guaranteed present on any fake-topology exposition page; its
#: absence means the scrape returned a truncated or wrong page.
PAGE_SENTINEL = b"accelerator_duty_cycle_percent"


def quantile(sorted_samples: list[float], q: float) -> float:
    """Nearest-rank quantile over an ascending-sorted non-empty list."""
    n = len(sorted_samples)
    return sorted_samples[min(max(int(n * q) - 1, 0), n - 1)]
