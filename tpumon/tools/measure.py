"""Shared measurement helpers for the latency bench and the soak tool.

One definition of the quantile formula and the page-sanity sentinel so
BENCH_r*.json and soak records stay directly comparable (two drifting
copies would make their p99 figures subtly different statistics).
"""

from __future__ import annotations

#: A family guaranteed present on any fake-topology exposition page; its
#: absence means the scrape returned a truncated or wrong page.
PAGE_SENTINEL = b"accelerator_duty_cycle_percent"


def quantile(sorted_samples: list[float], q: float) -> float:
    """Nearest-rank quantile over an ascending-sorted non-empty list."""
    n = len(sorted_samples)
    return sorted_samples[min(max(int(n * q) - 1, 0), n - 1)]


def fanin_stats(page: str) -> dict:
    """The fan-in wire ledger off one aggregator /metrics page: bytes
    and frames per (transport mode, representation kind), resyncs by
    reason, and the collect-duration sum/count pair. One parser shared
    by the fleet-delta soak, bench extras, and tests, so every
    bytes-per-node-per-cycle figure in the evidence records is the same
    arithmetic over the same counters."""
    import re

    out: dict = {"bytes": {}, "frames": {}, "resyncs": {}}
    for metric, slot in (
        ("tpu_fleet_fanin_bytes_total", "bytes"),
        ("tpu_fleet_fanin_frames_total", "frames"),
    ):
        for kind, mode, value in re.findall(
            r'^%s\{kind="([^"]+)",mode="([^"]+)"\} (\S+)' % metric,
            page, re.M,
        ):
            out[slot][f"{mode}/{kind}"] = float(value)
    for reason, value in re.findall(
        r'^tpu_fleet_fanin_resyncs_total\{reason="([^"]+)"\} (\S+)',
        page, re.M,
    ):
        out["resyncs"][reason] = float(value)
    for field in ("sum", "count"):
        m = re.search(
            r"^tpu_fleet_collect_duration_seconds_%s (\S+)" % field,
            page, re.M,
        )
        out[f"collect_{field}"] = float(m.group(1)) if m else 0.0
    return out


def fanin_window(before: dict, after: dict) -> dict:
    """Deltas between two :func:`fanin_stats` reads: per-slot byte and
    frame counts plus mean collect-cycle milliseconds over the window."""
    bytes_d = {
        slot: after["bytes"].get(slot, 0.0) - before["bytes"].get(slot, 0.0)
        for slot in after["bytes"]
    }
    frames_d = {
        slot: after["frames"].get(slot, 0.0)
        - before["frames"].get(slot, 0.0)
        for slot in after["frames"]
    }
    cycles = after["collect_count"] - before["collect_count"]
    seconds = after["collect_sum"] - before["collect_sum"]
    return {
        "bytes": {k: v for k, v in bytes_d.items() if v},
        "frames": {k: v for k, v in frames_d.items() if v},
        "resyncs": {
            reason: after["resyncs"].get(reason, 0.0)
            - before["resyncs"].get(reason, 0.0)
            for reason in after["resyncs"]
        },
        "collect_cycles": cycles,
        "collect_ms_per_cycle": (
            round(1e3 * seconds / cycles, 3) if cycles else None
        ),
    }
