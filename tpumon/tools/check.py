"""Invariant-checker CLI: ``python -m tpumon.tools.check [--strict]``.

Runs the AST-driven invariant analyzer (tpumon/analysis, rule catalog in
docs/INVARIANTS.md) over a checkout and reports violations against the
checked-in baseline (tpumon/analysis/baseline.txt):

- exit 0 — no new violations (baselined ones are summarized);
- exit 1 — new violations, or (``--strict``) stale baseline entries
  that no longer match anything and must be deleted.

``--update-baseline`` rewrites the baseline from the current findings
(preserving reasons for fingerprints that survive); use it once when
adopting a rule, then burn entries down. A stamp
(``.tpumon-invariants.json``) records the verdict for ``tpumon doctor``
and ``/debug/vars``.

``--format {text,json,sarif}`` picks the report encoding (``--json`` is
the legacy spelling of ``--format json``); ``--output FILE`` writes it
somewhere other than stdout (CI uploads the SARIF as an artifact).
``--changed-files A B ...`` is the incremental pre-commit mode: the
WHOLE project is still loaded and analyzed — thread-role propagation is
interprocedural, a diff-scoped parse would silently lose roots — but
only violations located in the named files are reported, and the stale
check and stamp are skipped (a partial view must not overwrite the
full-run verdict).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpumon.analysis import (
    ANALYZER_VERSION,
    load_baseline,
    load_project,
    run_rules,
)
from tpumon.analysis.baseline import baseline_path, write_stamp
from tpumon.analysis.core import all_rules
from tpumon.analysis.sarif import to_sarif


def _default_root() -> str:
    """The checkout containing this package."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(here)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpumon.tools.check",
        description="AST-driven invariant analyzer (docs/INVARIANTS.md)",
    )
    parser.add_argument(
        "--root", default=_default_root(),
        help="repo root to analyze (default: this checkout)",
    )
    parser.add_argument(
        "--rule", action="append", dest="rules", metavar="NAME",
        help=f"run only this rule (repeatable); known: "
        f"{', '.join(sorted(all_rules()))}",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="also fail on stale baseline entries (the CI gate)",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from current findings",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file (default: tpumon/analysis/baseline.txt "
        "under --root)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable report on stdout "
        "(legacy spelling of --format json)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default=None,
        dest="fmt", help="report encoding (default: text)",
    )
    parser.add_argument(
        "--output", default=None, metavar="FILE",
        help="write the report to FILE instead of stdout",
    )
    parser.add_argument(
        "--changed-files", nargs="*", default=None, metavar="PATH",
        help="incremental mode: analyze the whole project but report "
        "only violations located in these files (skips stale check "
        "and stamp)",
    )
    parser.add_argument(
        "--no-stamp", action="store_true",
        help="do not write the .tpumon-invariants.json stamp",
    )
    args = parser.parse_args(argv)
    fmt = args.fmt or ("json" if args.as_json else "text")
    if args.update_baseline and args.rules:
        # A partial run must never rewrite the whole baseline: every
        # other rule's accepted entries (and their curated reasons)
        # would silently vanish.
        parser.error("--update-baseline cannot be combined with --rule")

    root = os.path.abspath(args.root)
    project = load_project(root)
    violations = run_rules(project, args.rules)

    if args.changed_files is not None:
        changed = {_normalize_path(p, root) for p in args.changed_files}
        violations = [v for v in violations if v.path in changed]

    bl_path = args.baseline or baseline_path(root)
    baseline = load_baseline(bl_path)
    current = {v.fingerprint for v in violations}
    new = [v for v in violations if v.fingerprint not in baseline]
    suppressed = [v for v in violations if v.fingerprint in baseline]
    # Stale entries only assessable when every rule ran on every file.
    stale = (
        sorted(set(baseline) - current)
        if not args.rules and args.changed_files is None
        else []
    )

    if args.update_baseline:
        lines = [
            "# tpumon invariant baseline — accepted violations, one per",
            "# line: `<rule> <key>  # <reason>`. Entries that stop",
            "# matching are STALE and fail --strict: delete them.",
            "# Regenerate: python -m tpumon.tools.check --update-baseline",
            "",
        ]
        for v in violations:
            reason = baseline.get(v.fingerprint, "TODO: justify or fix")
            lines.append(f"{v.fingerprint}  # {reason}")
        with open(bl_path, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + "\n")
        print(f"baseline rewritten: {bl_path} ({len(violations)} entries)")
        return 0

    if fmt == "json":
        report = json.dumps(
            {
                "analyzer_version": ANALYZER_VERSION,
                "new": [v.__dict__ for v in new],
                "baselined": [v.fingerprint for v in suppressed],
                "stale": stale,
            },
            indent=2,
            sort_keys=True,
        )
        _emit(report, args.output)
    elif fmt == "sarif":
        report = json.dumps(
            to_sarif(violations, baseline, ANALYZER_VERSION),
            indent=2,
            sort_keys=True,
        )
        _emit(report, args.output)
    else:
        lines = []
        for v in new:
            loc = f"{v.path}:{v.line}" if v.line else v.path
            lines.append(f"{v.rule}: {loc}: {v.message}")
            lines.append(f"    fingerprint: {v.fingerprint}")
        for fp in stale:
            lines.append(
                f"stale-baseline: {fp!r} no longer matches anything — "
                f"delete it from {os.path.relpath(bl_path, root)}"
            )
        verdict = "OK" if not new else "FAIL"
        if stale and args.strict:
            verdict = "FAIL"
        scope = (
            f"{len(args.changed_files)} changed files"
            if args.changed_files is not None
            else f"{len(project.python)} py / "
            f"{len(project.texts)} text files"
        )
        lines.append(
            f"invariants {verdict}: {len(new)} new, "
            f"{len(suppressed)} baselined, {len(stale)} stale "
            f"(analyzer {ANALYZER_VERSION}, {scope})"
        )
        _emit("\n".join(lines), args.output)

    if not args.no_stamp and not args.rules and args.changed_files is None:
        by_rule: dict[str, int] = {}
        for v in new:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        try:
            write_stamp(
                root,
                new=len(new),
                baselined=len(suppressed),
                stale=len(stale),
                version=ANALYZER_VERSION,
                new_by_rule=by_rule,
            )
        except OSError as exc:
            print(f"warning: could not write stamp: {exc}", file=sys.stderr)

    if new:
        return 1
    if stale and args.strict:
        return 1
    return 0


def _normalize_path(path: str, root: str) -> str:
    """A --changed-files operand (absolute, or relative to the CWD or
    the root — whatever the CI diff produced) -> project-relative form."""
    if os.path.isabs(path):
        return os.path.relpath(path, root).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def _emit(report: str, output: str | None) -> None:
    if output is None:
        print(report)
        return
    with open(output, "w", encoding="utf-8") as fh:
        fh.write(report + "\n")


if __name__ == "__main__":
    sys.exit(main())
