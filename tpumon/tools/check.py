"""Invariant-checker CLI: ``python -m tpumon.tools.check [--strict]``.

Runs the AST-driven invariant analyzer (tpumon/analysis, rule catalog in
docs/INVARIANTS.md) over a checkout and reports violations against the
checked-in baseline (tpumon/analysis/baseline.txt):

- exit 0 — no new violations (baselined ones are summarized);
- exit 1 — new violations, or (``--strict``) stale baseline entries
  that no longer match anything and must be deleted.

``--update-baseline`` rewrites the baseline from the current findings
(preserving reasons for fingerprints that survive); use it once when
adopting a rule, then burn entries down. A stamp
(``.tpumon-invariants.json``) records the verdict for ``tpumon doctor``
and ``/debug/vars``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpumon.analysis import (
    ANALYZER_VERSION,
    load_baseline,
    load_project,
    run_rules,
)
from tpumon.analysis.baseline import baseline_path, write_stamp
from tpumon.analysis.core import all_rules


def _default_root() -> str:
    """The checkout containing this package."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(here)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpumon.tools.check",
        description="AST-driven invariant analyzer (docs/INVARIANTS.md)",
    )
    parser.add_argument(
        "--root", default=_default_root(),
        help="repo root to analyze (default: this checkout)",
    )
    parser.add_argument(
        "--rule", action="append", dest="rules", metavar="NAME",
        help=f"run only this rule (repeatable); known: "
        f"{', '.join(sorted(all_rules()))}",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="also fail on stale baseline entries (the CI gate)",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from current findings",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file (default: tpumon/analysis/baseline.txt "
        "under --root)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable report on stdout",
    )
    parser.add_argument(
        "--no-stamp", action="store_true",
        help="do not write the .tpumon-invariants.json stamp",
    )
    args = parser.parse_args(argv)
    if args.update_baseline and args.rules:
        # A partial run must never rewrite the whole baseline: every
        # other rule's accepted entries (and their curated reasons)
        # would silently vanish.
        parser.error("--update-baseline cannot be combined with --rule")

    root = os.path.abspath(args.root)
    project = load_project(root)
    violations = run_rules(project, args.rules)

    bl_path = args.baseline or baseline_path(root)
    baseline = load_baseline(bl_path)
    current = {v.fingerprint for v in violations}
    new = [v for v in violations if v.fingerprint not in baseline]
    suppressed = [v for v in violations if v.fingerprint in baseline]
    # Stale entries only assessable when every rule ran.
    stale = (
        sorted(set(baseline) - current) if not args.rules else []
    )

    if args.update_baseline:
        lines = [
            "# tpumon invariant baseline — accepted violations, one per",
            "# line: `<rule> <key>  # <reason>`. Entries that stop",
            "# matching are STALE and fail --strict: delete them.",
            "# Regenerate: python -m tpumon.tools.check --update-baseline",
            "",
        ]
        for v in violations:
            reason = baseline.get(v.fingerprint, "TODO: justify or fix")
            lines.append(f"{v.fingerprint}  # {reason}")
        with open(bl_path, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + "\n")
        print(f"baseline rewritten: {bl_path} ({len(violations)} entries)")
        return 0

    if args.as_json:
        print(
            json.dumps(
                {
                    "analyzer_version": ANALYZER_VERSION,
                    "new": [v.__dict__ for v in new],
                    "baselined": [v.fingerprint for v in suppressed],
                    "stale": stale,
                },
                indent=2,
                sort_keys=True,
            )
        )
    else:
        for v in new:
            loc = f"{v.path}:{v.line}" if v.line else v.path
            print(f"{v.rule}: {loc}: {v.message}")
            print(f"    fingerprint: {v.fingerprint}")
        for fp in stale:
            print(
                f"stale-baseline: {fp!r} no longer matches anything — "
                f"delete it from {os.path.relpath(bl_path, root)}"
            )
        verdict = "OK" if not new else "FAIL"
        if stale and args.strict:
            verdict = "FAIL"
        print(
            f"invariants {verdict}: {len(new)} new, "
            f"{len(suppressed)} baselined, {len(stale)} stale "
            f"(analyzer {ANALYZER_VERSION}, "
            f"{len(project.python)} py / {len(project.texts)} text files)"
        )

    if not args.no_stamp and not args.rules:
        try:
            write_stamp(
                root,
                new=len(new),
                baselined=len(suppressed),
                stale=len(stale),
                version=ANALYZER_VERSION,
            )
        except OSError as exc:
            print(f"warning: could not write stamp: {exc}", file=sys.stderr)

    if new:
        return 1
    if stale and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
