"""Native exposition renderer with build-on-demand and Python fallback.

The poll cycle renders the full metric page once per second
(SampleCache.publish). ``render_families`` moves the escape/format/join
hot loop into C when a compiler is available (~5x faster per render);
otherwise it falls back to ``prometheus_client.exposition.generate_latest``.
The extension is built once into ``tpumon/_native/build/`` the first time
it's requested (offline, plain cc, no pip), so shipping wheels is
unnecessary.

Output equivalence: label keys are sorted to match the fallback renderer
byte-for-byte; float values use Python repr where prometheus_client uses
Go-style scientific notation for large magnitudes (``17179869184.0`` vs
``1.7179869184e+010``) — both are valid exposition floats and parse to
identical values (covered by tests).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import sysconfig

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "build")
#: stem -> module | None. A None entry means "tried (or build in flight),
#: use the fallback"; load_extension(force=True) overwrites it.
_modules: dict[str, object | None] = {}


def compile_extension(stem: str) -> str | None:
    """Compile ``tpumon/_native/<stem>.c|.cc`` into build/; .so path or None.

    Shared by every native component (exposition renderer, history engine).
    EVERYTHING is inside the try: on a readOnlyRootFilesystem container the
    very first makedirs raises, and that must mean 'use the fallback',
    never a crash. ``.cc`` sources use the C++ driver (CXX env override,
    else g++); ``.c`` sources use sysconfig's CC.
    """
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
        so_path = os.path.join(_BUILD_DIR, stem + suffix)
        c_src = os.path.join(_HERE, stem + ".c")
        src = c_src if os.path.exists(c_src) else os.path.join(_HERE, stem + ".cc")
        if os.path.exists(so_path) and os.path.getmtime(so_path) >= os.path.getmtime(
            src
        ):
            return so_path
        if src.endswith(".cc"):
            compiler = [*(os.environ.get("CXX") or "g++").split(), "-std=c++17"]
        else:
            compiler = (sysconfig.get_config_var("CC") or "cc").split()
        include = sysconfig.get_path("include")
        cmd = [
            *compiler,
            "-O2",
            "-fPIC",
            "-shared",
            f"-I{include}",
            src,
            "-o",
            so_path,
        ]
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120, text=True
        )
        return so_path
    except Exception as exc:
        detail = getattr(exc, "stderr", "") or str(exc)
        log.info("native %s build unavailable: %s", stem, str(detail).strip()[:200])
        return None


def load_extension(stem: str, force: bool = False):
    """Memoized compile + import for any native component.

    One place owns the TPUMON_NO_NATIVE kill-switch and the per-stem
    cache so every component (exposition renderer, history engine, the
    next one) is a one-line call site. Returns the module or None.
    """
    if not force and stem in _modules:
        return _modules[stem]
    if os.environ.get("TPUMON_NO_NATIVE"):
        _modules[stem] = None
        return None
    mod = None
    so_path = compile_extension(stem)
    if so_path is not None:
        import importlib.util

        try:
            spec = importlib.util.spec_from_file_location(
                f"tpumon._native.{stem}", so_path
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception as exc:
            log.info("native %s load failed: %s", stem, exc)
            mod = None
    _modules[stem] = mod
    return mod


def prewarm_async() -> None:
    """Kick the compile/load off the poll path: mark the renderer as
    unavailable immediately (renders fall back to Python meanwhile) and
    finish loading in a daemon thread. Called at Exporter construction."""
    if "_exposition" in _modules or os.environ.get("TPUMON_NO_NATIVE"):
        return
    _modules["_exposition"] = None

    import threading

    threading.Thread(
        target=lambda: load_extension("_exposition", force=True),
        name="tpumon-native-build",
        daemon=True,
    ).start()


def native_available() -> bool:
    return load_extension("_exposition") is not None


def flatten_family(fam) -> tuple | None:
    """One metric-family object → the plain structure the C renderer
    takes: ``(expo_name, help, type, [samples])``, each sample a
    ``(sample_name, label_keys, label_values, value)`` tuple.

    Gauges, counters, and histograms (the three types the poll loop
    produces) all flatten; anything else — or samples carrying
    timestamps/exemplars — returns None and the general prometheus_client
    renderer takes over. Counters render under their text-format
    ``_total`` exposition name and histogram samples under their
    ``_bucket``/``_count``/``_sum`` names, matching prometheus_client
    byte-for-byte. The flattened shape doubles as the delta renderer's
    change fingerprint (tpumon/exporter/collector.py): equal flattenings
    render to equal bytes.
    """
    # Text exposition 0.0.4 names counters '<family>_total' in
    # HELP/TYPE and on every sample line.
    expo_name = fam.name + "_total" if fam.type == "counter" else fam.name
    if fam.type == "histogram":
        allowed = {
            fam.name + "_bucket",
            fam.name + "_count",
            fam.name + "_sum",
        }
    else:
        allowed = {expo_name}
    samples = []
    for s in fam.samples:
        if s.name not in allowed:
            return None
        if getattr(s, "timestamp", None) is not None or getattr(
            s, "exemplar", None
        ):
            return None
        # Sort label keys to match prometheus_client's renderer, so
        # native and fallback output are byte-identical.
        items = sorted(s.labels.items())
        keys = tuple(k for k, _ in items)
        vals = tuple(str(v) for _, v in items)
        samples.append((s.name, keys, vals, float(s.value)))
    return (expo_name, fam.documentation, fam.type, samples)


def _flatten(families) -> list | None:
    """Flatten a whole page; None when ANY family resists (the page then
    renders via prometheus_client as one unit)."""
    out = []
    for fam in families:
        flat = flatten_family(fam)
        if flat is None:
            return None
        out.append(flat)
    return out


def _python_render(families) -> bytes:
    from prometheus_client.exposition import generate_latest

    class _Shim:
        def collect(self):
            return families

    return generate_latest(_Shim())


def render_families(families) -> bytes:
    """Render metric families to text exposition, native when possible."""
    ext = load_extension("_exposition")
    if ext is None:
        return _python_render(families)
    flat = _flatten(families)
    if flat is None:
        return _python_render(families)
    return ext.render(flat)
