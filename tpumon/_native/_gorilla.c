/* Gorilla chunk codec (CPython C extension).
 *
 * The fleet ledger (tpumon/ledger/compress.py) seals immutable chunks
 * of (timestamp, value) samples with delta-of-delta integer timestamps
 * and XOR-compressed IEEE doubles. This module is the fast path for
 * encode/decode; tpumon/_native/__init__.py builds it on demand and the
 * pure-Python codec in compress.py is the always-available fallback.
 *
 * CONTRACT: output bytes are identical to encode_chunk_py for every
 * input (pinned by tests/test_ledger.py). Any format change lands in
 * BOTH implementations or not at all.
 *
 *   encode(timestamps: list[int], values: list[float]) -> bytes
 *   decode(data: bytes) -> (list[int], list[float])
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

typedef struct {
    char *buf;
    Py_ssize_t len;
    Py_ssize_t cap;
} gbuf;

static int gb_reserve(gbuf *b, Py_ssize_t extra) {
    if (b->len + extra <= b->cap) return 0;
    Py_ssize_t ncap = b->cap ? b->cap : 4096;
    while (ncap < b->len + extra) ncap *= 2;
    char *nbuf = PyMem_Realloc(b->buf, ncap);
    if (!nbuf) return -1;
    b->buf = nbuf;
    b->cap = ncap;
    return 0;
}

static int gb_byte(gbuf *b, unsigned char c) {
    if (gb_reserve(b, 1) < 0) return -1;
    b->buf[b->len++] = (char)c;
    return 0;
}

static int put_varint(gbuf *b, uint64_t v) {
    while (1) {
        unsigned char byte = v & 0x7F;
        v >>= 7;
        if (v) {
            if (gb_byte(b, byte | 0x80) < 0) return -1;
        } else {
            return gb_byte(b, byte);
        }
    }
}

/* MSB-first bit writer (mirrors compress.py _BitWriter). */
typedef struct {
    gbuf *out;
    uint64_t acc;
    int nbits;
} bitw;

static int bw_write(bitw *w, uint64_t value, int nbits) {
    /* nbits <= 64; keep the accumulator under 72 bits by draining. */
    if (nbits < 64) value &= (((uint64_t)1 << nbits) - 1);
    while (nbits > 0) {
        int take = nbits > 32 ? 32 : nbits;
        uint64_t part = (take < 64)
            ? (value >> (nbits - take)) & (((uint64_t)1 << take) - 1)
            : value;
        w->acc = (w->acc << take) | part;
        w->nbits += take;
        nbits -= take;
        while (w->nbits >= 8) {
            w->nbits -= 8;
            if (gb_byte(w->out,
                        (unsigned char)((w->acc >> w->nbits) & 0xFF)) < 0)
                return -1;
        }
        if (w->nbits > 0)
            w->acc &= (((uint64_t)1 << w->nbits) - 1);
        else
            w->acc = 0;
    }
    return 0;
}

static int bw_flush(bitw *w) {
    if (w->nbits) {
        unsigned char byte =
            (unsigned char)((w->acc << (8 - w->nbits)) & 0xFF);
        w->nbits = 0;
        w->acc = 0;
        return gb_byte(w->out, byte);
    }
    return 0;
}

static int clz64(uint64_t x) {
#if defined(__GNUC__) || defined(__clang__)
    return x ? __builtin_clzll(x) : 64;
#else
    int n = 0;
    if (!x) return 64;
    while (!(x & ((uint64_t)1 << 63))) { x <<= 1; n++; }
    return n;
#endif
}

static int ctz64(uint64_t x) {
#if defined(__GNUC__) || defined(__clang__)
    return x ? __builtin_ctzll(x) : 64;
#else
    int n = 0;
    if (!x) return 64;
    while (!(x & 1)) { x >>= 1; n++; }
    return n;
#endif
}

static uint64_t dbl_bits(double d) {
    uint64_t u;
    memcpy(&u, &d, 8);
    return u;
}

static double bits_dbl(uint64_t u) {
    double d;
    memcpy(&d, &u, 8);
    return d;
}

static PyObject *g_encode(PyObject *self, PyObject *args) {
    PyObject *ts_list, *val_list;
    if (!PyArg_ParseTuple(args, "OO", &ts_list, &val_list)) return NULL;
    ts_list = PySequence_Fast(ts_list, "timestamps must be a sequence");
    if (!ts_list) return NULL;
    val_list = PySequence_Fast(val_list, "values must be a sequence");
    if (!val_list) { Py_DECREF(ts_list); return NULL; }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(ts_list);
    if (n != PySequence_Fast_GET_SIZE(val_list)) {
        PyErr_SetString(PyExc_ValueError,
                        "timestamp/value length mismatch");
        goto fail;
    }
    gbuf out = {NULL, 0, 0};
    if (put_varint(&out, (uint64_t)n) < 0) goto nomem;
    if (n == 0) goto done;

    {
        long long ts0 = PyLong_AsLongLong(
            PySequence_Fast_GET_ITEM(ts_list, 0));
        if (ts0 == -1 && PyErr_Occurred()) goto fail_free;
        if (ts0 < 0) {
            PyErr_SetString(PyExc_ValueError, "negative timestamp");
            goto fail_free;
        }
        double v0 = PyFloat_AsDouble(PySequence_Fast_GET_ITEM(val_list, 0));
        if (v0 == -1.0 && PyErr_Occurred()) goto fail_free;
        if (put_varint(&out, (uint64_t)ts0) < 0) goto nomem;
        uint64_t b0 = dbl_bits(v0);
        if (gb_reserve(&out, 8) < 0) goto nomem;
        for (int k = 7; k >= 0; k--)
            out.buf[out.len++] = (char)((b0 >> (k * 8)) & 0xFF);
        if (n == 1) goto done;

        bitw bw = {&out, 0, 0};
        int64_t prev_ts = ts0;
        int64_t prev_delta = 0;
        uint64_t prev_bits = b0;
        int win_lead = -1;
        int win_len = 0;
        for (Py_ssize_t i = 1; i < n; i++) {
            long long tsll = PyLong_AsLongLong(
                PySequence_Fast_GET_ITEM(ts_list, i));
            if (tsll == -1 && PyErr_Occurred()) goto fail_free;
            int64_t ts = (int64_t)tsll;
            int64_t delta = ts - prev_ts;
            int64_t dod = delta - prev_delta;
            prev_ts = ts;
            prev_delta = delta;
            if (dod == 0) {
                if (bw_write(&bw, 0, 1) < 0) goto nomem;
            } else if (dod >= -63 && dod <= 64) {
                if (bw_write(&bw, 2, 2) < 0) goto nomem;
                if (bw_write(&bw, (uint64_t)(dod + 63), 7) < 0) goto nomem;
            } else if (dod >= -255 && dod <= 256) {
                if (bw_write(&bw, 6, 3) < 0) goto nomem;
                if (bw_write(&bw, (uint64_t)(dod + 255), 9) < 0) goto nomem;
            } else if (dod >= -2047 && dod <= 2048) {
                if (bw_write(&bw, 14, 4) < 0) goto nomem;
                if (bw_write(&bw, (uint64_t)(dod + 2047), 12) < 0)
                    goto nomem;
            } else {
                if (bw_write(&bw, 15, 4) < 0) goto nomem;
                if (bw_write(&bw, (uint64_t)dod, 64) < 0) goto nomem;
            }
            double v = PyFloat_AsDouble(
                PySequence_Fast_GET_ITEM(val_list, i));
            if (v == -1.0 && PyErr_Occurred()) goto fail_free;
            uint64_t vb = dbl_bits(v);
            uint64_t xor = vb ^ prev_bits;
            prev_bits = vb;
            if (xor == 0) {
                if (bw_write(&bw, 0, 1) < 0) goto nomem;
                continue;
            }
            if (bw_write(&bw, 1, 1) < 0) goto nomem;
            int lead = clz64(xor);
            if (lead > 31) lead = 31;
            int trail = ctz64(xor);
            if (win_lead >= 0 && lead >= win_lead
                && trail >= 64 - win_lead - win_len) {
                if (bw_write(&bw, 0, 1) < 0) goto nomem;
                if (bw_write(&bw, xor >> (64 - win_lead - win_len),
                             win_len) < 0)
                    goto nomem;
            } else {
                int length = 64 - lead - trail;
                if (bw_write(&bw, 1, 1) < 0) goto nomem;
                if (bw_write(&bw, (uint64_t)lead, 5) < 0) goto nomem;
                if (bw_write(&bw, (uint64_t)(length - 1), 6) < 0)
                    goto nomem;
                if (bw_write(&bw, xor >> trail, length) < 0) goto nomem;
                win_lead = lead;
                win_len = length;
            }
        }
        if (bw_flush(&bw) < 0) goto nomem;
    }
done: {
        PyObject *res = PyBytes_FromStringAndSize(out.buf, out.len);
        PyMem_Free(out.buf);
        Py_DECREF(ts_list);
        Py_DECREF(val_list);
        return res;
    }
nomem:
    PyErr_NoMemory();
fail_free:
    PyMem_Free(out.buf);
fail:
    Py_DECREF(ts_list);
    Py_DECREF(val_list);
    return NULL;
}

/* MSB-first bit reader (mirrors compress.py _BitReader). */
typedef struct {
    const unsigned char *data;
    Py_ssize_t len;
    Py_ssize_t idx;
    uint64_t acc;
    int nbits;
} bitr;

static int br_read(bitr *r, int nbits, uint64_t *out) {
    uint64_t value = 0;
    int want = nbits;
    while (want > 0) {
        int take = want > 32 ? 32 : want;
        while (r->nbits < take) {
            if (r->idx >= r->len) {
                PyErr_SetString(PyExc_ValueError,
                                "truncated chunk bitstream");
                return -1;
            }
            r->acc = (r->acc << 8) | r->data[r->idx++];
            r->nbits += 8;
        }
        r->nbits -= take;
        uint64_t part = (r->acc >> r->nbits) & (((uint64_t)1 << take) - 1);
        if (r->nbits > 0)
            r->acc &= (((uint64_t)1 << r->nbits) - 1);
        else
            r->acc = 0;
        value = (take < 64) ? ((value << take) | part) : part;
        want -= take;
    }
    *out = value;
    return 0;
}

static int get_varint(const unsigned char *data, Py_ssize_t len,
                      Py_ssize_t *idx, uint64_t *out) {
    uint64_t result = 0;
    int shift = 0;
    while (1) {
        if (*idx >= len) {
            PyErr_SetString(PyExc_ValueError, "truncated varint");
            return -1;
        }
        unsigned char byte = data[(*idx)++];
        result |= ((uint64_t)(byte & 0x7F)) << shift;
        if (!(byte & 0x80)) { *out = result; return 0; }
        shift += 7;
        if (shift > 70) {
            PyErr_SetString(PyExc_ValueError, "oversized varint");
            return -1;
        }
    }
}

static PyObject *g_decode(PyObject *self, PyObject *args) {
    Py_buffer view;
    if (!PyArg_ParseTuple(args, "y*", &view)) return NULL;
    const unsigned char *data = view.buf;
    Py_ssize_t len = view.len;
    Py_ssize_t idx = 0;
    PyObject *ts_list = NULL, *val_list = NULL, *res = NULL;
    uint64_t n;
    if (get_varint(data, len, &idx, &n) < 0) goto out;
    if (n > ((uint64_t)1 << 30)) {
        PyErr_SetString(PyExc_ValueError,
                        "implausible chunk sample count");
        goto out;
    }
    ts_list = PyList_New((Py_ssize_t)n);
    val_list = PyList_New((Py_ssize_t)n);
    if (!ts_list || !val_list) goto out;
    if (n == 0) goto ok;
    uint64_t ts0;
    if (get_varint(data, len, &idx, &ts0) < 0) goto out;
    if (idx + 8 > len) {
        PyErr_SetString(PyExc_ValueError, "truncated chunk header");
        goto out;
    }
    uint64_t b0 = 0;
    for (int k = 0; k < 8; k++) b0 = (b0 << 8) | data[idx++];
    {
        PyObject *o = PyLong_FromLongLong((long long)ts0);
        if (!o) goto out;
        PyList_SET_ITEM(ts_list, 0, o);
        o = PyFloat_FromDouble(bits_dbl(b0));
        if (!o) goto out;
        PyList_SET_ITEM(val_list, 0, o);
    }
    if (n == 1) goto ok;
    {
        bitr br = {data, len, idx, 0, 0};
        int64_t prev_ts = (int64_t)ts0;
        int64_t prev_delta = 0;
        uint64_t prev_bits = b0;
        int win_lead = -1;
        int win_len = 0;
        for (uint64_t i = 1; i < n; i++) {
            uint64_t bit, raw;
            int64_t dod;
            if (br_read(&br, 1, &bit) < 0) goto out;
            if (bit == 0) {
                dod = 0;
            } else {
                if (br_read(&br, 1, &bit) < 0) goto out;
                if (bit == 0) {
                    if (br_read(&br, 7, &raw) < 0) goto out;
                    dod = (int64_t)raw - 63;
                } else {
                    if (br_read(&br, 1, &bit) < 0) goto out;
                    if (bit == 0) {
                        if (br_read(&br, 9, &raw) < 0) goto out;
                        dod = (int64_t)raw - 255;
                    } else {
                        if (br_read(&br, 1, &bit) < 0) goto out;
                        if (bit == 0) {
                            if (br_read(&br, 12, &raw) < 0) goto out;
                            dod = (int64_t)raw - 2047;
                        } else {
                            if (br_read(&br, 64, &raw) < 0) goto out;
                            dod = (int64_t)raw;
                        }
                    }
                }
            }
            prev_delta += dod;
            prev_ts += prev_delta;
            PyObject *o = PyLong_FromLongLong((long long)prev_ts);
            if (!o) goto out;
            PyList_SET_ITEM(ts_list, (Py_ssize_t)i, o);
            if (br_read(&br, 1, &bit) < 0) goto out;
            if (bit != 0) {
                if (br_read(&br, 1, &bit) < 0) goto out;
                uint64_t xor;
                if (bit == 0) {
                    if (win_lead < 0) {
                        PyErr_SetString(PyExc_ValueError,
                                        "window reuse before any window");
                        goto out;
                    }
                    if (br_read(&br, win_len, &raw) < 0) goto out;
                    xor = raw << (64 - win_lead - win_len);
                } else {
                    uint64_t lead, lenbits;
                    if (br_read(&br, 5, &lead) < 0) goto out;
                    if (br_read(&br, 6, &lenbits) < 0) goto out;
                    win_lead = (int)lead;
                    win_len = (int)lenbits + 1;
                    if (win_lead + win_len > 64) {
                        PyErr_SetString(PyExc_ValueError,
                                        "invalid XOR window");
                        goto out;
                    }
                    if (br_read(&br, win_len, &raw) < 0) goto out;
                    xor = raw << (64 - win_lead - win_len);
                }
                prev_bits ^= xor;
            }
            o = PyFloat_FromDouble(bits_dbl(prev_bits));
            if (!o) goto out;
            PyList_SET_ITEM(val_list, (Py_ssize_t)i, o);
        }
    }
ok:
    res = PyTuple_Pack(2, ts_list, val_list);
out:
    Py_XDECREF(ts_list);
    Py_XDECREF(val_list);
    PyBuffer_Release(&view);
    return res;
}

static PyMethodDef g_methods[] = {
    {"encode", g_encode, METH_VARARGS,
     "encode(timestamps, values) -> sealed chunk bytes"},
    {"decode", g_decode, METH_VARARGS,
     "decode(data) -> (timestamps, values)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef g_module = {
    PyModuleDef_HEAD_INIT, "_gorilla",
    "Gorilla chunk codec (native half of tpumon/ledger/compress.py)",
    -1, g_methods,
};

PyMODINIT_FUNC PyInit__gorilla(void) { return PyModule_Create(&g_module); }
