/* Rollup bucket-math kernel (CPython C extension).
 *
 * The fleet tier re-aggregates a slice bucket by folding its member
 * node snapshots through ``_Agg.add_node`` (tpumon/fleet/rollup.py).
 * At 10k-node fleets that Python loop IS the rollup cost: ~50 dict
 * lookups and float ops of interpreter dispatch per node, times every
 * member of every dirty bucket, every collect cycle. This module is
 * the same fold in C — one call per bucket over the member list, with
 * every arithmetic step in the same order as the Python loop.
 *
 * Two entry points, one per fold the rollup performs:
 *
 *   aggregate(members) — the _Agg.add_node loop over (snap, state)
 *     members of one slice bucket;
 *   merge(buckets)     — the merge_buckets fold over _Agg.to_dict
 *     shaped docs (pool/fleet/cross-shard merges: additive totals,
 *     n-weighted duty/MFU means, min/max, worst-of provenance).
 *
 * CONTRACT: the accumulated state is value-identical to running
 * the pure-Python fold over the same inputs in the same order (pinned
 * by tests/test_fleet_stripes.py on randomized buckets). That includes
 * Python numeric semantics:
 *   - float accumulators start at 0.0 and add in member order (IEEE
 *     double, same associativity -> bit-identical sums);
 *   - int accumulators (ici healthy/links) stay Python ints unless a
 *     float value ever lands, after which they are floats forever
 *     (the promoting accumulator below mirrors int.__add__/float);
 *   - min/max keep the ORIGINAL Python object (an int stays an int in
 *     the JSON doc), compared by value exactly like ``<``/``>``.
 * Any semantic change lands in BOTH implementations or not at all.
 *
 *   aggregate(members: list[tuple[dict, str]]) -> state tuple
 *
 * Anything shape-unexpected raises; the Python wrapper falls back to
 * the pure loop (which then raises the same error for genuinely bad
 * input, or handles what this kernel does not model).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

/* Interned dict keys: PyDict_GetItemString rebuilds a unicode
 * object per call — at 10k folds/s that was a measured share of the
 * kernel's cost. Interned once at module init. */
static struct {
    PyObject *chips;
    PyObject *duty_pct;
    PyObject *hbm_used;
    PyObject *hbm_total;
    PyObject *ici;
    PyObject *healthy;
    PyObject *total;
    PyObject *mfu;
    PyObject *step_rate;
    PyObject *energy;
    PyObject *watts;
    PyObject *source;
    PyObject *tokens_per_joule;
    PyObject *lifecycle_transition;
    PyObject *degraded;
    PyObject *active;
    PyObject *straggler;
    PyObject *skew_pct;
    PyObject *step_skew_ratio;
    PyObject *cause;
    PyObject *hosts;
    PyObject *up;
    PyObject *stale;
    PyObject *dark;
    PyObject *degraded_hosts;
    PyObject *duty;
    PyObject *n;
    PyObject *mean;
    PyObject *min;
    PyObject *max;
    PyObject *links;
    PyObject *mfu_n;
    PyObject *step_rate_n;
    PyObject *energy_watts;
    PyObject *energy_n;
    PyObject *tokens_per_joule_n;
    PyObject *energy_source;
    PyObject *lifecycle_transitions;
    PyObject *stragglers;
    PyObject *straggler_skew_max_pct;
    PyObject *straggler_step_skew_max_ratio;
    PyObject *visibility;
    PyObject *score;
    PyObject *hbm_headroom_ratio;
} K;

/* Promoting accumulator: Python `x += v` where x starts as int 0 and
 * v is int or float. Stays integral until the first float. */
typedef struct {
    int is_float;
    long long i;
    double d;
} pacc;

static int pacc_add(pacc *a, PyObject *v) {
    if (!a->is_float && PyLong_Check(v)) {
        int overflow = 0;
        long long add = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow || (add == -1 && PyErr_Occurred())) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_OverflowError, "count overflow");
            return -1;
        }
        a->i += add;
        return 0;
    }
    {
        double add = PyFloat_AsDouble(v);
        if (add == -1.0 && PyErr_Occurred()) return -1;
        if (!a->is_float) {
            a->d = (double)a->i;
            a->is_float = 1;
        }
        a->d += add;
        return 0;
    }
}

static PyObject *pacc_value(const pacc *a) {
    if (a->is_float) return PyFloat_FromDouble(a->d);
    return PyLong_FromLongLong(a->i);
}

/* value-compare a candidate against the held best object; returns 1
 * when `v OP best` is true the way Python's < / > would answer for
 * numbers (doubles; NaN compares false, exactly like Python). */
static int num_lt(double v, double best) { return v < best; }
static int num_gt(double v, double best) { return v > best; }

static double as_double(PyObject *v, int *err) {
    double d = PyFloat_AsDouble(v);
    if (d == -1.0 && PyErr_Occurred()) { *err = 1; }
    return d;
}

static PyObject *r_aggregate(PyObject *self, PyObject *args) {
    PyObject *members;
    if (!PyArg_ParseTuple(args, "O", &members)) return NULL;
    members = PySequence_Fast(members, "members must be a sequence");
    if (!members) return NULL;

    long long hosts_up = 0, hosts_stale = 0, hosts_dark = 0;
    long long chips_n = 0, duty_n = 0, mfu_n = 0, step_rate_n = 0;
    long long energy_n = 0, tpj_n = 0, lifecycle = 0, degraded_n = 0;
    double duty_sum = 0.0, hbm_used = 0.0, hbm_total = 0.0;
    double mfu_sum = 0.0, step_rate_sum = 0.0;
    double energy_watts = 0.0, tpj_sum = 0.0;
    int energy_modeled = 0;
    pacc ici_healthy = {0, 0, 0.0}, ici_links = {0, 0, 0.0};
    PyObject *duty_min = NULL, *duty_max = NULL;     /* borrowed+incref */
    PyObject *skew_max = NULL, *step_skew_max = NULL;
    PyObject *stragglers = PyDict_New();
    PyObject *res = NULL;
    if (!stragglers) { Py_DECREF(members); return NULL; }

    Py_ssize_t n = PySequence_Fast_GET_SIZE(members);
    for (Py_ssize_t m = 0; m < n; m++) {
        PyObject *item = PySequence_Fast_GET_ITEM(members, m);
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 2) {
            PyErr_SetString(PyExc_TypeError,
                            "member must be a (snap, state) tuple");
            goto fail;
        }
        PyObject *snap = PyTuple_GET_ITEM(item, 0);
        PyObject *state = PyTuple_GET_ITEM(item, 1);
        if (!PyDict_Check(snap) || !PyUnicode_Check(state)) {
            PyErr_SetString(PyExc_TypeError,
                            "member must be a (dict, str) tuple");
            goto fail;
        }
        int is_dark = 0;
        if (PyUnicode_CompareWithASCIIString(state, "up") == 0) {
            hosts_up++;
        } else if (PyUnicode_CompareWithASCIIString(state, "stale") == 0) {
            hosts_stale++;
        } else if (PyUnicode_CompareWithASCIIString(state, "dark") == 0) {
            hosts_dark++;
            is_dark = 1;
        } else {
            PyErr_Format(PyExc_KeyError, "unknown ingest state %R", state);
            goto fail;
        }
        if (is_dark) continue;  /* counted, never merged */

        PyObject *chips = PyDict_GetItem(snap, K.chips);
        if (chips != NULL) {
            if (!PyDict_Check(chips)) {
                PyErr_SetString(PyExc_TypeError, "chips must be a dict");
                goto fail;
            }
            chips_n += (long long)PyDict_GET_SIZE(chips);
            PyObject *ckey, *row;
            Py_ssize_t pos = 0;
            while (PyDict_Next(chips, &pos, &ckey, &row)) {
                if (!PyDict_Check(row)) {
                    PyErr_SetString(PyExc_TypeError,
                                    "chip row must be a dict");
                    goto fail;
                }
                PyObject *duty = PyDict_GetItem(row, K.duty_pct);
                if (duty != NULL && duty != Py_None) {
                    int err = 0;
                    double dv = as_double(duty, &err);
                    if (err) goto fail;
                    duty_sum += dv;
                    duty_n++;
                    if (duty_min == NULL) {
                        Py_INCREF(duty); duty_min = duty;
                    } else {
                        int e2 = 0;
                        double best = as_double(duty_min, &e2);
                        if (e2) goto fail;
                        if (num_lt(dv, best)) {
                            Py_INCREF(duty);
                            Py_SETREF(duty_min, duty);
                        }
                    }
                    if (duty_max == NULL) {
                        Py_INCREF(duty); duty_max = duty;
                    } else {
                        int e2 = 0;
                        double best = as_double(duty_max, &e2);
                        if (e2) goto fail;
                        if (num_gt(dv, best)) {
                            Py_INCREF(duty);
                            Py_SETREF(duty_max, duty);
                        }
                    }
                }
                PyObject *used = PyDict_GetItem(row, K.hbm_used);
                PyObject *total = PyDict_GetItem(row, K.hbm_total);
                if (used != NULL && used != Py_None
                    && total != NULL && total != Py_None) {
                    int err = 0;
                    double uv = as_double(used, &err);
                    double tv = as_double(total, &err);
                    if (err) goto fail;
                    hbm_used += uv;
                    hbm_total += tv;
                }
            }
        }
        /* ici = snap.get("ici") or {} — falsy collapses to skip */
        PyObject *ici = PyDict_GetItem(snap, K.ici);
        if (ici != NULL) {
            int truthy = PyObject_IsTrue(ici);
            if (truthy < 0) goto fail;
            if (truthy) {
                if (!PyDict_Check(ici)) {
                    PyErr_SetString(PyExc_TypeError, "ici must be a dict");
                    goto fail;
                }
                PyObject *healthy = PyDict_GetItem(ici, K.healthy);
                PyObject *total = PyDict_GetItem(ici, K.total);
                if (healthy != NULL && pacc_add(&ici_healthy, healthy) < 0)
                    goto fail;
                if (total != NULL && pacc_add(&ici_links, total) < 0)
                    goto fail;
            }
        }
        PyObject *mfu = PyDict_GetItem(snap, K.mfu);
        if (mfu != NULL && mfu != Py_None) {
            int err = 0;
            double v = as_double(mfu, &err);
            if (err) goto fail;
            mfu_sum += v;
            mfu_n++;
        }
        PyObject *step_rate = PyDict_GetItem(snap, K.step_rate);
        if (step_rate != NULL && step_rate != Py_None) {
            int err = 0;
            double v = as_double(step_rate, &err);
            if (err) goto fail;
            step_rate_sum += v;
            step_rate_n++;
        }
        PyObject *energy = PyDict_GetItem(snap, K.energy);
        int energy_truthy = 0;
        if (energy != NULL) {
            energy_truthy = PyObject_IsTrue(energy);
            if (energy_truthy < 0) goto fail;
        }
        if (energy_truthy) {
            if (!PyDict_Check(energy)) {
                PyErr_SetString(PyExc_TypeError, "energy must be a dict");
                goto fail;
            }
            PyObject *watts = PyDict_GetItem(energy, K.watts);
            PyObject *source = PyDict_GetItem(energy, K.source);
            int w_truthy = 0;
            if (watts != NULL) {
                w_truthy = PyObject_IsTrue(watts);
                if (w_truthy < 0) goto fail;
            }
            if (w_truthy) {
                int err = 0;
                double v = as_double(watts, &err);
                if (err) goto fail;
                energy_watts += v;
                energy_n++;
                if (source == NULL || !PyUnicode_Check(source)
                    || PyUnicode_CompareWithASCIIString(
                           source, "measured") != 0)
                    energy_modeled = 1;
            }
            PyObject *tpj = PyDict_GetItem(energy, K.tokens_per_joule);
            if (tpj != NULL && tpj != Py_None) {
                int err = 0;
                double v = as_double(tpj, &err);
                if (err) goto fail;
                tpj_sum += v;
                tpj_n++;
                if (source == NULL || !PyUnicode_Check(source)
                    || PyUnicode_CompareWithASCIIString(
                           source, "measured") != 0)
                    energy_modeled = 1;
            }
        }
        PyObject *transition = PyDict_GetItem(snap, K.lifecycle_transition);
        if (transition != NULL) {
            int truthy = PyObject_IsTrue(transition);
            if (truthy < 0) goto fail;
            if (truthy) lifecycle++;
        }
        PyObject *degraded = PyDict_GetItem(snap, K.degraded);
        if (degraded != NULL) {
            int truthy = PyObject_IsTrue(degraded);
            if (truthy < 0) goto fail;
            if (truthy) {
                if (!PyDict_Check(degraded)) {
                    PyErr_SetString(PyExc_TypeError,
                                    "degraded must be a dict");
                    goto fail;
                }
                PyObject *active = PyDict_GetItem(degraded, K.active);
                int a_truthy = 0;
                if (active != NULL) {
                    a_truthy = PyObject_IsTrue(active);
                    if (a_truthy < 0) goto fail;
                }
                if (a_truthy) degraded_n++;
            }
        }
        PyObject *straggler = PyDict_GetItem(snap, K.straggler);
        if (straggler != NULL) {
            int truthy = PyObject_IsTrue(straggler);
            if (truthy < 0) goto fail;
            if (truthy) {
                if (!PyDict_Check(straggler)) {
                    PyErr_SetString(PyExc_TypeError,
                                    "straggler must be a dict");
                    goto fail;
                }
                PyObject *skew = PyDict_GetItem(straggler, K.skew_pct);
                if (skew != NULL && skew != Py_None) {
                    int err = 0;
                    double v = as_double(skew, &err);
                    if (err) goto fail;
                    int take = (skew_max == NULL);
                    if (!take) {
                        int e2 = 0;
                        double best = as_double(skew_max, &e2);
                        if (e2) goto fail;
                        take = num_gt(v, best);
                    }
                    if (take) {
                        Py_INCREF(skew);
                        Py_XSETREF(skew_max, skew);
                    }
                }
                PyObject *sskew = PyDict_GetItem(straggler, K.step_skew_ratio);
                if (sskew != NULL && sskew != Py_None) {
                    int err = 0;
                    double v = as_double(sskew, &err);
                    if (err) goto fail;
                    int take = (step_skew_max == NULL);
                    if (!take) {
                        int e2 = 0;
                        double best = as_double(step_skew_max, &e2);
                        if (e2) goto fail;
                        take = num_gt(v, best);
                    }
                    if (take) {
                        Py_INCREF(sskew);
                        Py_XSETREF(step_skew_max, sskew);
                    }
                }
                PyObject *active = PyDict_GetItem(straggler, K.active);
                int a_truthy = 0;
                if (active != NULL) {
                    a_truthy = PyObject_IsTrue(active);
                    if (a_truthy < 0) goto fail;
                }
                if (a_truthy) {
                    PyObject *cause = PyDict_GetItem(straggler, K.cause);
                    PyObject *key = cause;
                    if (key == NULL) {
                        key = PyUnicode_FromString("unknown");
                        if (!key) goto fail;
                    } else {
                        Py_INCREF(key);
                    }
                    PyObject *cur = PyDict_GetItemWithError(
                        stragglers, key);
                    if (cur == NULL && PyErr_Occurred()) {
                        Py_DECREF(key);
                        goto fail;
                    }
                    long long count = 0;
                    if (cur != NULL) {
                        int overflow = 0;
                        count = PyLong_AsLongLongAndOverflow(
                            cur, &overflow);
                        if (overflow
                            || (count == -1 && PyErr_Occurred())) {
                            Py_DECREF(key);
                            goto fail;
                        }
                    }
                    PyObject *next = PyLong_FromLongLong(count + 1);
                    if (!next) { Py_DECREF(key); goto fail; }
                    int rc = PyDict_SetItem(stragglers, key, next);
                    Py_DECREF(next);
                    Py_DECREF(key);
                    if (rc < 0) goto fail;
                }
            }
        }
    }

    res = Py_BuildValue(
        "(LLLL dL OO dd NN dL dL dL N dL LL N OO)",
        hosts_up, hosts_stale, hosts_dark, chips_n,
        duty_sum, duty_n,
        duty_min ? duty_min : Py_None,
        duty_max ? duty_max : Py_None,
        hbm_used, hbm_total,
        pacc_value(&ici_healthy), pacc_value(&ici_links),
        mfu_sum, mfu_n,
        step_rate_sum, step_rate_n,
        energy_watts, energy_n,
        PyBool_FromLong(energy_modeled),
        tpj_sum, tpj_n,
        lifecycle, degraded_n,
        stragglers,
        skew_max ? skew_max : Py_None,
        step_skew_max ? step_skew_max : Py_None);
    /* Py_BuildValue "N" stole stragglers + the two pacc values; "O"
     * entries were increfed by BuildValue, so drop our own refs. */
    Py_XDECREF(duty_min);
    Py_XDECREF(duty_max);
    Py_XDECREF(skew_max);
    Py_XDECREF(step_skew_max);
    Py_DECREF(members);
    return res;

fail:
    Py_XDECREF(duty_min);
    Py_XDECREF(duty_max);
    Py_XDECREF(skew_max);
    Py_XDECREF(step_skew_max);
    Py_DECREF(stragglers);
    Py_DECREF(members);
    return NULL;
}

/* _Agg.to_dict, in C, from r_aggregate's state tuple — the per-bucket
 * doc construction was the last interpreter-bound cost in the rollup
 * hot loop. Mirrors to_dict field for field (conditional presence,
 * true-division semantics, original min/max objects). */
static PyObject *doc_from_state(PyObject *st) {
    PyObject *doc = NULL, *tmp = NULL;
    long long hosts_up = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 0));
    long long hosts_stale = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 1));
    long long hosts_dark = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 2));
    long long duty_n = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 5));
    double duty_sum = PyFloat_AsDouble(PyTuple_GET_ITEM(st, 4));
    double hbm_used = PyFloat_AsDouble(PyTuple_GET_ITEM(st, 8));
    double hbm_total = PyFloat_AsDouble(PyTuple_GET_ITEM(st, 9));
    double mfu_sum = PyFloat_AsDouble(PyTuple_GET_ITEM(st, 12));
    long long mfu_n = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 13));
    double sr_sum = PyFloat_AsDouble(PyTuple_GET_ITEM(st, 14));
    long long sr_n = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 15));
    double watts = PyFloat_AsDouble(PyTuple_GET_ITEM(st, 16));
    long long energy_n = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 17));
    int modeled = PyObject_IsTrue(PyTuple_GET_ITEM(st, 18));
    double tpj_sum = PyFloat_AsDouble(PyTuple_GET_ITEM(st, 19));
    long long tpj_n = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 20));
    long long lifecycle = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 21));
    long long degraded = PyLong_AsLongLong(PyTuple_GET_ITEM(st, 22));
    PyObject *ici_healthy = PyTuple_GET_ITEM(st, 10);
    PyObject *ici_links = PyTuple_GET_ITEM(st, 11);
    PyObject *stragglers = PyTuple_GET_ITEM(st, 23);
    if (PyErr_Occurred() || modeled < 0) return NULL;

#define SET(key, valexpr) \
    do { \
        tmp = (valexpr); \
        if (!tmp) goto fail; \
        if (PyDict_SetItem(doc, (key), tmp) < 0) goto fail; \
        Py_CLEAR(tmp); \
    } while (0)

    doc = PyDict_New();
    if (!doc) return NULL;
    {
        PyObject *hosts = PyDict_New();
        if (!hosts) goto fail;
        tmp = hosts;  /* owned until stored */
        PyObject *v = PyLong_FromLongLong(hosts_up);
        if (!v || PyDict_SetItem(hosts, K.up, v) < 0) {
            Py_XDECREF(v); goto fail;
        }
        Py_DECREF(v);
        v = PyLong_FromLongLong(hosts_stale);
        if (!v || PyDict_SetItem(hosts, K.stale, v) < 0) {
            Py_XDECREF(v); goto fail;
        }
        Py_DECREF(v);
        v = PyLong_FromLongLong(hosts_dark);
        if (!v || PyDict_SetItem(hosts, K.dark, v) < 0) {
            Py_XDECREF(v); goto fail;
        }
        Py_DECREF(v);
        if (PyDict_SetItem(doc, K.hosts, hosts) < 0) goto fail;
        Py_CLEAR(tmp);
    }
    SET(K.chips, PyLong_FromLongLong(
        PyLong_AsLongLong(PyTuple_GET_ITEM(st, 3))));
    SET(K.degraded_hosts, PyLong_FromLongLong(degraded));
    SET(K.stale, PyBool_FromLong(hosts_stale > 0));
    {
        long long total = hosts_up + hosts_stale + hosts_dark;
        double vis = total <= 0 ? 1.0 : (double)hosts_up / (double)total;
        SET(K.visibility, PyFloat_FromDouble(vis));
    }
    if (duty_n) {
        PyObject *duty = PyDict_New();
        if (!duty) goto fail;
        tmp = duty;
        PyObject *v = PyFloat_FromDouble(duty_sum / (double)duty_n);
        if (!v || PyDict_SetItem(duty, K.mean, v) < 0) {
            Py_XDECREF(v); goto fail;
        }
        Py_DECREF(v);
        if (PyDict_SetItem(duty, K.min, PyTuple_GET_ITEM(st, 6)) < 0)
            goto fail;
        if (PyDict_SetItem(duty, K.max, PyTuple_GET_ITEM(st, 7)) < 0)
            goto fail;
        v = PyLong_FromLongLong(duty_n);
        if (!v || PyDict_SetItem(duty, K.n, v) < 0) {
            Py_XDECREF(v); goto fail;
        }
        Py_DECREF(v);
        if (PyDict_SetItem(doc, K.duty, duty) < 0) goto fail;
        Py_CLEAR(tmp);
    }
    if (hbm_total > 0.0) {
        SET(K.hbm_used, PyFloat_FromDouble(hbm_used));
        SET(K.hbm_total, PyFloat_FromDouble(hbm_total));
        SET(K.hbm_headroom_ratio,
            PyFloat_FromDouble(1.0 - hbm_used / hbm_total));
    }
    {
        int links_truthy = PyObject_IsTrue(ici_links);
        if (links_truthy < 0) goto fail;
        if (links_truthy) {
            PyObject *ici = PyDict_New();
            if (!ici) goto fail;
            tmp = ici;
            if (PyDict_SetItem(ici, K.healthy, ici_healthy) < 0) goto fail;
            if (PyDict_SetItem(ici, K.links, ici_links) < 0) goto fail;
            PyObject *score = PyNumber_TrueDivide(ici_healthy, ici_links);
            if (!score || PyDict_SetItem(ici, K.score, score) < 0) {
                Py_XDECREF(score); goto fail;
            }
            Py_DECREF(score);
            if (PyDict_SetItem(doc, K.ici, ici) < 0) goto fail;
            Py_CLEAR(tmp);
        }
    }
    if (mfu_n) {
        SET(K.mfu, PyFloat_FromDouble(mfu_sum / (double)mfu_n));
        SET(K.mfu_n, PyLong_FromLongLong(mfu_n));
    }
    if (sr_n) {
        SET(K.step_rate, PyFloat_FromDouble(sr_sum / (double)sr_n));
        SET(K.step_rate_n, PyLong_FromLongLong(sr_n));
    }
    if (energy_n || tpj_n) {
        PyObject *src = PyUnicode_FromString(
            modeled ? "modeled" : "measured");
        if (!src || PyDict_SetItem(doc, K.energy_source, src) < 0) {
            Py_XDECREF(src); goto fail;
        }
        Py_DECREF(src);
    }
    if (energy_n) {
        SET(K.energy_watts, PyFloat_FromDouble(watts));
        SET(K.energy_n, PyLong_FromLongLong(energy_n));
    }
    if (tpj_n) {
        SET(K.tokens_per_joule,
            PyFloat_FromDouble(tpj_sum / (double)tpj_n));
        SET(K.tokens_per_joule_n, PyLong_FromLongLong(tpj_n));
    }
    if (lifecycle) {
        SET(K.lifecycle_transitions, PyLong_FromLongLong(lifecycle));
    }
    if (PyDict_GET_SIZE(stragglers)) {
        /* to_dict copies; the state tuple is transient here, but a
         * caller holding both must not see shared mutation. */
        SET(K.stragglers, PyDict_Copy(stragglers));
    }
    if (PyTuple_GET_ITEM(st, 24) != Py_None) {
        if (PyDict_SetItem(doc, K.straggler_skew_max_pct,
                           PyTuple_GET_ITEM(st, 24)) < 0)
            goto fail;
    }
    if (PyTuple_GET_ITEM(st, 25) != Py_None) {
        if (PyDict_SetItem(doc, K.straggler_step_skew_max_ratio,
                           PyTuple_GET_ITEM(st, 25)) < 0)
            goto fail;
    }
#undef SET
    return doc;

fail:
    Py_XDECREF(tmp);
    Py_XDECREF(doc);
    return NULL;
}

/* aggregate_doc(members) -> the _Agg.to_dict doc for one bucket fold
 * (aggregate + doc construction without touching the interpreter). */
static PyObject *r_aggregate_doc(PyObject *self, PyObject *args) {
    PyObject *state = r_aggregate(self, args);
    if (!state) return NULL;
    PyObject *doc = doc_from_state(state);
    Py_DECREF(state);
    return doc;
}

/* Python int(value) over the number types a merge doc carries (peer
 * summaries arrive as JSON: ints and floats). Anything else raises —
 * the wrapper falls back to the Python fold, which coerces or raises
 * identically. Float truncation is toward zero, like int(). */
static int as_count(PyObject *v, long long *out) {
    if (PyLong_Check(v)) {
        int overflow = 0;
        long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow || (x == -1 && PyErr_Occurred())) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_OverflowError, "count overflow");
            return -1;
        }
        *out = x;
        return 0;
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        if (d != d || d >= 9.2e18 || d <= -9.2e18) {
            PyErr_SetString(PyExc_ValueError, "non-finite count");
            return -1;
        }
        *out = (long long)d;
        return 0;
    }
    PyErr_SetString(PyExc_TypeError, "count must be a number");
    return -1;
}

/* Python float(value) over ints/floats; anything else raises (the
 * Python fold is the arbiter for exotic coercions). */
static int as_floatv(PyObject *v, double *out) {
    if (PyFloat_Check(v)) { *out = PyFloat_AS_DOUBLE(v); return 0; }
    if (PyLong_Check(v)) {
        double d = PyLong_AsDouble(v);
        if (d == -1.0 && PyErr_Occurred()) return -1;
        *out = d;
        return 0;
    }
    PyErr_SetString(PyExc_TypeError, "value must be a number");
    return -1;
}

/* merge(buckets: list[dict]) -> state tuple + (duty_missing,
 * mfu_missing, any_stale) — the merge_buckets fold (additive totals,
 * n-weighted means, min/max keeping original objects, worst-of
 * provenance), value-identical to the pinned Python loop. */
static PyObject *r_merge(PyObject *self, PyObject *args) {
    PyObject *buckets;
    if (!PyArg_ParseTuple(args, "O", &buckets)) return NULL;
    buckets = PySequence_Fast(buckets, "buckets must be a sequence");
    if (!buckets) return NULL;

    long long hosts_up = 0, hosts_stale = 0, hosts_dark = 0;
    long long chips_n = 0, degraded_n = 0, duty_n = 0, mfu_n = 0;
    long long step_rate_n = 0, energy_n = 0, tpj_n = 0, lifecycle = 0;
    long long ici_healthy = 0, ici_links = 0;
    double duty_sum = 0.0, hbm_used = 0.0, hbm_total = 0.0;
    double mfu_sum = 0.0, step_rate_sum = 0.0;
    double energy_watts = 0.0, tpj_sum = 0.0;
    int energy_modeled = 0, duty_missing = 0, mfu_missing = 0;
    int any_stale = 0;
    PyObject *duty_min = NULL, *duty_max = NULL;
    PyObject *skew_max = NULL, *step_skew_max = NULL;
    PyObject *stragglers = PyDict_New();
    PyObject *res = NULL;
    if (!stragglers) { Py_DECREF(buckets); return NULL; }

    Py_ssize_t nb = PySequence_Fast_GET_SIZE(buckets);
    for (Py_ssize_t b = 0; b < nb; b++) {
        PyObject *bucket = PySequence_Fast_GET_ITEM(buckets, b);
        int truthy = PyObject_IsTrue(bucket);
        if (truthy < 0) goto fail;
        if (!truthy) continue;
        if (!PyDict_Check(bucket)) {
            PyErr_SetString(PyExc_TypeError, "bucket must be a dict");
            goto fail;
        }
        long long c;
        double d;
        PyObject *hosts = PyDict_GetItem(bucket, K.hosts);
        if (hosts != NULL) {
            if (!PyDict_Check(hosts)) {
                PyErr_SetString(PyExc_TypeError, "hosts must be a dict");
                goto fail;
            }
            PyObject *v = PyDict_GetItem(hosts, K.up);
            if (v != NULL) { if (as_count(v, &c) < 0) goto fail; hosts_up += c; }
            v = PyDict_GetItem(hosts, K.stale);
            if (v != NULL) { if (as_count(v, &c) < 0) goto fail; hosts_stale += c; }
            v = PyDict_GetItem(hosts, K.dark);
            if (v != NULL) { if (as_count(v, &c) < 0) goto fail; hosts_dark += c; }
        }
        PyObject *v = PyDict_GetItem(bucket, K.chips);
        if (v != NULL) { if (as_count(v, &c) < 0) goto fail; chips_n += c; }
        v = PyDict_GetItem(bucket, K.degraded_hosts);
        if (v != NULL) { if (as_count(v, &c) < 0) goto fail; degraded_n += c; }
        PyObject *duty = PyDict_GetItem(bucket, K.duty);
        if (duty != NULL) {
            int d_truthy = PyObject_IsTrue(duty);
            if (d_truthy < 0) goto fail;
            if (d_truthy) {
                if (!PyDict_Check(duty)) {
                    PyErr_SetString(PyExc_TypeError, "duty must be a dict");
                    goto fail;
                }
                PyObject *nobj = PyDict_GetItem(duty, K.n);
                int n_truthy = nobj != NULL ? PyObject_IsTrue(nobj) : 0;
                if (n_truthy < 0) goto fail;
                if (n_truthy) {
                    long long n;
                    if (as_count(nobj, &n) < 0) goto fail;
                    PyObject *mean = PyDict_GetItem(duty, K.mean);
                    if (mean == NULL) {
                        PyErr_SetString(PyExc_KeyError, "duty.mean");
                        goto fail;
                    }
                    if (as_floatv(mean, &d) < 0) goto fail;
                    duty_sum += d * (double)n;
                    duty_n += n;
                    PyObject *vmin = PyDict_GetItem(duty, K.min);
                    if (vmin != NULL && vmin != Py_None) {
                        int take = (duty_min == NULL);
                        if (!take) {
                            int e = 0;
                            double nd = as_double(vmin, &e);
                            double cd = as_double(duty_min, &e);
                            if (e) goto fail;
                            take = num_lt(nd, cd);
                        }
                        if (take) {
                            Py_INCREF(vmin);
                            Py_XSETREF(duty_min, vmin);
                        }
                    }
                    PyObject *vmax = PyDict_GetItem(duty, K.max);
                    if (vmax != NULL && vmax != Py_None) {
                        int take = (duty_max == NULL);
                        if (!take) {
                            int e = 0;
                            double nd = as_double(vmax, &e);
                            double cd = as_double(duty_max, &e);
                            if (e) goto fail;
                            take = num_gt(nd, cd);
                        }
                        if (take) {
                            Py_INCREF(vmax);
                            Py_XSETREF(duty_max, vmax);
                        }
                    }
                } else {
                    /* Pre-failover peer without the n weight: means
                     * cannot merge honestly — the doc drops duty. */
                    duty_missing = 1;
                }
            }
        }
        v = PyDict_GetItem(bucket, K.hbm_used);
        if (v != NULL) { if (as_floatv(v, &d) < 0) goto fail; hbm_used += d; }
        v = PyDict_GetItem(bucket, K.hbm_total);
        if (v != NULL) { if (as_floatv(v, &d) < 0) goto fail; hbm_total += d; }
        PyObject *ici = PyDict_GetItem(bucket, K.ici);
        if (ici != NULL) {
            int i_truthy = PyObject_IsTrue(ici);
            if (i_truthy < 0) goto fail;
            if (i_truthy) {
                if (!PyDict_Check(ici)) {
                    PyErr_SetString(PyExc_TypeError, "ici must be a dict");
                    goto fail;
                }
                v = PyDict_GetItem(ici, K.healthy);
                if (v != NULL) { if (as_count(v, &c) < 0) goto fail; ici_healthy += c; }
                v = PyDict_GetItem(ici, K.links);
                if (v != NULL) { if (as_count(v, &c) < 0) goto fail; ici_links += c; }
            }
        }
        PyObject *mfu = PyDict_GetItem(bucket, K.mfu);
        if (mfu != NULL && mfu != Py_None) {
            long long n = 0;
            v = PyDict_GetItem(bucket, K.mfu_n);
            if (v != NULL) { if (as_count(v, &n) < 0) goto fail; }
            if (n) {
                if (as_floatv(mfu, &d) < 0) goto fail;
                mfu_sum += d * (double)n;
                mfu_n += n;
            } else {
                mfu_missing = 1;
            }
        }
        PyObject *sr = PyDict_GetItem(bucket, K.step_rate);
        if (sr != NULL && sr != Py_None) {
            long long n = 0;
            v = PyDict_GetItem(bucket, K.step_rate_n);
            if (v != NULL) { if (as_count(v, &n) < 0) goto fail; }
            if (n) {
                if (as_floatv(sr, &d) < 0) goto fail;
                step_rate_sum += d * (double)n;
                step_rate_n += n;
            }
        }
        PyObject *ew = PyDict_GetItem(bucket, K.energy_watts);
        if (ew != NULL && ew != Py_None) {
            if (as_floatv(ew, &d) < 0) goto fail;
            energy_watts += d;
            long long n = 1;
            v = PyDict_GetItem(bucket, K.energy_n);
            if (v != NULL) { if (as_count(v, &n) < 0) goto fail; }
            energy_n += n;
        }
        PyObject *tpj = PyDict_GetItem(bucket, K.tokens_per_joule);
        if (tpj != NULL && tpj != Py_None) {
            long long n = 0;
            v = PyDict_GetItem(bucket, K.tokens_per_joule_n);
            if (v != NULL) { if (as_count(v, &n) < 0) goto fail; }
            if (n) {
                if (as_floatv(tpj, &d) < 0) goto fail;
                tpj_sum += d * (double)n;
                tpj_n += n;
            }
        }
        PyObject *src = PyDict_GetItem(bucket, K.energy_source);
        if (src != NULL && PyUnicode_Check(src)
            && PyUnicode_CompareWithASCIIString(src, "modeled") == 0)
            energy_modeled = 1;
        v = PyDict_GetItem(bucket, K.lifecycle_transitions);
        if (v != NULL) { if (as_count(v, &c) < 0) goto fail; lifecycle += c; }
        PyObject *stg = PyDict_GetItem(bucket, K.stragglers);
        if (stg != NULL) {
            if (!PyDict_Check(stg)) {
                PyErr_SetString(PyExc_TypeError,
                                "stragglers must be a dict");
                goto fail;
            }
            PyObject *cause, *count;
            Py_ssize_t pos = 0;
            while (PyDict_Next(stg, &pos, &cause, &count)) {
                long long add;
                if (as_count(count, &add) < 0) goto fail;
                long long cur = 0;
                PyObject *curo = PyDict_GetItemWithError(stragglers, cause);
                if (curo == NULL && PyErr_Occurred()) goto fail;
                if (curo != NULL && as_count(curo, &cur) < 0) goto fail;
                PyObject *next = PyLong_FromLongLong(cur + add);
                if (!next) goto fail;
                int rc = PyDict_SetItem(stragglers, cause, next);
                Py_DECREF(next);
                if (rc < 0) goto fail;
            }
        }
        v = PyDict_GetItem(bucket, K.straggler_skew_max_pct);
        if (v != NULL && v != Py_None) {
            int take = (skew_max == NULL);
            if (!take) {
                int e = 0;
                double nd = as_double(v, &e);
                double cd = as_double(skew_max, &e);
                if (e) goto fail;
                take = num_gt(nd, cd);
            }
            if (take) { Py_INCREF(v); Py_XSETREF(skew_max, v); }
        }
        v = PyDict_GetItem(bucket, K.straggler_step_skew_max_ratio);
        if (v != NULL && v != Py_None) {
            int take = (step_skew_max == NULL);
            if (!take) {
                int e = 0;
                double nd = as_double(v, &e);
                double cd = as_double(step_skew_max, &e);
                if (e) goto fail;
                take = num_gt(nd, cd);
            }
            if (take) { Py_INCREF(v); Py_XSETREF(step_skew_max, v); }
        }
        v = PyDict_GetItem(bucket, K.stale);
        if (v != NULL) {
            int s_truthy = PyObject_IsTrue(v);
            if (s_truthy < 0) goto fail;
            if (s_truthy) any_stale = 1;
        }
    }

    res = Py_BuildValue(
        "(LLLL dL OO dd NN dL dL dL N dL LL N OO NNN)",
        hosts_up, hosts_stale, hosts_dark, chips_n,
        duty_sum, duty_n,
        duty_min ? duty_min : Py_None,
        duty_max ? duty_max : Py_None,
        hbm_used, hbm_total,
        PyLong_FromLongLong(ici_healthy), PyLong_FromLongLong(ici_links),
        mfu_sum, mfu_n,
        step_rate_sum, step_rate_n,
        energy_watts, energy_n,
        PyBool_FromLong(energy_modeled),
        tpj_sum, tpj_n,
        lifecycle, degraded_n,
        stragglers,
        skew_max ? skew_max : Py_None,
        step_skew_max ? step_skew_max : Py_None,
        PyBool_FromLong(duty_missing),
        PyBool_FromLong(mfu_missing),
        PyBool_FromLong(any_stale));
    Py_XDECREF(duty_min);
    Py_XDECREF(duty_max);
    Py_XDECREF(skew_max);
    Py_XDECREF(step_skew_max);
    Py_DECREF(buckets);
    return res;

fail:
    Py_XDECREF(duty_min);
    Py_XDECREF(duty_max);
    Py_XDECREF(skew_max);
    Py_XDECREF(step_skew_max);
    Py_DECREF(stragglers);
    Py_DECREF(buckets);
    return NULL;
}

static PyMethodDef r_methods[] = {
    {"aggregate", r_aggregate, METH_VARARGS,
     "aggregate(members) -> accumulated _Agg state tuple"},
    {"aggregate_doc", r_aggregate_doc, METH_VARARGS,
     "aggregate_doc(members) -> _Agg.to_dict doc for one bucket"},
    {"merge", r_merge, METH_VARARGS,
     "merge(buckets) -> merged _Agg state tuple + "
     "(duty_missing, mfu_missing, any_stale)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef r_module = {
    PyModuleDef_HEAD_INIT, "_rollup",
    "Rollup bucket-math kernel (native half of tpumon/fleet/rollup.py)",
    -1, r_methods,
};

PyMODINIT_FUNC PyInit__rollup(void) {
    PyObject *mod = PyModule_Create(&r_module);
    if (!mod) return NULL;
#define INTERN(name) \
    K.name = PyUnicode_InternFromString(#name); \
    if (!K.name) { Py_DECREF(mod); return NULL; }
    INTERN(chips)
    INTERN(duty_pct)
    INTERN(hbm_used)
    INTERN(hbm_total)
    INTERN(ici)
    INTERN(healthy)
    INTERN(total)
    INTERN(mfu)
    INTERN(step_rate)
    INTERN(energy)
    INTERN(watts)
    INTERN(source)
    INTERN(tokens_per_joule)
    INTERN(lifecycle_transition)
    INTERN(degraded)
    INTERN(active)
    INTERN(straggler)
    INTERN(skew_pct)
    INTERN(step_skew_ratio)
    INTERN(cause)
    INTERN(hosts)
    INTERN(up)
    INTERN(stale)
    INTERN(dark)
    INTERN(degraded_hosts)
    INTERN(duty)
    INTERN(n)
    INTERN(mean)
    INTERN(min)
    INTERN(max)
    INTERN(links)
    INTERN(mfu_n)
    INTERN(step_rate_n)
    INTERN(energy_watts)
    INTERN(energy_n)
    INTERN(tokens_per_joule_n)
    INTERN(energy_source)
    INTERN(lifecycle_transitions)
    INTERN(stragglers)
    INTERN(straggler_skew_max_pct)
    INTERN(straggler_step_skew_max_ratio)
    INTERN(visibility)
    INTERN(score)
    INTERN(hbm_headroom_ratio)
#undef INTERN
    return mod;
}
