/* In-process sample-history engine — the DCGM hostengine/field-cache
 * analogue (SURVEY.md §2.1 "DCGM engine" row; dcgmi field watches keep a
 * bounded per-field sample cache with max-keep-age/max-keep-samples
 * semantics).
 *
 * The exporter polls at 1 Hz but Prometheus typically scrapes at 15-60 s,
 * so transients (duty-cycle spikes, throttle events, ICI link flaps)
 * alias away.  This engine is the 1 Hz flight recorder: each poll cycle
 * appends every sample point to a bounded per-series ring, and the
 * /history endpoint + `tpumon smi` read windowed summaries
 * (min/max/avg/last/rate) or raw points back out.
 *
 * C++ because this is runtime infrastructure, not compute: the hot call
 * is record_batch() on the poll thread (hundreds of points on a v5p-64
 * host), and queries come from HTTP threads concurrently — a
 * std::recursive_mutex guards the map independently of the GIL so a
 * mid-query allocation that triggers GC re-entry can never corrupt or
 * deadlock the structure.  Python fallback with identical semantics lives
 * in tpumon/history.py for no-compiler environments.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cmath>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>

namespace {

struct Sample {
  double ts;
  double value;
};

struct Series {
  std::deque<Sample> samples;
};

struct EngineState {
  double max_age = 600.0;
  Py_ssize_t max_samples = 4096;
  std::unordered_map<std::string, Series> series;
  unsigned long record_calls = 0;
  std::recursive_mutex mu;
};

struct EngineObject {
  PyObject_HEAD
  EngineState *state;
};

int Engine_init(PyObject *self, PyObject *args, PyObject *kwds) {
  static const char *kwlist[] = {"max_age", "max_samples", nullptr};
  double max_age = 600.0;
  Py_ssize_t max_samples = 4096;
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "|dn",
                                   const_cast<char **>(kwlist), &max_age,
                                   &max_samples))
    return -1;
  if (max_age <= 0 || max_samples <= 0) {
    PyErr_SetString(PyExc_ValueError, "max_age and max_samples must be > 0");
    return -1;
  }
  EngineObject *e = reinterpret_cast<EngineObject *>(self);
  if (e->state == nullptr) {
    /* tp_alloc zero-fills, so first __init__ sees nullptr. */
    e->state = new EngineState();
    e->state->max_age = max_age;
    e->state->max_samples = max_samples;
  } else {
    /* Re-running __init__ must not delete a state whose mutex another
     * thread may hold (use-after-free): keep the pointer stable and
     * reset the contents under that same mutex instead. */
    std::lock_guard<std::recursive_mutex> lock(e->state->mu);
    e->state->series.clear();
    e->state->record_calls = 0;
    e->state->max_age = max_age;
    e->state->max_samples = max_samples;
  }
  return 0;
}

void Engine_dealloc(PyObject *self) {
  EngineObject *e = reinterpret_cast<EngineObject *>(self);
  delete e->state;
  e->state = nullptr;
  PyTypeObject *tp = Py_TYPE(self);
  tp->tp_free(self);
  Py_DECREF(tp);
}

void evict(Series &s, double now, const EngineState &st) {
  const double horizon = now - st.max_age;
  while (!s.samples.empty() &&
         (s.samples.front().ts < horizon ||
          static_cast<Py_ssize_t>(s.samples.size()) > st.max_samples))
    s.samples.pop_front();
}

/* record_batch(ts, items): items is a sequence of (key: str, value: float).
 * Every 256 calls, series whose newest sample has aged out are dropped so
 * label churn (pods coming and going) cannot grow the map unboundedly. */
PyObject *Engine_record_batch(PyObject *self, PyObject *args) {
  double ts;
  PyObject *items;
  if (!PyArg_ParseTuple(args, "dO", &ts, &items)) return nullptr;
  PyObject *fast = PySequence_Fast(items, "items must be a sequence");
  if (fast == nullptr) return nullptr;

  EngineState *st = reinterpret_cast<EngineObject *>(self)->state;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  {
    std::lock_guard<std::recursive_mutex> lock(st->mu);
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
      if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 2) {
        Py_DECREF(fast);
        PyErr_SetString(PyExc_TypeError, "items must be (str, float) tuples");
        return nullptr;
      }
      PyObject *key_obj = PyTuple_GET_ITEM(item, 0);
      Py_ssize_t key_len = 0;
      const char *key = PyUnicode_AsUTF8AndSize(key_obj, &key_len);
      if (key == nullptr) {
        Py_DECREF(fast);
        return nullptr;
      }
      double value = PyFloat_AsDouble(PyTuple_GET_ITEM(item, 1));
      if (value == -1.0 && PyErr_Occurred()) {
        Py_DECREF(fast);
        return nullptr;
      }
      Series &s = st->series[std::string(key, key_len)];
      s.samples.push_back({ts, value});
      evict(s, ts, *st);
    }
    if (++st->record_calls % 256 == 0) {
      const double horizon = ts - st->max_age;
      for (auto it = st->series.begin(); it != st->series.end();) {
        if (it->second.samples.empty() ||
            it->second.samples.back().ts < horizon)
          it = st->series.erase(it);
        else
          ++it;
      }
    }
  }
  Py_DECREF(fast);
  Py_RETURN_NONE;
}

/* query(key, since=0.0) -> list[(ts, value)] (empty for unknown key). */
PyObject *Engine_query(PyObject *self, PyObject *args, PyObject *kwds) {
  static const char *kwlist[] = {"key", "since", nullptr};
  const char *key;
  double since = 0.0;
  if (!PyArg_ParseTupleAndKeywords(args, kwds, "s|d",
                                   const_cast<char **>(kwlist), &key, &since))
    return nullptr;
  EngineState *st = reinterpret_cast<EngineObject *>(self)->state;

  /* Copy matching samples out under the lock, build Python objects after:
   * object allocation can trigger GC and arbitrary re-entry. */
  std::deque<Sample> copy;
  {
    std::lock_guard<std::recursive_mutex> lock(st->mu);
    auto it = st->series.find(key);
    if (it != st->series.end()) {
      for (const Sample &s : it->second.samples)
        if (s.ts >= since) copy.push_back(s);
    }
  }
  PyObject *out = PyList_New(static_cast<Py_ssize_t>(copy.size()));
  if (out == nullptr) return nullptr;
  Py_ssize_t i = 0;
  for (const Sample &s : copy) {
    PyObject *pair = Py_BuildValue("(dd)", s.ts, s.value);
    if (pair == nullptr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i++, pair);
  }
  return out;
}

PyObject *summary_dict(const std::deque<Sample> &samples, double lo) {
  double mn = 0, mx = 0, sum = 0, first = 0, last = 0;
  double first_ts = 0, last_ts = 0;
  long count = 0;
  for (const Sample &s : samples) {
    if (s.ts < lo) continue;
    if (count == 0) {
      mn = mx = first = s.value;
      first_ts = s.ts;
    } else {
      mn = std::min(mn, s.value);
      mx = std::max(mx, s.value);
    }
    last = s.value;
    last_ts = s.ts;
    sum += s.value;
    count++;
  }
  if (count == 0) Py_RETURN_NONE;
  double dt = last_ts - first_ts;
  double rate = dt > 0 ? (last - first) / dt : 0.0;
  return Py_BuildValue(
      "{s:l,s:d,s:d,s:d,s:d,s:d,s:d,s:d,s:d}", "count", count, "min", mn,
      "max", mx, "avg", sum / count, "first", first, "last", last, "first_ts",
      first_ts, "last_ts", last_ts, "rate", rate);
}

/* summarize(key, window, now) -> dict | None */
PyObject *Engine_summarize(PyObject *self, PyObject *args) {
  const char *key;
  double window, now;
  if (!PyArg_ParseTuple(args, "sdd", &key, &window, &now)) return nullptr;
  EngineState *st = reinterpret_cast<EngineObject *>(self)->state;
  std::deque<Sample> copy;
  {
    std::lock_guard<std::recursive_mutex> lock(st->mu);
    auto it = st->series.find(key);
    if (it == st->series.end()) Py_RETURN_NONE;
    copy = it->second.samples;
  }
  return summary_dict(copy, now - window);
}

/* summarize_all(window, now) -> {key: dict} (series with no samples in the
 * window are omitted). */
PyObject *Engine_summarize_all(PyObject *self, PyObject *args) {
  double window, now;
  if (!PyArg_ParseTuple(args, "dd", &window, &now)) return nullptr;
  EngineState *st = reinterpret_cast<EngineObject *>(self)->state;
  std::unordered_map<std::string, std::deque<Sample>> copy;
  {
    std::lock_guard<std::recursive_mutex> lock(st->mu);
    for (const auto &kv : st->series) copy[kv.first] = kv.second.samples;
  }
  PyObject *out = PyDict_New();
  if (out == nullptr) return nullptr;
  for (const auto &kv : copy) {
    PyObject *summary = summary_dict(kv.second, now - window);
    if (summary == nullptr) {
      Py_DECREF(out);
      return nullptr;
    }
    if (summary == Py_None) {
      Py_DECREF(summary);
      continue;
    }
    int rc = PyDict_SetItemString(out, kv.first.c_str(), summary);
    Py_DECREF(summary);
    if (rc < 0) {
      Py_DECREF(out);
      return nullptr;
    }
  }
  return out;
}

PyObject *Engine_keys(PyObject *self, PyObject *) {
  EngineState *st = reinterpret_cast<EngineObject *>(self)->state;
  std::deque<std::string> copy;
  {
    std::lock_guard<std::recursive_mutex> lock(st->mu);
    for (const auto &kv : st->series) copy.push_back(kv.first);
  }
  std::sort(copy.begin(), copy.end());
  PyObject *out = PyList_New(static_cast<Py_ssize_t>(copy.size()));
  if (out == nullptr) return nullptr;
  Py_ssize_t i = 0;
  for (const std::string &k : copy) {
    PyObject *s = PyUnicode_FromStringAndSize(k.data(), k.size());
    if (s == nullptr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i++, s);
  }
  return out;
}

/* stats() -> (n_series, n_samples) */
PyObject *Engine_stats(PyObject *self, PyObject *) {
  EngineState *st = reinterpret_cast<EngineObject *>(self)->state;
  size_t n_series, n_samples = 0;
  {
    std::lock_guard<std::recursive_mutex> lock(st->mu);
    n_series = st->series.size();
    for (const auto &kv : st->series) n_samples += kv.second.samples.size();
  }
  return Py_BuildValue("(nn)", static_cast<Py_ssize_t>(n_series),
                       static_cast<Py_ssize_t>(n_samples));
}

PyMethodDef Engine_methods[] = {
    {"record_batch", Engine_record_batch, METH_VARARGS,
     "record_batch(ts, [(key, value), ...])"},
    {"query", reinterpret_cast<PyCFunction>(Engine_query),
     METH_VARARGS | METH_KEYWORDS, "query(key, since=0.0) -> [(ts, value)]"},
    {"summarize", Engine_summarize, METH_VARARGS,
     "summarize(key, window, now) -> dict | None"},
    {"summarize_all", Engine_summarize_all, METH_VARARGS,
     "summarize_all(window, now) -> {key: dict}"},
    {"keys", Engine_keys, METH_NOARGS, "keys() -> [str]"},
    {"stats", Engine_stats, METH_NOARGS, "stats() -> (n_series, n_samples)"},
    {nullptr, nullptr, 0, nullptr},
};

PyType_Slot Engine_slots[] = {
    {Py_tp_init, reinterpret_cast<void *>(Engine_init)},
    {Py_tp_dealloc, reinterpret_cast<void *>(Engine_dealloc)},
    {Py_tp_methods, Engine_methods},
    {Py_tp_doc,
     const_cast<char *>("Bounded per-series sample-history ring "
                        "(max_age seconds, max_samples per series).")},
    {0, nullptr},
};

PyType_Spec Engine_spec = {
    "tpumon._native._history.Engine",
    sizeof(EngineObject),
    0,
    Py_TPFLAGS_DEFAULT,
    Engine_slots,
};

PyModuleDef history_module = {
    PyModuleDef_HEAD_INIT, "_history",
    "Native sample-history engine (DCGM field-cache analogue).", -1,
    nullptr,
};

}  // namespace

extern "C" PyMODINIT_FUNC PyInit__history(void) {
  PyObject *mod = PyModule_Create(&history_module);
  if (mod == nullptr) return nullptr;
  PyObject *engine_type = PyType_FromSpec(&Engine_spec);
  if (engine_type == nullptr || PyModule_AddObject(mod, "Engine", engine_type) < 0) {
    Py_XDECREF(engine_type);
    Py_DECREF(mod);
    return nullptr;
  }
  return mod;
}
