/* Fast Prometheus text-exposition renderer (CPython C extension).
 *
 * The exporter renders the full device-metric page once per poll cycle
 * (tpumon/exporter/collector.py SampleCache.publish). This module moves
 * the label-escaping / string-assembly / float-formatting hot loop to C;
 * tpumon/_native/__init__.py builds it on demand and falls back to the
 * prometheus_client renderer when no compiler is available, so the
 * extension is an optimization, never a dependency.
 *
 * Input (prepared by tpumon/_native/__init__.py from metric families):
 *   families: list of (name: str, help: str, typ: str, samples: list)
 *   sample:   (sample_name: str, label_keys: tuple[str, ...],
 *              label_values: tuple[str, ...], value: float)
 * The per-sample name supports histogram families, whose samples render
 * under <family>_bucket/_count/_sum rather than the family name.
 * Output: bytes in text format 0.0.4 (same grammar prometheus_client
 * emits; float formatting via PyOS_double_to_string repr mode so values
 * round-trip identically to the Python renderer).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

typedef struct {
    char *buf;
    Py_ssize_t len;
    Py_ssize_t cap;
} strbuf;

static int sb_reserve(strbuf *sb, Py_ssize_t extra) {
    if (sb->len + extra <= sb->cap) return 0;
    Py_ssize_t ncap = sb->cap ? sb->cap : 4096;
    while (ncap < sb->len + extra) ncap *= 2;
    char *nbuf = PyMem_Realloc(sb->buf, ncap);
    if (!nbuf) return -1;
    sb->buf = nbuf;
    sb->cap = ncap;
    return 0;
}

static int sb_put(strbuf *sb, const char *data, Py_ssize_t n) {
    if (sb_reserve(sb, n) < 0) return -1;
    memcpy(sb->buf + sb->len, data, n);
    sb->len += n;
    return 0;
}

static int sb_putc(strbuf *sb, char c) { return sb_put(sb, &c, 1); }

/* Escape for HELP text: backslash and newline. */
static int sb_put_escaped_help(strbuf *sb, const char *s, Py_ssize_t n) {
    for (Py_ssize_t i = 0; i < n; i++) {
        char c = s[i];
        if (c == '\\') { if (sb_put(sb, "\\\\", 2) < 0) return -1; }
        else if (c == '\n') { if (sb_put(sb, "\\n", 2) < 0) return -1; }
        else if (sb_putc(sb, c) < 0) return -1;
    }
    return 0;
}

/* Escape for label values: backslash, double-quote, newline. */
static int sb_put_escaped_label(strbuf *sb, const char *s, Py_ssize_t n) {
    for (Py_ssize_t i = 0; i < n; i++) {
        char c = s[i];
        if (c == '\\') { if (sb_put(sb, "\\\\", 2) < 0) return -1; }
        else if (c == '"') { if (sb_put(sb, "\\\"", 2) < 0) return -1; }
        else if (c == '\n') { if (sb_put(sb, "\\n", 2) < 0) return -1; }
        else if (sb_putc(sb, c) < 0) return -1;
    }
    return 0;
}

static int sb_put_pystr(strbuf *sb, PyObject *obj,
                        int (*putter)(strbuf *, const char *, Py_ssize_t)) {
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(obj, &n);
    if (!s) return -1;
    return putter(sb, s, n);
}

static int sb_put_raw_pystr(strbuf *sb, PyObject *obj) {
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(obj, &n);
    if (!s) return -1;
    return sb_put(sb, s, n);
}

static PyObject *render(PyObject *self, PyObject *families) {
    (void)self;
    if (!PyList_Check(families)) {
        PyErr_SetString(PyExc_TypeError, "families must be a list");
        return NULL;
    }
    strbuf sb = {NULL, 0, 0};

    Py_ssize_t nfam = PyList_GET_SIZE(families);
    for (Py_ssize_t f = 0; f < nfam; f++) {
        PyObject *fam = PyList_GET_ITEM(families, f);
        PyObject *name, *help, *typ, *samples;
        if (!PyArg_ParseTuple(fam, "OOOO", &name, &help, &typ, &samples))
            goto fail;

        if (sb_put(&sb, "# HELP ", 7) < 0) goto fail;
        if (sb_put_raw_pystr(&sb, name) < 0) goto fail;
        if (sb_putc(&sb, ' ') < 0) goto fail;
        if (sb_put_pystr(&sb, help, sb_put_escaped_help) < 0) goto fail;
        if (sb_put(&sb, "\n# TYPE ", 8) < 0) goto fail;
        if (sb_put_raw_pystr(&sb, name) < 0) goto fail;
        if (sb_putc(&sb, ' ') < 0) goto fail;
        if (sb_put_raw_pystr(&sb, typ) < 0) goto fail;
        if (sb_putc(&sb, '\n') < 0) goto fail;

        Py_ssize_t nsamp = PyList_GET_SIZE(samples);
        for (Py_ssize_t i = 0; i < nsamp; i++) {
            PyObject *samp = PyList_GET_ITEM(samples, i);
            PyObject *sname, *keys, *vals;
            double value;
            if (!PyArg_ParseTuple(samp, "OOOd", &sname, &keys, &vals, &value))
                goto fail;

            if (sb_put_raw_pystr(&sb, sname) < 0) goto fail;
            Py_ssize_t nlab = PyTuple_GET_SIZE(keys);
            if (nlab > 0) {
                if (sb_putc(&sb, '{') < 0) goto fail;
                for (Py_ssize_t k = 0; k < nlab; k++) {
                    if (k && sb_putc(&sb, ',') < 0) goto fail;
                    if (sb_put_raw_pystr(&sb, PyTuple_GET_ITEM(keys, k)) < 0)
                        goto fail;
                    if (sb_put(&sb, "=\"", 2) < 0) goto fail;
                    if (sb_put_pystr(&sb, PyTuple_GET_ITEM(vals, k),
                                     sb_put_escaped_label) < 0)
                        goto fail;
                    if (sb_putc(&sb, '"') < 0) goto fail;
                }
                if (sb_putc(&sb, '}') < 0) goto fail;
            }
            if (sb_putc(&sb, ' ') < 0) goto fail;

            /* Non-finite values use the canonical Prometheus spellings;
             * finite ones use repr-mode doubles (round-trip exact). */
            if (Py_IS_NAN(value)) {
                if (sb_put(&sb, "NaN", 3) < 0) goto fail;
            } else if (Py_IS_INFINITY(value)) {
                if (sb_put(&sb, value > 0 ? "+Inf" : "-Inf", 4) < 0) goto fail;
            } else {
                char *num = PyOS_double_to_string(value, 'r', 0,
                                                  Py_DTSF_ADD_DOT_0, NULL);
                if (!num) goto fail;
                int rc = sb_put(&sb, num, (Py_ssize_t)strlen(num));
                PyMem_Free(num);
                if (rc < 0) goto fail;
            }
            if (sb_putc(&sb, '\n') < 0) goto fail;
        }
    }

    PyObject *out = PyBytes_FromStringAndSize(sb.buf, sb.len);
    PyMem_Free(sb.buf);
    return out;

fail:
    PyMem_Free(sb.buf);
    if (!PyErr_Occurred())
        PyErr_SetString(PyExc_RuntimeError, "exposition render failed");
    return NULL;
}

/* Assemble a page from pre-rendered per-family byte segments (the
 * incremental-render fast path): one exact-size allocation + memcpy per
 * segment, no intermediate buffers. */
static PyObject *concat(PyObject *self, PyObject *segments) {
    (void)self;
    if (!PyList_Check(segments)) {
        PyErr_SetString(PyExc_TypeError, "segments must be a list");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(segments);
    Py_ssize_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *seg = PyList_GET_ITEM(segments, i);
        if (!PyBytes_Check(seg)) {
            PyErr_SetString(PyExc_TypeError, "segments must be bytes");
            return NULL;
        }
        total += PyBytes_GET_SIZE(seg);
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, total);
    if (!out) return NULL;
    char *dst = PyBytes_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *seg = PyList_GET_ITEM(segments, i);
        Py_ssize_t len = PyBytes_GET_SIZE(seg);
        memcpy(dst, PyBytes_AS_STRING(seg), len);
        dst += len;
    }
    return out;
}

/* Family-index probe for the cardinality governor (tpumon/guard): are
 * all samples in this family published under ONE sample name?  Mixed
 * names mean a histogram-shaped family (_bucket/_sum/_count rows) whose
 * cardinality is already bounded by its bucket ladder — the governor
 * must skip it.  At a 10k+ series budget the pure-Python set build this
 * replaces is the governor's entire per-cycle cost; here it is one
 * attribute fetch + one compare per sample, pointer-equality first
 * (producers reuse the same interned name object per family). */
static PyObject *uniform_names(PyObject *self, PyObject *samples) {
    (void)self;
    PyObject *fast = PySequence_Fast(samples, "samples must be a sequence");
    if (!fast) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    PyObject *first = NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        PyObject *name = PyObject_GetAttrString(item, "name");
        if (!name) {
            Py_DECREF(fast);
            Py_XDECREF(first);
            return NULL;
        }
        if (first == NULL) {
            first = name;
            continue;
        }
        if (name != first) {
            int eq = PyObject_RichCompareBool(name, first, Py_EQ);
            if (eq < 0) {
                Py_DECREF(name);
                Py_DECREF(first);
                Py_DECREF(fast);
                return NULL;
            }
            if (!eq) {
                Py_DECREF(name);
                Py_DECREF(first);
                Py_DECREF(fast);
                Py_RETURN_FALSE;
            }
        }
        Py_DECREF(name);
    }
    Py_XDECREF(first);
    Py_DECREF(fast);
    Py_RETURN_TRUE;
}

static PyMethodDef methods[] = {
    {"render", render, METH_O,
     "render(families) -> bytes — Prometheus text exposition 0.0.4"},
    {"concat", concat, METH_O,
     "concat(segments) -> bytes — join pre-rendered page segments"},
    {"uniform_names", uniform_names, METH_O,
     "uniform_names(samples) -> bool — one sample name across the family"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_exposition",
    "Native Prometheus text-exposition renderer", -1, methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__exposition(void) {
    return PyModule_Create(&moduledef);
}
