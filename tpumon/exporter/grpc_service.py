"""Optional gRPC metrics service hosted by the exporter (SURVEY.md §1 L4:
"optional gRPC streaming from the cloud-tpu monitoring service").

The DCGM-hostengine analogue serves its field cache over a native RPC
endpoint; tpumon's equivalent serves the SAME pre-rendered exposition the
HTTP scrape path uses, over two proto-free methods:

- ``/tpumon.v1.Metrics/Get``   (unary)            — current page
- ``/tpumon.v1.Metrics/Watch`` (server-streaming) — current page, then one
  message per poll cycle (1 Hz push: a gRPC consumer sees every poll,
  where a Prometheus pull sees one in 15-60 s)

Wire format: requests are empty messages; responses are a minimal
protobuf ``PageResponse { bytes page = 1; uint64 version = 2; }`` built
with the same hand varint codec as tpumon/backends/reflection.py (no
.proto files shipped or needed). The server also answers server
reflection, so ``grpcurl``-style discovery and the tpumon grpc backend's
``services()`` both see ``tpumon.v1.Metrics``.

Enabled with ``--grpc-serve-port`` / ``TPUMON_GRPC_SERVE_PORT``:
``-1`` (the default) disables the service, ``0`` binds an ephemeral port
(tests), any other value is the listening port.
"""

from __future__ import annotations

import logging

from tpumon.backends.reflection import (
    _encode_varint,
    _iter_fields,
    _len_field,
)

log = logging.getLogger(__name__)

SERVICE_NAME = "tpumon.v1.Metrics"
METHOD_GET = f"/{SERVICE_NAME}/Get"
METHOD_WATCH = f"/{SERVICE_NAME}/Watch"

#: Watch wakes up at least this often to notice a cancelled stream even
#: when the poller has stalled.
_WATCH_IDLE_TIMEOUT = 5.0

#: Concurrent Watch streams admitted. Each stream parks a worker thread
#: for its lifetime; capping below the pool size reserves workers so
#: Get/reflection stay responsive no matter how many watchers connect.
_MAX_WATCHERS = 12
_POOL_WORKERS = 16

#: Default per-client Watch-stream cap (tpumon/guard): one misbehaving
#: consumer reconnect-looping Watch must not consume the global watcher
#: budget. Overridden by the guard's watch_per_client when wired.
_DEFAULT_WATCH_PER_CLIENT = 4

#: Transport-level self-protection (tpumon/guard): requests on this
#: service are EMPTY messages, so anything big is abuse — bound it at
#: the transport; plus server-side keepalive and idle-connection
#: eviction so half-dead clients can't hold HTTP/2 connections forever.
_SERVER_OPTIONS = (
    ("grpc.so_reuseport", 0),
    ("grpc.max_receive_message_length", 1 << 16),
    ("grpc.keepalive_time_ms", 30000),
    ("grpc.keepalive_timeout_ms", 10000),
    ("grpc.http2.max_pings_without_data", 2),
    ("grpc.max_connection_idle_ms", 300000),
)


def encode_page_response(
    page: bytes, version: int, epoch: int | None = None,
) -> bytes:
    """PageResponse{bytes page=1; uint64 version=2; uint64 epoch=3}.

    ``epoch`` (delta pushes only) is the server's delta-stream epoch, so
    a consumer can seed the HTTP conditional-GET base from a Watch frame
    and fail over watch→poll WITHOUT a resync; old clients skip the
    unknown field per protobuf rules."""
    out = _len_field(1, page) + _encode_varint((2 << 3) | 0) + _encode_varint(
        version
    )
    if epoch is not None:
        out += _encode_varint((3 << 3) | 0) + _encode_varint(epoch)
    return out


def decode_page_response(data: bytes) -> tuple[bytes, int]:
    """Inverse of encode_page_response (used by clients and tests)."""
    page, version, _epoch = decode_page_response_meta(data)
    return page, version


def decode_page_response_meta(data: bytes) -> tuple[bytes, int, int | None]:
    """(page, version, delta epoch|None) — the fleet fan-in decode."""
    page, version, epoch = b"", 0, None
    for field, wire, value in _iter_fields(data):
        if field == 1 and wire == 2:
            page = value
        elif field == 2 and wire == 0:
            version = value
        elif field == 3 and wire == 0:
            epoch = value
    return page, version, epoch


class MetricsGrpcServer:
    """Wraps a grpcio server with generic (bytes-level) handlers.

    ``render_with_version`` returns an atomic (full page, cache version)
    pair (cached device families + self-telemetry); ``cache`` provides
    wait_newer for the Watch push loop.
    """

    def __init__(
        self, render_with_version, cache, addr: str, port: int, tracer=None,
        guard=None, renderer=None,
    ) -> None:
        import threading

        import grpc
        from concurrent.futures import ThreadPoolExecutor
        from contextlib import nullcontext

        from tpumon.exporter.encodings import (
            FORMAT_DELTA,
            requested_format,
            requested_format_meta,
        )

        self._render_with_version = render_with_version
        self._cache = cache
        #: NegotiatedRenderer (tpumon/exporter/server.py): when wired,
        #: Get/Watch honor PageRequest.format and serve the same cached
        #: per-format payloads as HTTP negotiation — text requests
        #: included, so tpumon_exposition_requests_total counts gRPC
        #: traffic too. Without it (older embedders) every request
        #: serves text via the plain renderer, exactly as before.
        self._renderer = renderer

        def negotiated_page(request: bytes) -> tuple[bytes, int]:
            if self._renderer is None:
                return self._render_with_version()
            return self._renderer.page_with_version(requested_format(request))
        watcher_slots = threading.BoundedSemaphore(_MAX_WATCHERS)
        # Per-client stream accounting (tpumon/guard): `guard` supplies
        # the cap and the tpumon_shed_requests_total funnel; without it
        # the default cap still applies (sheds just aren't counted).
        per_client_cap = (
            guard.watch_per_client
            if guard is not None
            else _DEFAULT_WATCH_PER_CLIENT
        )
        client_streams: dict[str, int] = {}
        clients_lock = threading.Lock()

        def count_shed(reason: str) -> None:
            if guard is not None:
                guard.count_shed("grpc_watch", reason)

        def serve_span(name: str):
            # tpumon.trace serving spans: these run on gRPC worker
            # threads (no poll cycle open), so they feed only the
            # per-stage duration self-metric, never a cycle's span tree.
            if tracer is None:
                return nullcontext()
            return tracer.span(name, stage="grpc_serve")

        def get(request: bytes, context):
            with serve_span("grpc_get"):
                page, version = negotiated_page(request)
            return encode_page_response(page, version)

        def delta_watch(context, sub=False):
            """Delta-format push loop (ROADMAP item 3): the stream's
            first frame is ALWAYS the full snapshot (a reconnecting
            consumer lands on a consistent base by construction), each
            subsequent publish pushes the changed-segment patch against
            the seq this stream last sent, and every
            ``delta_resync_frames`` deltas a full resync frame rides the
            stream anyway — an undetected consumer bug diverges for at
            most one resync window. PageResponse.version carries the
            delta sequence number."""
            renderer = self._renderer
            last_seq = None
            deltas_since_full = 0
            version = 0
            while context.is_active():
                newer = cache.wait_newer(version, _WATCH_IDLE_TIMEOUT)
                if newer == version:
                    continue  # idle timeout: re-check liveness
                version = newer
                base = last_seq
                if (
                    base is not None
                    and deltas_since_full >= renderer.delta_resync_frames
                ):
                    base = None  # periodic full-snapshot resync
                with serve_span("grpc_watch_push"):
                    payload, seq, kind = renderer.delta_frame(base, sub=sub)
                deltas_since_full = (
                    deltas_since_full + 1 if kind == "delta" else 0
                )
                last_seq = seq
                yield encode_page_response(
                    payload, seq, epoch=renderer.delta.epoch
                )

        def watch(request: bytes, context):
            # Client address without the ephemeral port: the per-client
            # cap must see "the same consumer reconnecting", not a new
            # identity per TCP connection.
            peer = context.peer()
            client = peer.rsplit(":", 1)[0] if ":" in peer else peer
            with clients_lock:
                if (
                    per_client_cap > 0
                    and client_streams.get(client, 0) >= per_client_cap
                ):
                    count_shed("client_cap")
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"per-client watch limit ({per_client_cap}) reached",
                    )
                client_streams[client] = client_streams.get(client, 0) + 1
            try:
                if not watcher_slots.acquire(blocking=False):
                    count_shed("stream_cap")
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"watcher limit ({_MAX_WATCHERS}) reached",
                    )
                try:
                    fmt, sub = requested_format_meta(request)
                    if (
                        fmt == FORMAT_DELTA
                        and self._renderer is not None
                        # Honor TPUMON_EXPOSITION_FORMATS here too: a
                        # delta-disabled exporter must fall back to the
                        # negotiated page (text) on EVERY transport, or
                        # the knob silently stops applying to Watch.
                        and FORMAT_DELTA in self._renderer.formats
                    ):
                        yield from delta_watch(context, sub=sub)
                    else:
                        version = 0
                        while context.is_active():
                            newer = cache.wait_newer(
                                version, _WATCH_IDLE_TIMEOUT
                            )
                            if newer == version:
                                continue  # idle timeout: re-check liveness
                            with serve_span("grpc_watch_push"):
                                page, version = negotiated_page(request)
                            yield encode_page_response(page, version)
                finally:
                    watcher_slots.release()
            finally:
                with clients_lock:
                    n = client_streams.get(client, 1) - 1
                    if n <= 0:
                        client_streams.pop(client, None)
                    else:
                        client_streams[client] = n

        def reflect(request_iterator, context):
            # list_services is the only query we answer; everything else
            # gets an error_response (field 7) per the protocol.
            for req in request_iterator:
                is_list = any(
                    field == 7 for field, _, _ in _iter_fields(req)
                )
                if is_list:
                    services = b"".join(
                        _len_field(1, _len_field(1, name.encode()))
                        for name in (
                            SERVICE_NAME,
                            "grpc.reflection.v1alpha.ServerReflection",
                        )
                    )
                    yield _len_field(6, services)
                else:
                    # ErrorResponse { int32 error_code = 1; string
                    # error_message = 2 } — code 12 = UNIMPLEMENTED, so
                    # spec-conformant clients branch on the code instead
                    # of parsing the message text.
                    unimplemented = (
                        _encode_varint((1 << 3) | 0)
                        + _encode_varint(12)
                        + _len_field(2, b"only list_services")
                    )
                    yield _len_field(7, unimplemented)

        metrics_handler = grpc.method_handlers_generic_handler(
            SERVICE_NAME,
            {
                "Get": grpc.unary_unary_rpc_method_handler(
                    get, request_deserializer=None, response_serializer=None
                ),
                "Watch": grpc.unary_stream_rpc_method_handler(
                    watch, request_deserializer=None, response_serializer=None
                ),
            },
        )
        reflection_handler = grpc.method_handlers_generic_handler(
            "grpc.reflection.v1alpha.ServerReflection",
            {
                "ServerReflectionInfo": grpc.stream_stream_rpc_method_handler(
                    reflect,
                    request_deserializer=None,
                    response_serializer=None,
                )
            },
        )
        # Pool sized above the watcher cap so Get/reflection always have
        # free workers. so_reuseport=0: without it a second server binds
        # the SAME port successfully on Linux and the kernel round-robins
        # clients between processes — the bind-conflict check below would
        # never fire. The rest of _SERVER_OPTIONS is transport
        # self-protection: bounded request messages, keepalive, and
        # idle-connection eviction (tpumon/guard).
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=_POOL_WORKERS),
            options=_SERVER_OPTIONS,
        )
        self._server.add_generic_rpc_handlers(
            (metrics_handler, reflection_handler)
        )
        self.port = self._server.add_insecure_port(f"{addr}:{port}")
        if self.port == 0:
            # grpc reports bind failure by returning port 0, not raising.
            self._server.stop(grace=None)
            raise RuntimeError(f"could not bind grpc metrics service to {addr}:{port}")
        self._server.start()
        log.info("grpc metrics service on %s:%d (%s)", addr, self.port, SERVICE_NAME)

    def close(self) -> None:
        self._server.stop(grace=0.5)


def fetch_page(addr: str, timeout: float = 5.0) -> tuple[bytes, int]:
    """Client helper: one unary Get against a MetricsGrpcServer."""
    import grpc

    channel = grpc.insecure_channel(addr)
    try:
        call = channel.unary_unary(
            METHOD_GET, request_serializer=None, response_deserializer=None
        )
        return decode_page_response(call(b"", timeout=timeout))
    finally:
        channel.close()


def watch_pages(addr: str, max_messages: int, timeout: float = 30.0):
    """Client helper: collect up to ``max_messages`` Watch pushes."""
    import grpc

    channel = grpc.insecure_channel(addr)
    try:
        call = channel.unary_stream(
            METHOD_WATCH, request_serializer=None, response_deserializer=None
        )
        stream = call(b"", timeout=timeout)
        out = []
        try:
            for raw in stream:
                out.append(decode_page_response(raw))
                if len(out) >= max_messages:
                    break
        finally:
            stream.cancel()
        return out
    finally:
        channel.close()
