"""Cumulative Prometheus histograms fed by the 1 Hz poll loop.

BASELINE config 3 asks for "per-chip MXU duty-cycle + tensorcore_util
*histograms*" (BASELINE.json:8; SURVEY.md §1 L3 "gauges/histograms").
The gauges alone alias away everything between Prometheus scrapes: at a
15-60 s scrape interval, 14-59 of every 60 one-hertz samples are never
seen. These histograms close that gap inside the scrape itself — every
poll observes the current per-chip/per-core utilization into cumulative
buckets, so the *distribution* of the 1 Hz series is recoverable from
any scrape interval (`histogram_quantile` over `_bucket` rates), without
the non-Prometheus /history side channel.

State lives on the poller thread only (observe() is called from
build_families, families() from the same poll cycle); the rendered
output is published through the same atomic SampleCache as everything
else, so no extra locking is needed.
"""

from __future__ import annotations

from prometheus_client.core import HistogramMetricFamily
from prometheus_client.utils import floatToGoString

#: Utilization-percent buckets: fine at the idle end (is the chip doing
#: anything?) and the saturated end (is it pegged?), coarse in between.
PERCENT_BUCKETS: tuple[float, ...] = (
    1.0, 5.0, 10.0, 25.0, 50.0, 75.0, 90.0, 95.0, 99.0, float("inf"),
)

#: device metric source -> (histogram family, help, per-point label key).
#: The label key must match what tpumon.parsing emits for the source's
#: shape (PER_CHIP -> "chip", PER_CORE -> "core").
DISTRIBUTION_SOURCES: dict[str, tuple[str, str, str]] = {
    "duty_cycle_pct": (
        "accelerator_duty_cycle_distribution_percent",
        "Distribution of the 1 Hz per-chip duty-cycle samples since "
        "exporter start (cumulative buckets; recovers what the gauge "
        "aliases away between scrapes).",
        "chip",
    ),
    "tensorcore_util": (
        "accelerator_core_utilization_distribution_percent",
        "Distribution of the 1 Hz per-core TensorCore-utilization samples "
        "since exporter start (cumulative buckets).",
        "core",
    ),
}


class PollHistograms:
    """Cumulative per-series buckets for the distribution sources."""

    def __init__(self, buckets: tuple[float, ...] = PERCENT_BUCKETS) -> None:
        if buckets[-1] != float("inf"):
            buckets = buckets + (float("inf"),)
        self._buckets = buckets
        self._les = tuple(floatToGoString(b) for b in buckets)
        #: (source, label value) -> [per-bucket counts..., sum]
        self._state: dict[tuple[str, str], list[float]] = {}

    def observe(self, source: str, points) -> None:
        """Fold one poll cycle's parsed points into the buckets."""
        spec = DISTRIBUTION_SOURCES.get(source)
        if spec is None:
            return
        label_key = spec[2]
        for point in points:
            if point.value != point.value:
                # NaN (parsing's _to_float accepts "nan"): it would land
                # in no bucket but poison _sum for the exporter's
                # lifetime — drop it, same stance as any garbled row.
                continue
            series = (source, point.labels.get(label_key, ""))
            state = self._state.get(series)
            if state is None:
                state = [0.0] * (len(self._buckets) + 1)
                self._state[series] = state
            for idx, bound in enumerate(self._buckets):
                if point.value <= bound:
                    state[idx] += 1.0
                    break
            state[-1] += point.value

    def families(self, base_keys, base_vals) -> list:
        """Histogram families for everything observed so far."""
        out = []
        for source, (family, help_text, label_key) in DISTRIBUTION_SOURCES.items():
            series = sorted(
                (label, state)
                for (src, label), state in self._state.items()
                if src == source
            )
            if not series:
                continue
            fam = HistogramMetricFamily(
                family, help_text, labels=base_keys + (label_key,)
            )
            for label, state in series:
                cumulative = 0.0
                buckets = []
                for le, count in zip(self._les, state[:-1]):
                    cumulative += count
                    buckets.append((le, cumulative))
                fam.add_metric(base_vals + (label,), buckets, state[-1])
            out.append(fam)
        return out


__all__ = ["PollHistograms", "DISTRIBUTION_SOURCES", "PERCENT_BUCKETS"]
