"""DaemonSet container entrypoint (SURVEY.md §3.1).

``python -m tpumon.exporter.main`` (or the ``tpumon-exporter`` console
script): load config → pick backend → prime cache → serve until SIGTERM.
"""

from __future__ import annotations

import logging
import signal
import threading
import sys

from tpumon.config import Config
from tpumon.exporter.server import build_exporter

log = logging.getLogger(__name__)

#: Level names main() accepts (the logging module's public set).
_LOG_LEVELS = ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL")


def _resolve_log_level(name: str) -> tuple[int, str | None]:
    """Level name → numeric level, plus a warning message when the name
    is invalid (returned rather than logged, because logging isn't
    configured yet when this runs — the caller logs it right after
    ``basicConfig``, once, instead of silently serving at INFO)."""
    level = getattr(logging, name.upper(), None)
    if isinstance(level, int):
        return level, None
    return logging.INFO, (
        f"invalid TPUMON_LOG_LEVEL {name!r}; accepted: "
        f"{', '.join(_LOG_LEVELS)} — falling back to INFO"
    )


def _configure_logging(cfg: Config) -> None:
    level, level_warning = _resolve_log_level(cfg.log_level)
    if cfg.log_format.strip().lower() == "json":
        # Structured line-per-record JSON, trace-id correlated
        # (tpumon/trace/logfmt.py) — opt-in via TPUMON_LOG_FORMAT=json.
        from tpumon.trace import JsonLogFormatter

        handler = logging.StreamHandler()
        handler.setFormatter(JsonLogFormatter())
        logging.basicConfig(level=level, handlers=[handler])
    else:
        logging.basicConfig(
            level=level,
            format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        )
    if level_warning is not None:
        log.warning("%s", level_warning)


def main(argv: list[str] | None = None) -> int:
    cfg = Config.load(argv)
    _configure_logging(cfg)

    # Scrape-tail control, daemon-only (embedders keep their own setting):
    # the poll cycle holds the GIL in ~ms chunks each second, and CPython's
    # default 5 ms switch interval lets it stall a scrape thread the full
    # 5 ms (measured in bench.py). Opt out with TPUMON_KEEP_SWITCH_INTERVAL.
    import os
    import sys as _sys

    if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
        _sys.setswitchinterval(min(_sys.getswitchinterval(), 0.001))

    exporter = build_exporter(cfg)
    stop = threading.Event()

    def _signal(signum, frame) -> None:
        log.info("received signal %s, shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _signal)
    signal.signal(signal.SIGINT, _signal)

    exporter.start()
    try:
        stop.wait()  # deadline: woken by the SIGTERM/SIGINT handler — lifecycle wait, not a request path
    finally:
        exporter.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
