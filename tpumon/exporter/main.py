"""DaemonSet container entrypoint (SURVEY.md §3.1).

``python -m tpumon.exporter.main`` (or the ``tpumon-exporter`` console
script): load config → pick backend → prime cache → serve until SIGTERM.
"""

from __future__ import annotations

import logging
import signal
import threading
import sys

from tpumon.config import Config
from tpumon.exporter.server import build_exporter

log = logging.getLogger(__name__)


def main(argv: list[str] | None = None) -> int:
    cfg = Config.load(argv)
    logging.basicConfig(
        level=getattr(logging, cfg.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )

    # Scrape-tail control, daemon-only (embedders keep their own setting):
    # the poll cycle holds the GIL in ~ms chunks each second, and CPython's
    # default 5 ms switch interval lets it stall a scrape thread the full
    # 5 ms (measured in bench.py). Opt out with TPUMON_KEEP_SWITCH_INTERVAL.
    import os
    import sys as _sys

    if not os.environ.get("TPUMON_KEEP_SWITCH_INTERVAL"):
        _sys.setswitchinterval(min(_sys.getswitchinterval(), 0.001))

    exporter = build_exporter(cfg)
    stop = threading.Event()

    def _signal(signum, frame) -> None:
        log.info("received signal %s, shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _signal)
    signal.signal(signal.SIGINT, _signal)

    exporter.start()
    try:
        stop.wait()
    finally:
        exporter.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
