"""Host-side telemetry families (accelerator-diagnosis context).

Per the host-side-telemetry literature on diagnosing accelerator
performance from the host (CPU steal starving the input pipeline, memory
pressure evicting the page cache, NIC saturation delaying DCN
transfers), the DaemonSet exports a small set of host gauges next to the
device families. This is deliberately NOT a node-exporter replacement —
just the handful of signals that explain accelerator symptoms, carrying
the same base identity labels so one PromQL join correlates them with
per-chip metrics.

psutil-backed; when psutil is missing every family is absent (the usual
absent-not-zero stance), and the exporter keeps running.
"""

from __future__ import annotations

import logging

from prometheus_client.core import CounterMetricFamily, GaugeMetricFamily

log = logging.getLogger(__name__)

#: Process-wide first-call marker for psutil.cpu_percent priming (dict so
#: tests can reset it without poking a module global rebinding).
_cpu_primed: dict[str, bool] = {}

#: family -> (kind, description, extra labels)
HOST_FAMILIES: dict[str, tuple[str, str, tuple[str, ...]]] = {
    "host_cpu_percent": (
        "gauge",
        "Host CPU utilization percent (all cores averaged) — input-pipeline "
        "starvation context for accelerator duty dips",
        (),
    ),
    "host_memory_used_bytes": (
        "gauge",
        "Host memory in use, bytes",
        (),
    ),
    "host_memory_total_bytes": (
        "gauge",
        "Host memory total, bytes",
        (),
    ),
    "host_load1": (
        "gauge",
        "1-minute load average",
        (),
    ),
    "host_network_bytes_total": (
        "counter",
        "Host network bytes since boot by direction, summed over ALL "
        "interfaces incl. lo/veth (psutil) — DCN saturation context; "
        "tpu_hostcorr_net_bytes_per_second is the physical-NIC-only "
        "rate, so the two deliberately disagree on pod-dense nodes",
        ("dir",),
    ),
}


def host_families(base_keys: tuple[str, ...], base_vals: tuple[str, ...]):
    """Build the host gauge/counter families; [] when psutil is missing."""
    try:
        import psutil
    except Exception as exc:  # pragma: no cover - psutil is installed here
        log.debug("host metrics disabled: psutil unavailable (%s)", exc)
        return []

    out = []
    try:
        # interval=None is a non-blocking delta since the *previous* call,
        # so the first call in a process has no interval and psutil
        # documents its return as meaningless (it reports 0.0). Prime on
        # the first cycle and leave the family absent (absent ≠ zero)
        # rather than publishing a fake idle sample.
        cpu_pct = psutil.cpu_percent(interval=None)
        primed = _cpu_primed.get("done", False)
        _cpu_primed["done"] = True
        if primed:
            cpu = GaugeMetricFamily(
                "host_cpu_percent",
                HOST_FAMILIES["host_cpu_percent"][1],
                labels=base_keys,
            )
            cpu.add_metric(base_vals, cpu_pct)
            out.append(cpu)

        vm = psutil.virtual_memory()
        used = GaugeMetricFamily(
            "host_memory_used_bytes",
            HOST_FAMILIES["host_memory_used_bytes"][1],
            labels=base_keys,
        )
        used.add_metric(base_vals, float(vm.total - vm.available))
        out.append(used)
        total = GaugeMetricFamily(
            "host_memory_total_bytes",
            HOST_FAMILIES["host_memory_total_bytes"][1],
            labels=base_keys,
        )
        total.add_metric(base_vals, float(vm.total))
        out.append(total)

        load1 = GaugeMetricFamily(
            "host_load1", HOST_FAMILIES["host_load1"][1], labels=base_keys
        )
        load1.add_metric(base_vals, float(psutil.getloadavg()[0]))
        out.append(load1)

        nio = psutil.net_io_counters()
        net = CounterMetricFamily(
            "host_network_bytes",
            HOST_FAMILIES["host_network_bytes_total"][1],
            labels=base_keys + ("dir",),
        )
        net.add_metric(base_vals + ("tx",), float(nio.bytes_sent))
        net.add_metric(base_vals + ("rx",), float(nio.bytes_recv))
        out.append(net)
    except Exception as exc:
        # Any psutil hiccup degrades to fewer families, never a dead poll.
        log.debug("host telemetry partial failure: %s", exc)
    return out
