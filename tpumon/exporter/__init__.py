from tpumon.exporter.collector import CachedCollector, Poller, SampleCache, build_families
from tpumon.exporter.server import ExporterServer, build_exporter

__all__ = [
    "CachedCollector",
    "Poller",
    "SampleCache",
    "build_families",
    "ExporterServer",
    "build_exporter",
]
