"""Negotiated exposition formats + per-encoding response caches.

The scrape path serves one logical document — the node's metric page —
in whichever representation the consumer is cheapest to feed
(ROADMAP item 2; PAPER.md §exposition):

- **text** — Prometheus text 0.0.4, the default and the only format old
  exporters speak. Served from the pre-rendered SampleCache bytes.
- **openmetrics** — OpenMetrics 1.0 text for scrapers that negotiate it
  (``Accept: application/openmetrics-text``). Rendered lazily from the
  cached family snapshot, at most once per cache version.
- **snapshot** — a compact length-prefixed binary snapshot of the
  fleet-relevant fields (the ``node_snapshot_from_text`` structure),
  requested first by the fleet tier's NodeFeed so fan-in is a direct
  decode instead of a 0.37 ms/page text parse. Old exporters ignore the
  Accept header and serve text; the magic prefix makes the two
  indistinguishable to mix up.
- **delta** — a sequence-numbered PATCH against a previous snapshot
  (ROADMAP item 3: fleet fan-in cost proportional to change rate, not
  fleet size). A delta frame carries only the snapshot's top-level
  segments that changed since the consumer's acknowledged sequence —
  the wire form of the delta renderer's invalidation set. Consumers
  that hold no base (new/reconnecting), name a base the server no
  longer has, or observe a sequence gap get a FULL snapshot frame (a
  resync) instead; drift is impossible by construction because a delta
  only ever applies to the exact base it names.

Every format is cached per (format, content-encoding) keyed on the page
version pair, so an unchanged page costs zero encode work no matter how
many scrapers ask (:class:`EncodedPageCache`): the dcgm-exporter genre
re-serializes and re-compresses the world per scrape; tpumon pays once
per change.
"""

from __future__ import annotations

import gzip
import json
import logging
import threading

from tpumon.backends.reflection import (
    _decode_varint,
    _encode_varint,
    _iter_fields,
)

log = logging.getLogger(__name__)

#: Format names accepted by TPUMON_EXPOSITION_FORMATS (CSV).
FORMAT_TEXT = "text"
FORMAT_OPENMETRICS = "openmetrics"
FORMAT_SNAPSHOT = "snapshot"
FORMAT_DELTA = "delta"
KNOWN_FORMATS = (
    FORMAT_TEXT, FORMAT_OPENMETRICS, FORMAT_SNAPSHOT, FORMAT_DELTA,
)

#: Content types, response side. Text matches prometheus_client.
TEXT_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)
SNAPSHOT_CONTENT_TYPE = "application/vnd.tpumon.snapshot"
DELTA_CONTENT_TYPE = "application/vnd.tpumon.delta"

CONTENT_TYPES = {
    FORMAT_TEXT: TEXT_CONTENT_TYPE,
    FORMAT_OPENMETRICS: OPENMETRICS_CONTENT_TYPE,
    FORMAT_SNAPSHOT: SNAPSHOT_CONTENT_TYPE,
    FORMAT_DELTA: DELTA_CONTENT_TYPE,
}

#: Wire prefix of the snapshot encoding: magic + format version byte.
#: A text exposition page can never start with these bytes, so a client
#: that asked for a snapshot detects an old text-only exporter from the
#: payload itself (transport-agnostic: HTTP body or gRPC page field).
SNAPSHOT_MAGIC = b"TPMN\x01"

#: Wire prefix of a delta frame (same magic + length-prefix envelope as
#: the snapshot encoding, distinct magic). A delta consumer therefore
#: distinguishes "patch" from "resync" from "old text-only exporter" by
#: the first bytes of every payload, on every transport.
DELTA_MAGIC = b"TPMD\x01"

#: Response/request header pair for the conditional-GET (HTTP polling)
#: form of the delta protocol: the server stamps every snapshot/delta
#: response with its stream epoch and sequence; a poller echoes them
#: back to name its base. gRPC Watch needs neither — the stream itself
#: scopes the sequence (PageResponse.version) and a reconnect always
#: starts from a full frame.
DELTA_SEQ_HEADER = "X-Tpumon-Delta-Seq"
DELTA_BASE_HEADER = "X-Tpumon-Delta-Base"


def parse_formats(raw: tuple[str, ...]) -> tuple[str, ...]:
    """Validate a TPUMON_EXPOSITION_FORMATS tuple: unknown names are
    dropped WITH a warning (malformed env must not take the scrape
    plane down, but a typo silently disabling an encoding would only
    surface as the fleet tier quietly falling back to the slow text
    parse), and text is always present — it is the compatibility floor
    every consumer (Prometheus, curl, old fleet shards) can parse.
    Names are case-insensitive, like every other env knob."""
    raw = tuple(f.strip().lower() for f in raw)
    unknown = tuple(f for f in raw if f not in KNOWN_FORMATS)
    if unknown:
        log.warning(
            "ignoring unknown exposition format(s) %s; accepted: %s",
            ", ".join(unknown), ", ".join(KNOWN_FORMATS),
        )
    formats = tuple(f for f in raw if f in KNOWN_FORMATS)
    if FORMAT_TEXT not in formats:
        formats = (FORMAT_TEXT, *formats)
    return formats


def negotiate(accept: str, formats: tuple[str, ...]) -> str:
    """Pick the exposition format for an Accept header value.

    Semantics (deliberately small — this is an exporter, not a general
    content server):

    - each *enabled* format scores the best q among Accept entries whose
      media type names it exactly (``application/vnd.tpumon.snapshot``,
      ``application/openmetrics-text``, ``text/plain``);
    - ``text/*`` and ``*/*`` score for **text only** — a wildcard client
      (curl, a browser) must get the default format, never a binary
      payload;
    - highest q wins; ties break toward the more specific ask
      (delta > snapshot > openmetrics > text), which only matters when a
      client explicitly lists two formats at equal q;
    - no Accept header, or nothing matching: text.
    """
    if not accept:
        return FORMAT_TEXT
    scores = dict.fromkeys(formats, 0.0)
    for entry in accept.split(","):
        parts = entry.split(";")
        media = parts[0].strip().lower()
        q = 1.0
        for param in parts[1:]:
            key, _, value = param.partition("=")
            if key.strip().lower() == "q":
                try:
                    q = float(value.strip())
                except ValueError:
                    q = 0.0
        target = None
        if media == DELTA_CONTENT_TYPE:
            target = FORMAT_DELTA
        elif media == SNAPSHOT_CONTENT_TYPE:
            target = FORMAT_SNAPSHOT
        elif media == "application/openmetrics-text":
            target = FORMAT_OPENMETRICS
        elif media in ("text/plain", "text/*", "*/*"):
            target = FORMAT_TEXT
        if target in scores:
            scores[target] = max(scores[target], q)
    best_q = max(scores.values())
    if best_q <= 0.0:
        return FORMAT_TEXT
    for fmt in (FORMAT_DELTA, FORMAT_SNAPSHOT, FORMAT_OPENMETRICS, FORMAT_TEXT):
        if scores.get(fmt, 0.0) == best_q:
            return fmt
    return FORMAT_TEXT


# -- compact snapshot codec -------------------------------------------------

def encode_snapshot(snap: dict) -> bytes:
    """Snapshot dict -> magic + varint payload length + compact JSON.

    The payload is canonical (sorted keys, tight separators) so equal
    snapshots encode to equal bytes — the per-version response cache
    and the equivalence tests both lean on that. Non-finite floats ride
    Python's NaN/Infinity tokens: this codec owns both ends, and
    mapping them to null would break decode==parse equivalence for
    pages that legitimately carry NaN samples.
    """
    payload = json.dumps(
        snap, sort_keys=True, separators=(",", ":")
    ).encode()
    return SNAPSHOT_MAGIC + _encode_varint(len(payload)) + payload


def is_snapshot(data: bytes) -> bool:
    return data.startswith(SNAPSHOT_MAGIC)


def decode_snapshot(data: bytes, max_bytes: int | None = None) -> dict:
    """Inverse of :func:`encode_snapshot`; raises ValueError on a frame
    that is not a well-formed snapshot (callers fall back to the text
    parser).

    ``max_bytes`` caps the DECLARED payload length, checked before any
    payload-sized work: a hostile length prefix (varints happily encode
    2**60) must be rejected up front, not discovered as an allocation —
    the fleet tier passes TPUMON_FLEET_MAX_SNAPSHOT_BYTES here.
    """
    if not is_snapshot(data):
        raise ValueError("not a tpumon snapshot frame")
    body = data[len(SNAPSHOT_MAGIC):]
    length, idx = _decode_varint(body, 0)
    if length < 0 or (max_bytes is not None and length > max_bytes):
        raise ValueError(
            f"snapshot length prefix {length} exceeds cap {max_bytes}"
        )
    payload = body[idx:idx + length]
    if len(payload) != length:
        raise ValueError("truncated snapshot payload")
    doc = json.loads(payload.decode())
    if not isinstance(doc, dict):
        raise ValueError("snapshot payload is not an object")
    return doc


# -- delta frame codec ------------------------------------------------------

#: Snapshot segments that are per-key-diffable maps: a capable consumer
#: can take a SUB-delta (changed inner keys only) instead of the whole
#: segment. ``chips`` is the one that matters — it is the largest
#: segment on the page, and the common steady-state frame is ONE chip's
#: gauge jittering, which used to re-ship every chip's row.
SUB_DELTA_SEGMENTS = ("chips",)


def snapshot_delta(prev: dict, cur: dict) -> tuple[dict, list]:
    """(changed segments, dropped keys) between two node snapshots.

    Segments are the snapshot's TOP-LEVEL keys — exactly the granularity
    the fleet rollup consumes them at (identity, chips, ici, straggler,
    energy, ...), and the dict-equality comparison per key is a C loop.
    A key present in both with equal value ships nothing; an idle node's
    delta is therefore just ``last_poll_ts`` — the heartbeat."""
    changed = {
        key: value
        for key, value in cur.items()
        if key not in prev or prev[key] != value
    }
    dropped = [key for key in prev if key not in cur]
    return changed, dropped


def snapshot_delta_sub(prev: dict, cur: dict) -> tuple[dict, list, dict]:
    """Like :func:`snapshot_delta`, but SUB_DELTA_SEGMENTS whose value
    changed ship as per-inner-key patches: ``(changed, dropped, subs)``
    with ``subs = {segment: {"set": {inner: value}, "drop": [inner]}}``.

    Sub frames are served ONLY to consumers that advertised the
    capability (Accept ``;sub=1`` / PageRequest.sub) — a PR 12-era
    ``apply_delta`` would silently ignore the ``sub`` key and drift,
    which is exactly the failure class the delta protocol exists to
    make impossible, so capability travels with the ask, never assumed.
    """
    changed, dropped = snapshot_delta(prev, cur)
    subs: dict = {}
    for segment in SUB_DELTA_SEGMENTS:
        value = changed.get(segment)
        prev_value = prev.get(segment)
        if (
            isinstance(value, dict)
            and isinstance(prev_value, dict)
            and prev_value
        ):
            subs[segment] = {
                "set": {
                    k: v
                    for k, v in value.items()
                    if k not in prev_value or prev_value[k] != v
                },
                "drop": [k for k in prev_value if k not in value],
            }
            del changed[segment]
    return changed, dropped, subs


def encode_delta(
    seq: int, base: int, changed: dict, dropped: list,
    subs: dict | None = None,
) -> bytes:
    """Delta frame: DELTA_MAGIC + varint payload length + canonical JSON
    ``{"seq", "base", "set", "drop"[, "sub"]}``. Same envelope
    discipline as :func:`encode_snapshot` (sorted keys, tight
    separators, NaN tokens allowed) so equal deltas encode to equal
    bytes and the per-(base, seq) frame cache can share one encode
    across every consumer. ``sub`` (sub-segment patches) is emitted
    only when non-empty, so frames without it are byte-identical to the
    PR 12 wire format."""
    doc: dict = {"seq": seq, "base": base, "set": changed, "drop": dropped}
    if subs:
        doc["sub"] = subs
    payload = json.dumps(
        doc, sort_keys=True, separators=(",", ":"),
    ).encode()
    return DELTA_MAGIC + _encode_varint(len(payload)) + payload


def is_delta(data: bytes) -> bool:
    return data.startswith(DELTA_MAGIC)


def decode_delta(data: bytes, max_bytes: int | None = None) -> dict:
    """Inverse of :func:`encode_delta`; raises ValueError on anything
    that is not a well-formed delta frame.

    Mirrors :func:`decode_snapshot`'s hostile-input stance: ``max_bytes``
    caps the DECLARED payload length before any payload-sized work (a
    length prefix claiming terabytes is rejected pre-allocation), and
    the decoded shape is type-checked — ``seq``/``base`` must be ints,
    ``set`` an object, ``drop`` a list of strings — so a corrupt feed
    can never smuggle a non-mergeable patch into per-feed state."""
    if not is_delta(data):
        raise ValueError("not a tpumon delta frame")
    body = data[len(DELTA_MAGIC):]
    length, idx = _decode_varint(body, 0)
    if length < 0 or (max_bytes is not None and length > max_bytes):
        raise ValueError(
            f"delta length prefix {length} exceeds cap {max_bytes}"
        )
    payload = body[idx:idx + length]
    if len(payload) != length:
        raise ValueError("truncated delta payload")
    doc = json.loads(payload.decode())
    if not isinstance(doc, dict):
        raise ValueError("delta payload is not an object")
    if not isinstance(doc.get("seq"), int) or not isinstance(
        doc.get("base"), int
    ):
        raise ValueError("delta frame missing integer seq/base")
    if not isinstance(doc.get("set"), dict):
        raise ValueError("delta set is not an object")
    drop = doc.get("drop", [])
    if not isinstance(drop, list) or not all(
        isinstance(key, str) for key in drop
    ):
        raise ValueError("delta drop is not a list of keys")
    subs = doc.get("sub")
    if subs is not None:
        if not isinstance(subs, dict):
            raise ValueError("delta sub is not an object")
        for segment, patch in subs.items():
            if (
                not isinstance(segment, str)
                or not isinstance(patch, dict)
                or not isinstance(patch.get("set"), dict)
                or not isinstance(patch.get("drop", []), list)
                or not all(
                    isinstance(k, str) for k in patch.get("drop", [])
                )
            ):
                raise ValueError("delta sub patch has wrong shape")
    return doc


def apply_delta(state: dict, delta: dict) -> dict:
    """New snapshot = ``state`` patched by one decoded delta frame.

    Returns a NEW dict (the previous snapshot object may still be
    serving readers — the fleet collect loop holds references without
    locks, so in-place mutation would tear a rollup mid-cycle).
    Sub-segment patches build a NEW inner dict for the same reason."""
    merged = dict(state)
    merged.update(delta["set"])
    for key in delta.get("drop", ()):
        merged.pop(key, None)
    for segment, patch in (delta.get("sub") or {}).items():
        inner = dict(merged.get(segment) or {})
        inner.update(patch["set"])
        for key in patch.get("drop", ()):
            inner.pop(key, None)
        merged[segment] = inner
    return merged


class DeltaHistory:
    """Bounded (seq → snapshot) history + encoded-frame cache: the
    server half of the delta protocol, shared by HTTP conditional GETs
    and every gRPC Watch stream.

    - ``record(key, snap)`` assigns the next sequence number to a new
      page-version key (idempotent per key: all transports observe the
      same seq for the same page), retaining the last ``depth`` snaps.
    - ``frame_from(base)`` returns ``(frame, seq, kind)``: a delta frame
      when ``base`` is retained and the encoded patch is actually
      smaller than a full resync, else the full snapshot frame. One
      encode per (base, seq) pair no matter how many consumers share
      that transition.
    - ``epoch`` scopes the sequence numbers to this process: a consumer
      that survived a server restart would otherwise eventually see its
      stale base number reassigned to unrelated content and apply a
      wrong-base patch — the silent-drift failure the protocol exists
      to make impossible.
    """

    def __init__(self, depth: int = 8) -> None:
        import os as _os

        self._lock = threading.Lock()
        self._depth = max(2, depth)
        #: seq -> snapshot dict, insertion-ordered (oldest first).
        self._snaps: dict[int, dict] = {}  # guarded-by: self._lock
        self._key: tuple | None = None  # guarded-by: self._lock
        self._seq = 0  # guarded-by: self._lock
        #: (base, seq, sub?) -> encoded frame; cleared as bases age out.
        #: The sub flag keys the cache because the same (base, seq)
        #: transition encodes differently for sub-capable consumers.
        self._frames: dict[tuple[int, int, bool], bytes] = {}  # guarded-by: self._lock
        self._full: bytes | None = None  # guarded-by: self._lock
        self.epoch = int.from_bytes(_os.urandom(4), "big")

    def record(self, key: tuple, snap: dict, full_frame: bytes) -> int:
        """Publish the snapshot for page-version ``key``; returns its
        sequence number. ``full_frame`` is the already-encoded snapshot
        frame (the resync payload) for this seq."""
        with self._lock:
            if key == self._key:
                return self._seq
            if self._key is not None and key < self._key:
                # A slow builder losing the race to a NEWER version must
                # not publish older content as the newest seq (version
                # pairs are monotonic and componentwise comparable —
                # the EncodedPageCache stance).
                return self._seq
            self._seq += 1
            self._key = key
            self._snaps[self._seq] = snap
            self._full = full_frame
            while len(self._snaps) > self._depth:
                oldest = next(iter(self._snaps))
                del self._snaps[oldest]
            self._frames = {
                pair: frame
                for pair, frame in self._frames.items()
                if pair[0] in self._snaps
            }
            return self._seq

    def frame_from(
        self, base: int | None, sub: bool = False
    ) -> tuple[bytes, int, str] | None:
        """(payload, seq, "delta"|"snapshot") against the CURRENT seq,
        or None when nothing was ever recorded. A base that is current
        returns an empty delta (heartbeat for transports that must send
        something); an unknown/pruned base returns the full frame.
        ``sub`` (consumer-advertised capability) shrinks map segments
        to per-inner-key patches — the one-chip-jitter frame ships one
        chip's row, not the whole chips map."""
        sub = bool(sub)
        with self._lock:
            seq = self._seq
            full = self._full
            if full is None:
                return None
            if base is None or base not in self._snaps:
                return full, seq, FORMAT_SNAPSHOT
            cached = self._frames.get((base, seq, sub))
            if cached is not None:
                return cached, seq, FORMAT_DELTA
            prev = self._snaps[base]
            cur = self._snaps[seq]
        # Encode OUTSIDE the lock (EncodedPageCache's builder stance): a
        # diff+encode must never block other consumers' cache hits. Two
        # racing consumers at the same (base, seq) produce identical
        # bytes; the second store is a harmless overwrite.
        if sub:
            changed, dropped, subs = snapshot_delta_sub(prev, cur)
            frame = encode_delta(seq, base, changed, dropped, subs)
        else:
            changed, dropped = snapshot_delta(prev, cur)
            frame = encode_delta(seq, base, changed, dropped)
        if len(frame) >= len(full):
            # The patch outgrew the resync (mass change): serve the full
            # frame — cheaper for the consumer AND self-limits delta
            # traffic to pages where deltas actually win.
            return full, seq, FORMAT_SNAPSHOT
        with self._lock:
            if base in self._snaps and seq == self._seq:
                self._frames[(base, seq, sub)] = frame
        return frame, seq, FORMAT_DELTA


# -- OpenMetrics rendering --------------------------------------------------

def openmetrics_render(families) -> bytes:
    """Render metric families as one OpenMetrics 1.0 document (with the
    ``# EOF`` terminator). Runs at most once per cache version — never
    on the per-scrape path."""
    from prometheus_client.openmetrics.exposition import generate_latest

    class _Shim:
        def collect(self):
            return families

    return generate_latest(_Shim())


def openmetrics_join(parts: list[bytes]) -> bytes:
    """Concatenate independently rendered OpenMetrics documents into one:
    every part's ``# EOF`` terminator except the last is dropped."""
    eof = b"# EOF\n"
    out: list[bytes] = []
    for i, part in enumerate(parts):
        if i < len(parts) - 1 and part.endswith(eof):
            part = part[: -len(eof)]
        out.append(part)
    return b"".join(out)


# -- per-encoding response cache --------------------------------------------

class EncodedPageCache:
    """Last-version response cache per (format, content-encoding).

    ``get(slot, key, build)`` returns the cached body when ``key`` (the
    page-version pair) still matches the slot, else calls ``build()``,
    stores, and returns. One entry per slot: scrapers all want the
    current page, so history is worthless. The builder runs OUTSIDE the
    lock — an encode must never block cache hits for other slots — at
    the cost of a redundant build when two scrapers race the same
    version transition (both results are identical bytes, and the race
    window is one encode).

    The ``observe(slot, hit)`` hook feeds the
    ``tpumon_render_encode_saves_total`` self-metric.
    """

    def __init__(self, observe=None) -> None:
        self._lock = threading.Lock()
        self._entries: dict[tuple, tuple] = {}  # guarded-by: self._lock
        self._observe = observe
        self.hits = 0  # guarded-by: self._lock
        self.misses = 0  # guarded-by: self._lock

    def get(self, slot: tuple, key: tuple, build):
        with self._lock:
            entry = self._entries.get(slot)
            if entry is not None and entry[0] == key:
                self.hits += 1
                body = entry[1]
            else:
                body = None
                self.misses += 1
        if body is not None:
            self._count(slot, True)
            return body
        body = build()
        with self._lock:
            # A slow builder that lost the race must not clobber an
            # entry a faster builder stored for a NEWER version
            # meanwhile (the slot would thrash, re-paying the encode per
            # scrape around every version transition): store when the
            # slot is untouched since our lookup, or when our key is not
            # older than the stored one (version pairs are monotonic and
            # componentwise comparable; every slot keeps one key shape).
            stored = self._entries.get(slot)
            if stored is entry or (stored is not None and key >= stored[0]):
                self._entries[slot] = (key, body)
        self._count(slot, False)
        return body

    def _count(self, slot: tuple, hit: bool) -> None:
        if self._observe is not None:
            try:
                self._observe(slot, hit)
            except Exception:
                # A metrics hook must never fail a scrape.
                log.debug("encode-cache observer failed", exc_info=True)

    def stats(self) -> tuple[int, int]:
        with self._lock:
            return self.hits, self.misses


def gzip_page(body: bytes) -> bytes:
    """Single-member gzip at level 1 — the one spelling of response
    compression (multi-member concatenation of separately compressed
    halves would silently truncate on one-shot zlib decoders)."""
    return gzip.compress(body, compresslevel=1)


def snapshot_request(fmt: str, sub: bool = False) -> bytes:
    """PageRequest{string format = 1; bool sub = 2} for the gRPC
    Get/Watch methods. ``sub`` advertises sub-segment delta capability;
    pre-PR 14 servers skip the unknown field per protobuf rules and
    serve whole-segment deltas — the capability degrades, never the
    stream."""
    data = fmt.encode()
    out = _encode_varint((1 << 3) | 2) + _encode_varint(len(data)) + data
    if sub:
        out += _encode_varint((2 << 3) | 0) + _encode_varint(1)
    return out


def requested_format(request: bytes) -> str:
    """Parse a PageRequest's format field; empty/garbage requests mean
    text (the pre-negotiation wire shape — old clients send b"")."""
    return requested_format_meta(request)[0]


def requested_format_meta(request: bytes) -> tuple[str, bool]:
    """(format, sub-delta capability) from a PageRequest. Old clients
    never set field 2, so sub defaults False — whole-segment frames."""
    if not request:
        return FORMAT_TEXT, False
    fmt = FORMAT_TEXT
    sub = False
    try:
        for field, wire, value in _iter_fields(request):
            if field == 1 and wire == 2:
                name = value.decode("utf-8", "replace")
                fmt = name if name in KNOWN_FORMATS else FORMAT_TEXT
            elif field == 2 and wire == 0:
                sub = bool(value)
    except Exception as exc:
        # A malformed request frame negotiates down to text, never errors.
        log.debug("unparseable page request (%s); serving text", exc)
        return FORMAT_TEXT, False
    return fmt, sub


def accept_delta_sub(accept: str) -> bool:
    """True when an Accept header's delta entry advertises the
    sub-segment capability (``application/vnd.tpumon.delta;sub=1``).
    Media-type parameters are exactly where HTTP puts capability hints;
    old servers' negotiate() ignores unknown parameters, so the ask is
    backward-inert."""
    for entry in accept.split(","):
        parts = entry.split(";")
        if parts[0].strip().lower() != DELTA_CONTENT_TYPE:
            continue
        for param in parts[1:]:
            key, _, value = param.partition("=")
            if key.strip().lower() == "sub" and value.strip() == "1":
                return True
    return False


__all__ = [
    "CONTENT_TYPES",
    "DELTA_BASE_HEADER",
    "DELTA_CONTENT_TYPE",
    "DELTA_MAGIC",
    "DELTA_SEQ_HEADER",
    "DeltaHistory",
    "EncodedPageCache",
    "SUB_DELTA_SEGMENTS",
    "accept_delta_sub",
    "FORMAT_DELTA",
    "FORMAT_OPENMETRICS",
    "FORMAT_SNAPSHOT",
    "FORMAT_TEXT",
    "KNOWN_FORMATS",
    "OPENMETRICS_CONTENT_TYPE",
    "SNAPSHOT_CONTENT_TYPE",
    "SNAPSHOT_MAGIC",
    "TEXT_CONTENT_TYPE",
    "apply_delta",
    "decode_delta",
    "decode_snapshot",
    "encode_delta",
    "encode_snapshot",
    "gzip_page",
    "is_delta",
    "is_snapshot",
    "negotiate",
    "openmetrics_join",
    "openmetrics_render",
    "parse_formats",
    "requested_format",
    "requested_format_meta",
    "snapshot_delta",
    "snapshot_delta_sub",
    "snapshot_request",
]
