"""Negotiated exposition formats + per-encoding response caches.

The scrape path serves one logical document — the node's metric page —
in whichever representation the consumer is cheapest to feed
(ROADMAP item 2; PAPER.md §exposition):

- **text** — Prometheus text 0.0.4, the default and the only format old
  exporters speak. Served from the pre-rendered SampleCache bytes.
- **openmetrics** — OpenMetrics 1.0 text for scrapers that negotiate it
  (``Accept: application/openmetrics-text``). Rendered lazily from the
  cached family snapshot, at most once per cache version.
- **snapshot** — a compact length-prefixed binary snapshot of the
  fleet-relevant fields (the ``node_snapshot_from_text`` structure),
  requested first by the fleet tier's NodeFeed so fan-in is a direct
  decode instead of a 0.37 ms/page text parse. Old exporters ignore the
  Accept header and serve text; the magic prefix makes the two
  indistinguishable to mix up.

Every format is cached per (format, content-encoding) keyed on the page
version pair, so an unchanged page costs zero encode work no matter how
many scrapers ask (:class:`EncodedPageCache`): the dcgm-exporter genre
re-serializes and re-compresses the world per scrape; tpumon pays once
per change.
"""

from __future__ import annotations

import gzip
import json
import logging
import threading

from tpumon.backends.reflection import (
    _decode_varint,
    _encode_varint,
    _iter_fields,
)

log = logging.getLogger(__name__)

#: Format names accepted by TPUMON_EXPOSITION_FORMATS (CSV).
FORMAT_TEXT = "text"
FORMAT_OPENMETRICS = "openmetrics"
FORMAT_SNAPSHOT = "snapshot"
KNOWN_FORMATS = (FORMAT_TEXT, FORMAT_OPENMETRICS, FORMAT_SNAPSHOT)

#: Content types, response side. Text matches prometheus_client.
TEXT_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)
SNAPSHOT_CONTENT_TYPE = "application/vnd.tpumon.snapshot"

CONTENT_TYPES = {
    FORMAT_TEXT: TEXT_CONTENT_TYPE,
    FORMAT_OPENMETRICS: OPENMETRICS_CONTENT_TYPE,
    FORMAT_SNAPSHOT: SNAPSHOT_CONTENT_TYPE,
}

#: Wire prefix of the snapshot encoding: magic + format version byte.
#: A text exposition page can never start with these bytes, so a client
#: that asked for a snapshot detects an old text-only exporter from the
#: payload itself (transport-agnostic: HTTP body or gRPC page field).
SNAPSHOT_MAGIC = b"TPMN\x01"


def parse_formats(raw: tuple[str, ...]) -> tuple[str, ...]:
    """Validate a TPUMON_EXPOSITION_FORMATS tuple: unknown names are
    dropped WITH a warning (malformed env must not take the scrape
    plane down, but a typo silently disabling an encoding would only
    surface as the fleet tier quietly falling back to the slow text
    parse), and text is always present — it is the compatibility floor
    every consumer (Prometheus, curl, old fleet shards) can parse.
    Names are case-insensitive, like every other env knob."""
    raw = tuple(f.strip().lower() for f in raw)
    unknown = tuple(f for f in raw if f not in KNOWN_FORMATS)
    if unknown:
        log.warning(
            "ignoring unknown exposition format(s) %s; accepted: %s",
            ", ".join(unknown), ", ".join(KNOWN_FORMATS),
        )
    formats = tuple(f for f in raw if f in KNOWN_FORMATS)
    if FORMAT_TEXT not in formats:
        formats = (FORMAT_TEXT, *formats)
    return formats


def negotiate(accept: str, formats: tuple[str, ...]) -> str:
    """Pick the exposition format for an Accept header value.

    Semantics (deliberately small — this is an exporter, not a general
    content server):

    - each *enabled* format scores the best q among Accept entries whose
      media type names it exactly (``application/vnd.tpumon.snapshot``,
      ``application/openmetrics-text``, ``text/plain``);
    - ``text/*`` and ``*/*`` score for **text only** — a wildcard client
      (curl, a browser) must get the default format, never a binary
      payload;
    - highest q wins; ties break toward the more specific ask
      (snapshot > openmetrics > text), which only matters when a client
      explicitly lists two formats at equal q;
    - no Accept header, or nothing matching: text.
    """
    if not accept:
        return FORMAT_TEXT
    scores = dict.fromkeys(formats, 0.0)
    for entry in accept.split(","):
        parts = entry.split(";")
        media = parts[0].strip().lower()
        q = 1.0
        for param in parts[1:]:
            key, _, value = param.partition("=")
            if key.strip().lower() == "q":
                try:
                    q = float(value.strip())
                except ValueError:
                    q = 0.0
        target = None
        if media == SNAPSHOT_CONTENT_TYPE:
            target = FORMAT_SNAPSHOT
        elif media == "application/openmetrics-text":
            target = FORMAT_OPENMETRICS
        elif media in ("text/plain", "text/*", "*/*"):
            target = FORMAT_TEXT
        if target in scores:
            scores[target] = max(scores[target], q)
    best_q = max(scores.values())
    if best_q <= 0.0:
        return FORMAT_TEXT
    for fmt in (FORMAT_SNAPSHOT, FORMAT_OPENMETRICS, FORMAT_TEXT):
        if scores.get(fmt, 0.0) == best_q:
            return fmt
    return FORMAT_TEXT


# -- compact snapshot codec -------------------------------------------------

def encode_snapshot(snap: dict) -> bytes:
    """Snapshot dict -> magic + varint payload length + compact JSON.

    The payload is canonical (sorted keys, tight separators) so equal
    snapshots encode to equal bytes — the per-version response cache
    and the equivalence tests both lean on that. Non-finite floats ride
    Python's NaN/Infinity tokens: this codec owns both ends, and
    mapping them to null would break decode==parse equivalence for
    pages that legitimately carry NaN samples.
    """
    payload = json.dumps(
        snap, sort_keys=True, separators=(",", ":")
    ).encode()
    return SNAPSHOT_MAGIC + _encode_varint(len(payload)) + payload


def is_snapshot(data: bytes) -> bool:
    return data.startswith(SNAPSHOT_MAGIC)


def decode_snapshot(data: bytes, max_bytes: int | None = None) -> dict:
    """Inverse of :func:`encode_snapshot`; raises ValueError on a frame
    that is not a well-formed snapshot (callers fall back to the text
    parser).

    ``max_bytes`` caps the DECLARED payload length, checked before any
    payload-sized work: a hostile length prefix (varints happily encode
    2**60) must be rejected up front, not discovered as an allocation —
    the fleet tier passes TPUMON_FLEET_MAX_SNAPSHOT_BYTES here.
    """
    if not is_snapshot(data):
        raise ValueError("not a tpumon snapshot frame")
    body = data[len(SNAPSHOT_MAGIC):]
    length, idx = _decode_varint(body, 0)
    if length < 0 or (max_bytes is not None and length > max_bytes):
        raise ValueError(
            f"snapshot length prefix {length} exceeds cap {max_bytes}"
        )
    payload = body[idx:idx + length]
    if len(payload) != length:
        raise ValueError("truncated snapshot payload")
    doc = json.loads(payload.decode())
    if not isinstance(doc, dict):
        raise ValueError("snapshot payload is not an object")
    return doc


# -- OpenMetrics rendering --------------------------------------------------

def openmetrics_render(families) -> bytes:
    """Render metric families as one OpenMetrics 1.0 document (with the
    ``# EOF`` terminator). Runs at most once per cache version — never
    on the per-scrape path."""
    from prometheus_client.openmetrics.exposition import generate_latest

    class _Shim:
        def collect(self):
            return families

    return generate_latest(_Shim())


def openmetrics_join(parts: list[bytes]) -> bytes:
    """Concatenate independently rendered OpenMetrics documents into one:
    every part's ``# EOF`` terminator except the last is dropped."""
    eof = b"# EOF\n"
    out: list[bytes] = []
    for i, part in enumerate(parts):
        if i < len(parts) - 1 and part.endswith(eof):
            part = part[: -len(eof)]
        out.append(part)
    return b"".join(out)


# -- per-encoding response cache --------------------------------------------

class EncodedPageCache:
    """Last-version response cache per (format, content-encoding).

    ``get(slot, key, build)`` returns the cached body when ``key`` (the
    page-version pair) still matches the slot, else calls ``build()``,
    stores, and returns. One entry per slot: scrapers all want the
    current page, so history is worthless. The builder runs OUTSIDE the
    lock — an encode must never block cache hits for other slots — at
    the cost of a redundant build when two scrapers race the same
    version transition (both results are identical bytes, and the race
    window is one encode).

    The ``observe(slot, hit)`` hook feeds the
    ``tpumon_render_encode_saves_total`` self-metric.
    """

    def __init__(self, observe=None) -> None:
        self._lock = threading.Lock()
        self._entries: dict[tuple, tuple] = {}  # guarded-by: self._lock
        self._observe = observe
        self.hits = 0  # guarded-by: self._lock
        self.misses = 0  # guarded-by: self._lock

    def get(self, slot: tuple, key: tuple, build):
        with self._lock:
            entry = self._entries.get(slot)
            if entry is not None and entry[0] == key:
                self.hits += 1
                body = entry[1]
            else:
                body = None
                self.misses += 1
        if body is not None:
            self._count(slot, True)
            return body
        body = build()
        with self._lock:
            # A slow builder that lost the race must not clobber an
            # entry a faster builder stored for a NEWER version
            # meanwhile (the slot would thrash, re-paying the encode per
            # scrape around every version transition): store when the
            # slot is untouched since our lookup, or when our key is not
            # older than the stored one (version pairs are monotonic and
            # componentwise comparable; every slot keeps one key shape).
            stored = self._entries.get(slot)
            if stored is entry or (stored is not None and key >= stored[0]):
                self._entries[slot] = (key, body)
        self._count(slot, False)
        return body

    def _count(self, slot: tuple, hit: bool) -> None:
        if self._observe is not None:
            try:
                self._observe(slot, hit)
            except Exception:
                # A metrics hook must never fail a scrape.
                log.debug("encode-cache observer failed", exc_info=True)

    def stats(self) -> tuple[int, int]:
        with self._lock:
            return self.hits, self.misses


def gzip_page(body: bytes) -> bytes:
    """Single-member gzip at level 1 — the one spelling of response
    compression (multi-member concatenation of separately compressed
    halves would silently truncate on one-shot zlib decoders)."""
    return gzip.compress(body, compresslevel=1)


def snapshot_request(fmt: str) -> bytes:
    """PageRequest{string format = 1} for the gRPC Get/Watch methods."""
    data = fmt.encode()
    return _encode_varint((1 << 3) | 2) + _encode_varint(len(data)) + data


def requested_format(request: bytes) -> str:
    """Parse a PageRequest's format field; empty/garbage requests mean
    text (the pre-negotiation wire shape — old clients send b"")."""
    if not request:
        return FORMAT_TEXT
    try:
        for field, wire, value in _iter_fields(request):
            if field == 1 and wire == 2:
                fmt = value.decode("utf-8", "replace")
                return fmt if fmt in KNOWN_FORMATS else FORMAT_TEXT
    except Exception as exc:
        # A malformed request frame negotiates down to text, never errors.
        log.debug("unparseable page request (%s); serving text", exc)
    return FORMAT_TEXT


__all__ = [
    "CONTENT_TYPES",
    "EncodedPageCache",
    "FORMAT_OPENMETRICS",
    "FORMAT_SNAPSHOT",
    "FORMAT_TEXT",
    "KNOWN_FORMATS",
    "OPENMETRICS_CONTENT_TYPE",
    "SNAPSHOT_CONTENT_TYPE",
    "SNAPSHOT_MAGIC",
    "TEXT_CONTENT_TYPE",
    "decode_snapshot",
    "encode_snapshot",
    "gzip_page",
    "is_snapshot",
    "negotiate",
    "openmetrics_join",
    "openmetrics_render",
    "parse_formats",
    "requested_format",
    "snapshot_request",
]
