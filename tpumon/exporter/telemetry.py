"""Exporter self-telemetry (SURVEY.md §5.1).

``exporter_scrape_duration_seconds`` is the BASELINE headline metric
(p99 scrape latency, BASELINE.json:2); buckets are sub-millisecond-heavy
because the scrape path only reads a cached snapshot (SURVEY.md §3.2) and
should land far under the 1 Hz poll budget.
"""

from __future__ import annotations

from prometheus_client import Counter, Gauge, Histogram
from prometheus_client.registry import CollectorRegistry

SCRAPE_BUCKETS = (
    0.0001,
    0.00025,
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
)

POLL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


class SelfTelemetry:
    """All exporter-about-itself metrics, bound to one registry."""

    def __init__(self, registry: CollectorRegistry) -> None:
        self.scrape_duration = Histogram(
            "exporter_scrape_duration_seconds",
            "Wall time to render one /metrics exposition (headline p99).",
            buckets=SCRAPE_BUCKETS,
            registry=registry,
        )
        self.poll_duration = Histogram(
            "exporter_poll_duration_seconds",
            "Wall time of one device poll cycle across all metric families.",
            buckets=POLL_BUCKETS,
            registry=registry,
        )
        self.trace_stage_duration = Histogram(
            "tpumon_trace_stage_duration_seconds",
            "Per-stage poll-pipeline span durations from the internal "
            "trace plane (tpumon/trace); stage=publish covers the "
            "exposition render, stage=backend_rpc the gRPC monitoring "
            "RPCs, stage=grpc_serve the exporter's own Get/Watch.",
            labelnames=("stage",),
            buckets=POLL_BUCKETS,
            registry=registry,
        )
        self.poll_stage_errors = Counter(
            "tpumon_poll_stage_errors",
            "Swallowed per-cycle stage failures (history record, anomaly "
            "pass): the cycle survives but that stage's output is "
            "missing — alertable instead of log-only.",
            labelnames=("stage",),
            registry=registry,
        )
        self.poll_errors = Counter(
            "collector_errors_total",
            "Device-query or parse failures, by kind; samples are dropped, "
            "the exporter never crashes on these (SURVEY.md §5.3).",
            labelnames=("kind",),
            registry=registry,
        )
        self.polls = Counter(
            "collector_polls_total",
            "Completed poll cycles.",
            registry=registry,
        )
        self.last_poll = Gauge(
            "collector_last_poll_timestamp_seconds",
            "Unix time of the last completed poll cycle (liveness signal).",
            registry=registry,
        )
        self.poll_lag = Gauge(
            "collector_poll_lag_seconds",
            "How far the last cycle overran the configured interval "
            "(0 when keeping up).",
            registry=registry,
        )
        self.coverage = Gauge(
            "exporter_metric_coverage_ratio",
            "Mapped fraction of the device library's supported metrics "
            "(BASELINE ≥0.95 target).",
            registry=registry,
        )
        # -- fault-tolerance plane (tpumon/resilience) -------------------
        self.up = Gauge(
            "tpumon_up",
            "1 while the poll loop completes cycles; 0 after a "
            "wholesale-failed cycle or a watchdog-detected hang (the "
            "next completed cycle restores 1).",
            registry=registry,
        )
        self.degraded = Gauge(
            "tpumon_degraded",
            "1 when the last cycle served anything other than "
            "fresh-complete data: stale-but-served families, an open "
            "circuit breaker, or a recovered enumeration outage "
            "(tpumon/resilience).",
            registry=registry,
        )
        self.family_staleness = Gauge(
            "tpumon_family_staleness_seconds",
            "Age of each family currently served from the last-good "
            "cache instead of a fresh device query; absent when the "
            "family is fresh.",
            labelnames=("family",),
            registry=registry,
        )
        self.breaker_state = Gauge(
            "tpumon_breaker_state",
            "Per-device-query circuit-breaker state: 0 closed, "
            "1 half-open (probing), 2 open (calls refused, last-good "
            "served).",
            labelnames=("query",),
            registry=registry,
        )
        self.retries = Counter(
            "tpumon_retries",
            "Transport-level device-call retries (bounded exponential "
            "backoff with jitter, tpumon/resilience/policy.py), by call.",
            labelnames=("call",),
            registry=registry,
        )
        self.watchdog_recoveries = Counter(
            "tpumon_watchdog_recoveries",
            "Stuck-poll-cycle recoveries: the watchdog detected a device "
            "call past the hang budget and tore the backend down "
            "(interrupt + channel re-init).",
            registry=registry,
        )
        # -- self-protection plane (tpumon/guard) ------------------------
        self.guard_state = Gauge(
            "tpumon_guard_state",
            "Self-protection memory state: 0 normal, 1 soft watermark "
            "(rings shrunk, slow-cycle capture off), 2 hard watermark "
            "(metrics-only serving; debug-class requests shed).",
            registry=registry,
        )
        self.guard_rss = Gauge(
            "tpumon_guard_rss_bytes",
            "Exporter process RSS as sampled by the memory watchdog "
            "each poll cycle (0 until the first sample or when no RSS "
            "source exists).",
            registry=registry,
        )
        self.shed_requests = Counter(
            "tpumon_shed_requests",
            "Requests refused by the ingress guard, by endpoint class "
            "and reason (concurrency, rate, memory, slowloris): the "
            "client got a cheap 503 + Retry-After instead of service.",
            labelnames=("endpoint", "reason"),
            registry=registry,
        )
        self.cardinality_dropped = Counter(
            "tpumon_cardinality_dropped_series",
            "Series collapsed into the sentinel `other` label value by "
            "the per-family cardinality budget "
            "(TPUMON_GUARD_MAX_SERIES_PER_FAMILY), by family.",
            labelnames=("family",),
            registry=registry,
        )
        # -- delta render + negotiated exposition ------------------------
        self.render_delta = Gauge(
            "tpumon_render_delta",
            "1 while the incremental (delta) page renderer is active: "
            "per-family cached byte segments, only changed families "
            "re-render each cycle (TPUMON_RENDER_DELTA).",
            registry=registry,
        )
        self.render_cache_hits = Counter(
            "tpumon_render_family_cache_hits",
            "Family byte segments served unchanged from the render "
            "cache across poll cycles (delta renderer; a re-rendered "
            "family is not a hit).",
            registry=registry,
        )
        self.render_invalidated = Gauge(
            "tpumon_render_invalidated_families",
            "Families re-rendered in the last poll cycle because their "
            "samples changed (or first appeared); page total minus this "
            "is the cycle's cache-hit count.",
            registry=registry,
        )
        self.render_encode_saves = Counter(
            "tpumon_render_encode_saves",
            "Scrape responses served straight from the per-encoding "
            "response cache (zero encode work), by exposition format "
            "and content encoding.",
            labelnames=("format", "encoding"),
            registry=registry,
        )
        self.exposition_requests = Counter(
            "tpumon_exposition_requests",
            "Negotiated /metrics (and gRPC Get/Watch) responses by "
            "exposition format (text / openmetrics / snapshot).",
            labelnames=("format",),
            registry=registry,
        )
        self.backend_info = Gauge(
            "exporter_backend_info",
            "Static info about the active device backend (value is 1).",
            labelnames=("backend", "version"),
            registry=registry,
        )
        # Pre-create both error kinds so the families exist from scrape #1.
        self.poll_errors.labels(kind="backend")
        self.poll_errors.labels(kind="parse")
        # Same for the trace-plane stages: the pipeline stages always run,
        # so their series must exist before the first traced cycle lands.
        for stage in ("build_families", "history_record", "anomaly", "publish"):
            self.trace_stage_duration.labels(stage=stage)
        self.poll_stage_errors.labels(stage="history_record")
        self.poll_stage_errors.labels(stage="anomaly")
        # Exposition formats: text always serves; pre-create the others
        # so "format never requested" is a scrapeable zero, not absence.
        for fmt in ("text", "openmetrics", "snapshot"):
            self.exposition_requests.labels(format=fmt)
            # Snapshot responses are never gzip-encoded (already compact).
            encodings = ("identity",) if fmt == "snapshot" else (
                "identity", "gzip",
            )
            for enc in encodings:
                self.render_encode_saves.labels(format=fmt, encoding=enc)
