"""HTTP scrape plane (SURVEY.md §1 L4, §3.2).

A threading WSGI server exposing ``/metrics`` (Prometheus text exposition)
plus ``/healthz`` (K8s liveness: fails when the poll loop stalls). Scrape
timing is measured by middleware around the exposition app and feeds the
``exporter_scrape_duration_seconds`` headline histogram.
"""

from __future__ import annotations

import gzip
import logging
import socket
import threading
import time
from socketserver import ThreadingMixIn
from wsgiref.simple_server import (
    ServerHandler,
    WSGIRequestHandler,
    WSGIServer,
    make_server,
)

from prometheus_client import exposition
from prometheus_client.registry import CollectorRegistry

from tpumon.backends.base import Backend
from tpumon.config import Config
from tpumon.exporter.collector import Poller, SampleCache
from tpumon.exporter.telemetry import SelfTelemetry

log = logging.getLogger(__name__)

#: /healthz fails if no poll completed within this many intervals.
HEALTH_STALE_INTERVALS = 5.0


class _Handler(WSGIRequestHandler):
    """HTTP/1.1 keep-alive so Prometheus reuses its scrape connection.

    Plain wsgiref serves ONE request per connection (its ``handle`` never
    loops) and stamps HTTP/1.0 status lines regardless of
    ``protocol_version`` — so this re-implements ``handle`` as the
    standard BaseHTTPRequestHandler loop and forces the handler's HTTP
    version. Every response carries an exact Content-Length (see
    ``_make_app``), which persistent connections require.
    """

    protocol_version = "HTTP/1.1"
    # Persistent connections + Nagle + delayed ACK = ~40 ms stalls on every
    # scrape after the first (measured: keep-alive p50 44 ms without this,
    # ~1 ms with). Prometheus reuses its scrape connection, so this is the
    # production path.
    disable_nagle_algorithm = True

    def handle(self) -> None:
        self.close_connection = True
        self.handle_one_request()
        while not self.close_connection:
            self.handle_one_request()

    def handle_one_request(self) -> None:
        self.raw_requestline = self.rfile.readline(65537)
        if len(self.raw_requestline) > 65536:
            self.requestline = ""
            self.request_version = ""
            self.command = ""
            self.send_error(414)
            self.close_connection = True
            return
        if not self.raw_requestline:
            self.close_connection = True
            return
        if not self.parse_request():  # sets close_connection itself
            return
        handler = ServerHandler(
            self.rfile,
            self.wfile,
            self.get_stderr(),
            self.get_environ(),
            multithread=True,
        )
        handler.http_version = "1.1"
        handler.request_handler = self
        handler.run(self.server.get_app())

    def log_message(self, *args) -> None:  # keep scrape noise out of logs
        pass


class _ThreadingWSGIServer(ThreadingMixIn, WSGIServer):
    daemon_threads = True
    allow_reuse_address = True
    address_family = socket.AF_INET


#: Prometheus text exposition format 0.0.4.
_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _finite(raw: str) -> float | None:
    """Shared query-param validation for /history and /anomalies: a
    finite, non-negative float, else None (the endpoints answer 400
    instead of silently coercing NaN/inf/negative time values)."""
    import math

    try:
        v = float(raw)
    except ValueError:
        return None
    return v if math.isfinite(v) and v >= 0 else None


def _json_dump(doc) -> bytes:
    """RFC-strict JSON body shared by /history and /anomalies: device
    anomalies can produce NaN samples, and json.dumps would happily emit
    the non-RFC `NaN` token that jq / JSON.parse reject. Map non-finite
    floats to null instead."""
    import json
    import math

    def clean(o):
        if isinstance(o, float) and not math.isfinite(o):
            return None
        if isinstance(o, dict):
            return {k: clean(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return [clean(v) for v in o]
        return o

    return json.dumps(
        clean(doc), sort_keys=True, allow_nan=False
    ).encode() + b"\n"


def _make_app(
    render_body, telemetry: SelfTelemetry, health, history=None,
    device_health=None, post_scrape=None, anomalies=None, tracer=None,
    debug_vars=None,
):
    """WSGI app. ``render_body(want_gzip: bool) -> bytes`` produces the
    /metrics payload (already gzip-encoded when asked); the exporter
    passes cached-bytes + self-telemetry concatenation, the sidecar a
    plain registry render. ``history`` (a tpumon.history.History) enables
    the /history JSON endpoint; ``device_health`` (a () -> dict callable)
    enables /health/devices (the dcgmi-health analogue); ``anomalies``
    (a tpumon.anomaly.AnomalyEngine) enables /anomalies; ``tracer``
    (a tpumon.trace.Tracer) enables /debug/traces[/slow] and
    ``debug_vars`` (a () -> dict callable) /debug/vars. ``post_scrape``
    (if set) runs after the duration observation — the exporter uses it
    to poke the off-path self-telemetry renderer."""

    def app(environ, start_response):
        path = environ.get("PATH_INFO", "/")
        if path in ("/debug/traces", "/debug/traces/slow") and tracer is not None:
            body, status = _traces_response(
                tracer, environ.get("QUERY_STRING", ""),
                slow=path.endswith("/slow"),
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/debug/vars" and debug_vars is not None:
            body = _json_dump(debug_vars())
            start_response(
                "200 OK",
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/anomalies" and anomalies is not None:
            body, status = _anomalies_response(
                anomalies, environ.get("QUERY_STRING", "")
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/health/devices" and device_health is not None:
            import json

            doc = device_health()
            body = json.dumps(doc, sort_keys=True).encode() + b"\n"
            status = (
                "200 OK" if doc.get("status") != "crit"
                else "503 Service Unavailable"
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/history" and history is not None:
            body, status = _history_response(
                history, environ.get("QUERY_STRING", "")
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path in ("/healthz", "/livez", "/readyz"):
            ok, detail = health()
            status = "200 OK" if ok else "503 Service Unavailable"
            body = detail.encode()
            start_response(
                status,
                [
                    ("Content-Type", "text/plain; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path in ("/metrics", "/"):
            t0 = time.perf_counter()
            try:
                # Prometheus sends Accept-Encoding: gzip on every scrape;
                # at 1 Hz × full families the ~10x shrink matters on the
                # pod network.
                want_gzip = "gzip" in environ.get("HTTP_ACCEPT_ENCODING", "")
                body = render_body(want_gzip)
                headers = [("Content-Type", _CONTENT_TYPE)]
                if want_gzip:
                    headers.append(("Content-Encoding", "gzip"))
                headers.append(("Content-Length", str(len(body))))
                start_response("200 OK", headers)
                return [body]
            finally:
                telemetry.scrape_duration.observe(time.perf_counter() - t0)
                if post_scrape is not None:
                    post_scrape()
        body = b"not found; try /metrics, /healthz, or /debug/vars\n"
        start_response(
            "404 Not Found",
            [
                ("Content-Type", "text/plain; charset=utf-8"),
                ("Content-Length", str(len(body))),
            ],
        )
        return [body]

    return app


def _history_response(history, query_string: str) -> tuple[bytes, str]:
    """The /history JSON API (off the scrape hot path).

    - ``GET /history`` → windowed summaries for every live series:
      ``{"window": s, "now": ts, "native": bool, "series": {key: {count,
      min, max, avg, first, last, first_ts, last_ts, rate}}}``
    - ``GET /history?window=30`` → same with a custom window.
    - ``GET /history?series=<key>[&since=<ts>]`` → raw 1 Hz points for one
      series: ``{"series": key, "points": [[ts, value], ...]}``. The key
      is the exact string from the summary view (URL-encoded).

    ``since`` and ``window`` share one validator (module-level
    ``_finite``): NaN/inf/negative values are a 400, never coerced.
    """
    from urllib.parse import parse_qs

    params = parse_qs(query_string)
    now = time.time()
    key = params.get("series", [None])[0]
    if key is not None:
        since = _finite(params.get("since", ["0"])[0])
        if since is None:
            return b'{"error": "bad since"}\n', "400 Bad Request"
        points = history.query(key, since)
        body = _json_dump(
            {"series": key, "now": now, "points": [[t, v] for t, v in points]}
        )
        return body, "200 OK"
    window = _finite(params.get("window", [str(history.max_age)])[0])
    if window is None:
        return b'{"error": "bad window"}\n', "400 Bad Request"
    summaries = history.summarize_all(window, now)
    body = _json_dump(
        {
            "window": window,
            "now": now,
            "native": history.is_native,
            "series": summaries,
        }
    )
    return body, "200 OK"


def _traces_response(tracer, query_string: str, slow: bool) -> tuple[bytes, str]:
    """The /debug/traces[/slow] JSON API (poll-thread state, rendered
    lazily here — never on the scrape path).

    - ``GET /debug/traces`` → the completed-cycle ring: per-cycle span
      trees with trace id, stage names, monotonic start/duration, and
      the PollStats scalars.
    - ``GET /debug/traces/slow`` → only the cycles that overran the
      TPUMON_TRACE_SLOW_CYCLE_MS budget — the exporter's own flight
      recorder.
    - ``?since=<ts>`` replays traces ending at/after ``ts`` — the same
      replay semantics (and the same ``_finite`` validator) as /history
      and /anomalies.
    """
    from urllib.parse import parse_qs

    params = parse_qs(query_string)
    since = _finite(params.get("since", ["0"])[0])
    if since is None:
        return b'{"error": "bad since"}\n', "400 Bad Request"
    doc = tracer.counts()
    doc["now"] = time.time()
    doc["slow_cycle_ms"] = tracer.slow_cycle_ms
    doc["traces"] = tracer.traces(slow=slow, since=since)
    return _json_dump(doc), "200 OK"


def _anomalies_response(engine, query_string: str) -> tuple[bytes, str]:
    """The /anomalies JSON API (poll-thread state, no device calls).

    - ``GET /anomalies`` → every retained event (bounded per-device
      rings) plus the engine envelope: ``{"now": ts, "detectors": [...],
      "cycles": n, "active": n, "total": n, "status": ok|warn|crit,
      "events": [{id, detector, severity, device, signal, message,
      value, onset_ts, clear_ts, updated_ts, window}, ...]}`` —
      id-ordered, so replays are deterministic.
    - ``GET /anomalies?since=<ts>`` → only events updated (onset OR
      clear) at/after ``ts`` — the same replay semantics as /history.
    """
    from urllib.parse import parse_qs

    params = parse_qs(query_string)
    since = _finite(params.get("since", ["0"])[0])
    if since is None:
        return b'{"error": "bad since"}\n', "400 Bad Request"
    doc = engine.summary()
    doc["now"] = time.time()
    doc["events"] = engine.events(since)
    return _json_dump(doc), "200 OK"


def registry_renderer(registry: CollectorRegistry):
    def render(want_gzip: bool) -> bytes:
        body = exposition.generate_latest(registry)
        return gzip.compress(body, compresslevel=1) if want_gzip else body

    return render


class _SelfTelemetryPage:
    """Cached render of the self-telemetry registry, refreshed OFF the
    scrape latency path.

    ``generate_latest`` over the self-telemetry registry costs ~0.3 ms
    (measured: median 0.26 ms, p99 0.46 ms on this host) — the dominant
    app-level cost of a scrape once the device page is pre-rendered bytes,
    and the driver of the r1→r3 p99 drift (0.641→0.965 ms). A scrape's own
    duration observation was never visible in its own response (the
    histogram is observed *after* rendering), so serving a render that is
    at most MIN_REFRESH_SPACING old loses nothing a monitoring consumer
    can see.

    Refresh triggers: ``poke()`` after each scrape (the refresher thread
    renders, off the latency path) and a synchronous ``refresh()`` from
    the poll cycle (so a poll's gauge updates are scrapeable the moment
    ``poll_once`` returns — tests rely on that determinism). Renders are
    serialized under a render mutex so the two callers cannot publish
    out of order; the scrape path takes only the publish lock, which a
    render holds just for the byte-swap.
    """

    #: Minimum spacing between poke-triggered renders. Back-to-back
    #: scrapes otherwise contend with their own telemetry render for the
    #: GIL (measured: p99 0.81 ms with per-scrape renders vs 0.33 ms
    #: without); Prometheus scrapes are >=1 s apart, so 250 ms staleness
    #: is invisible while bursts (soak tests, fan-in scrapers) coalesce.
    MIN_REFRESH_SPACING = 0.25

    def __init__(self, registry: CollectorRegistry) -> None:
        self._registry = registry
        self._lock = threading.Lock()
        self._render_lock = threading.Lock()
        self._bytes = exposition.generate_latest(registry)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tpumon-selftel", daemon=True
        )
        self._thread.start()

    def latest(self) -> bytes:
        with self._lock:
            return self._bytes

    def refresh(self) -> None:
        """One re-render (~0.3 ms), safe from any thread: the render
        mutex makes render+publish atomic w.r.t. other renderers, so a
        later render can never be overwritten by an earlier one."""
        with self._render_lock:
            body = exposition.generate_latest(self._registry)
            with self._lock:
                self._bytes = body

    def poke(self) -> None:
        self._wake.set()

    def _run(self) -> None:
        while True:
            self._wake.wait()
            if self._stop.is_set():
                return
            self._wake.clear()
            try:
                self.refresh()
            except Exception:  # never let a render bug kill the refresher
                log.exception("self-telemetry render failed")
            # Coalesce bursts: all pokes during the pause fold into one
            # render when it ends.
            if self._stop.wait(self.MIN_REFRESH_SPACING):
                return

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=2.0)


class ExporterServer:
    """Owns the WSGI server thread; ``port`` is resolved after bind
    (port 0 → ephemeral, used heavily by tests)."""

    def __init__(self, app, addr: str, port: int) -> None:
        self._httpd = make_server(
            addr, port, app, server_class=_ThreadingWSGIServer, handler_class=_Handler
        )
        self.addr = addr
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="tpumon-http",
            daemon=True,
        )
        self._started = False

    @property
    def url(self) -> str:
        host = "127.0.0.1" if self.addr in ("0.0.0.0", "") else self.addr
        return f"http://{host}:{self.port}"

    def start(self) -> None:
        self._thread.start()
        self._started = True

    def close(self) -> None:
        # shutdown() waits on an event only serve_forever() sets; calling it
        # on a never-started server would deadlock the failure path.
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()


class Exporter:
    """Fully wired exporter: backend + poller + registry + HTTP server."""

    def __init__(self, cfg: Config, backend: Backend) -> None:
        self.cfg = cfg
        self.backend = backend
        self._started_at = time.time()
        # Self-telemetry lives in its own registry: the device families are
        # pre-rendered once per poll (SampleCache), so a scrape serves
        # cached bytes + this small registry's render.
        self.registry = CollectorRegistry()
        self.telemetry = SelfTelemetry(self.registry)
        self.cache = SampleCache()
        # Start the native-renderer build off the poll path; renders use
        # the Python fallback until it's ready.
        from tpumon import _native

        _native.prewarm_async()
        attribution = None
        if cfg.pod_attribution:
            from tpumon.attribution import PodAttribution, PodResourcesClient

            attribution = PodAttribution(
                PodResourcesClient(cfg.kubelet_socket, cfg.grpc_timeout)
            )
        self.history = None
        if cfg.history_window > 0:
            from tpumon.history import History

            # Malformed knobs degrade to the default, never CrashLoopBackOff
            # (same stance as config._env_int).
            max_samples = cfg.history_max_samples
            if max_samples <= 0:
                max_samples = type(cfg)().history_max_samples
            self.history = History(
                max_age=cfg.history_window, max_samples=max_samples
            )
        self.histograms = None
        if cfg.histograms:
            from tpumon.exporter.histograms import PollHistograms

            self.histograms = PollHistograms()
        self.anomaly = None
        if cfg.anomaly:
            from tpumon.anomaly import AnomalyEngine

            # Same malformed-knob stance as history_max_samples above.
            max_events = cfg.anomaly_events_max
            if max_events <= 0:
                max_events = type(cfg)().anomaly_events_max
            self.anomaly = AnomalyEngine(
                history=self.history, max_events=max_events
            )
        self.tracer = None
        if cfg.trace:
            from tpumon.trace import Tracer

            defaults = type(cfg)()
            slow_ms = cfg.trace_slow_cycle_ms
            if slow_ms <= 0:  # malformed-knob stance, as history/anomaly
                slow_ms = defaults.trace_slow_cycle_ms
            ring = cfg.trace_ring if cfg.trace_ring > 0 else defaults.trace_ring
            slow_ring = (
                cfg.trace_slow_ring
                if cfg.trace_slow_ring > 0
                else defaults.trace_slow_ring
            )
            stage_hist = self.telemetry.trace_stage_duration

            def observe_stage(stage: str, seconds: float) -> None:
                stage_hist.labels(stage=stage).observe(seconds)

            self.tracer = Tracer(
                slow_cycle_ms=slow_ms, ring=ring, slow_ring=slow_ring,
                observe=observe_stage,
            )
        self.resilience = None
        if cfg.resilience:
            from tpumon.resilience import PollResilience

            defaults = type(cfg)()
            self.resilience = PollResilience(
                breaker_failures=(
                    cfg.breaker_failures
                    if cfg.breaker_failures > 0
                    else defaults.breaker_failures
                ),
                breaker_open_s=(
                    cfg.breaker_open_s
                    if cfg.breaker_open_s > 0
                    else defaults.breaker_open_s
                ),
                breaker_probes=(
                    cfg.breaker_probes
                    if cfg.breaker_probes > 0
                    else defaults.breaker_probes
                ),
                stale_serve_s=max(0.0, cfg.stale_serve_s),
            )
        self.watchdog = None
        if cfg.watchdog_hang_s > 0:
            from tpumon.resilience import PollWatchdog

            self.watchdog = PollWatchdog(
                cfg.watchdog_hang_s, self._recover_backend
            )
        self.poller = Poller(
            backend, cfg, self.cache, self.telemetry, attribution,
            history=self.history, histograms=self.histograms,
            anomaly=self.anomaly, tracer=self.tracer,
            resilience=self.resilience, watchdog=self.watchdog,
        )
        version_fn = getattr(backend, "version", None)
        self.telemetry.backend_info.labels(
            backend=backend.name,
            version=version_fn() if version_fn else "unknown",
        ).set(1)

        # Self-telemetry render cache: both page halves are now cached
        # bytes on the scrape path (device page per poll, self-telemetry
        # per scrape/poll via the off-path refresher).
        self._selfpage = _SelfTelemetryPage(self.registry)
        self.poller.on_cycle = self._selfpage.refresh

        def render(want_gzip: bool) -> bytes:
            # Single gzip member per response: multi-member concatenation
            # of a cached compressed part would be RFC-legal but silently
            # truncates on one-shot zlib decoders (browsers, naive
            # scrapers); level-1 over ~35 KB costs ~0.3 ms, a price worth
            # universal correctness.
            body = self.cache.rendered() + self._selfpage.latest()
            return gzip.compress(body, compresslevel=1) if want_gzip else body

        #: Full-page renderer (device cache + self-telemetry).
        self.render_page = lambda: render(False)

        def render_with_version() -> tuple[bytes, int]:
            # Atomic pair: the device page and the version it carries come
            # from one cache read, so gRPC change-detection can't tear.
            dev, version = self.cache.rendered_with_version()
            return dev + self._selfpage.latest(), version

        self.render_with_version = render_with_version
        app = _make_app(
            render, self.telemetry, self._health, self.history,
            self._device_health, post_scrape=self._selfpage.poke,
            anomalies=self.anomaly, tracer=self.tracer,
            debug_vars=self._debug_vars,
        )
        self.server = ExporterServer(app, cfg.addr, cfg.port)
        self.grpc_server = None
        if cfg.grpc_serve_port >= 0:  # -1 disables; 0 = ephemeral (tests)
            try:
                from tpumon.exporter.grpc_service import MetricsGrpcServer

                self.grpc_server = MetricsGrpcServer(
                    self.render_with_version, self.cache, cfg.addr,
                    cfg.grpc_serve_port, tracer=self.tracer,
                )
            except Exception as exc:
                # grpcio missing or bind failure must not take down the
                # HTTP scrape plane.
                log.warning("grpc metrics service unavailable: %s", exc)

    def _recover_backend(self) -> None:
        """Watchdog hook: a poll cycle is stuck past the hang budget.

        Runs on the watchdog thread. ``interrupt()`` releases injected
        hangs (fault backend); ``reset()`` tears down transport state
        (the gRPC backend closes its channel, failing any in-flight RPC
        so the stuck call raises and the cycle completes). The flags are
        re-rendered immediately so the very next scrape shows the onset.
        """
        self.telemetry.watchdog_recoveries.inc()
        self.telemetry.up.set(0.0)
        self.telemetry.degraded.set(1.0)
        for method in ("interrupt", "reset"):
            fn = getattr(self.backend, method, None)
            if fn is None:
                continue
            try:
                fn()
            except Exception:
                log.exception("backend %s() failed during recovery", method)
        try:
            self._selfpage.refresh()
        except Exception:
            log.exception("self-telemetry refresh failed during recovery")

    def _debug_vars(self) -> dict:
        """The /debug/vars body (expvar analogue): process, config, and
        subsystem occupancy — O(1) in-process reads only, no device
        calls, nothing shared with the scrape path."""
        import dataclasses
        import gc
        import os
        import sys

        stats = self.poller.last_stats
        doc: dict = {
            "now": time.time(),
            "uptime_seconds": time.time() - self._started_at,
            "pid": os.getpid(),
            "python": sys.version.split()[0],
            "backend": self.backend.name,
            "config": dataclasses.asdict(self.cfg),
            "gc": {"counts": gc.get_count(), "enabled": gc.isenabled()},
            "threads": sorted(t.name for t in threading.enumerate()),
            "cache_version": self.cache.rendered_with_version()[1],
            "last_poll": {
                "families": stats.families,
                "points": stats.points,
                "coverage": stats.coverage,
                "backend_errors": stats.backend_errors,
                "parse_errors": stats.parse_errors,
                "degraded": stats.degraded,
                "breaker_open": stats.breaker_open,
                "stale_families": {
                    name: round(age, 3)
                    for name, age in stats.stale_families.items()
                },
            },
        }
        if self.resilience is not None:
            doc["resilience"] = self.resilience.snapshot()
        if self.watchdog is not None:
            doc.setdefault("resilience", {})["watchdog"] = {
                "hang_budget_s": self.watchdog.hang_budget_s,
                "recoveries": self.watchdog.recoveries,
            }
        if self.tracer is not None:
            doc["trace"] = {
                "slow_cycle_ms": self.tracer.slow_cycle_ms,
                **self.tracer.counts(),
            }
        if self.history is not None:
            series, samples = self.history.stats()
            doc["history"] = {
                "series": series,
                "samples": samples,
                "native": self.history.is_native,
            }
        if self.anomaly is not None:
            doc["anomaly"] = self.anomaly.summary()
        return doc

    def _device_health(self) -> dict:
        """The /health/devices body: the verdict the poll cycle already
        computed (PollStats.health) — O(1) per request, never touches the
        device backend. The poller primes synchronously at start, so the
        None fallback only covers a request racing construction."""
        health = self.poller.last_stats.health
        if health is None:
            return {"status": "ok", "findings": [], "chips": 0, "coverage": None}
        return health

    def _health(self) -> tuple[bool, str]:
        last = self.telemetry.last_poll._value.get()
        if last == 0:
            return False, "no poll completed yet\n"
        age = time.time() - last
        budget = self.cfg.interval * HEALTH_STALE_INTERVALS
        if age > budget:
            return False, f"poll loop stale: last poll {age:.1f}s ago\n"
        return True, "ok\n"

    def start(self) -> None:
        if self.watchdog is not None:
            self.watchdog.start()
        self.poller.start()
        self.server.start()
        log.info(
            "exporter serving %s/metrics (backend=%s, interval=%.2fs)",
            self.server.url,
            self.backend.name,
            self.cfg.interval,
        )

    def close(self) -> None:
        if self.grpc_server is not None:
            self.grpc_server.close()
        self.server.close()
        # Poller first: a cycle stuck in a device call still gets watchdog
        # recovery while stop() waits on the join.
        self.poller.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        self._selfpage.close()
        self.backend.close()


def build_exporter(cfg: Config, backend: Backend | None = None) -> Exporter:
    if backend is None:
        from tpumon.backends import create_backend

        backend = create_backend(cfg)
    return Exporter(cfg, backend)
