"""HTTP scrape plane (SURVEY.md §1 L4, §3.2).

A threading WSGI server exposing ``/metrics`` (Prometheus text exposition)
plus ``/healthz`` (K8s liveness: fails when the poll loop stalls). Scrape
timing is measured by middleware around the exposition app and feeds the
``exporter_scrape_duration_seconds`` headline histogram.
"""

from __future__ import annotations

import gzip
import io
import logging
import socket
import sys
import threading
import time
from socketserver import ThreadingMixIn
from wsgiref.simple_server import (
    ServerHandler,
    WSGIRequestHandler,
    WSGIServer,
    make_server,
)

from prometheus_client import exposition
from prometheus_client.registry import CollectorRegistry

from tpumon.backends.base import Backend
from tpumon.config import Config
from tpumon.exporter.collector import Poller, SampleCache
from tpumon.exporter.telemetry import SelfTelemetry

log = logging.getLogger(__name__)

#: /healthz fails if no poll completed within this many intervals.
HEALTH_STALE_INTERVALS = 5.0


#: Hard caps on the request head, independent of any guard config: one
#: line (request line or header) and the whole head (line + headers).
#: Past either, the server answers 414/431 and closes — it never buffers
#: proportionally to what the client sends.
_MAX_HEAD_LINE = 65536
_MAX_HEAD_BYTES = 65536


class _HeadAborted(Exception):
    """Request-head read did not complete. ``kind``:

    - "idle" — no first byte within the keep-alive idle window (routine
      eviction, not counted);
    - "deadline" — bytes arrived but the head missed its overall
      deadline: the slowloris shape (counted, answered 408);
    - "eof" — the peer hung up mid-head (a Ctrl-C'd curl, a port
      scanner): quiet close, NOT a slowloris — misclassifying it would
      keep the shedding alert asserted on routine probe traffic."""

    def __init__(self, kind: str) -> None:
        self.kind = kind


class _HeadTooLong(Exception):
    """``kind`` is "line" (→414 for the request line) or "total" (→431)."""

    def __init__(self, kind: str) -> None:
        self.kind = kind


class _DeadlineReader:
    """Buffered head reader over the raw connection that enforces an
    OVERALL deadline across ``recv()`` calls.

    A per-recv socket timeout alone cannot kill a slowloris: a client
    dripping one byte per ``timeout - ε`` keeps every individual recv
    legal forever. This reader re-arms the socket timeout with the
    *remaining* deadline before each recv, so the head as a whole is
    bounded no matter how the bytes arrive. Leftover bytes (pipelined
    requests) stay buffered across calls.
    """

    def __init__(self, sock) -> None:
        self._sock = sock
        self._buf = bytearray()

    def read_head(
        self, idle_timeout: float | None, header_timeout: float | None
    ) -> bytes:
        """Request line + headers + blank line, raw. Waits up to
        ``idle_timeout`` for the first byte (keep-alive eviction); once
        any byte exists the whole head must land within
        ``header_timeout``. Raises _HeadAborted / _HeadTooLong /
        ConnectionError; returns b"" on a clean EOF before any byte."""
        head = bytearray()
        scan_from = 0
        deadline = (
            time.monotonic() + header_timeout
            if header_timeout and self._buf
            else None
        )
        while True:
            nl = self._buf.find(b"\n", scan_from)
            if nl >= 0:
                line = self._buf[: nl + 1]
                del self._buf[: nl + 1]
                scan_from = 0
                if len(line) > _MAX_HEAD_LINE:
                    # 414 only fits the request line; an oversized
                    # HEADER line is 431 territory (RFC 6585).
                    raise _HeadTooLong("line" if not head else "total")
                head += line
                if len(head) > _MAX_HEAD_BYTES:
                    raise _HeadTooLong("total")
                if line in (b"\r\n", b"\n") and head != line:
                    return bytes(head)
                if line in (b"\r\n", b"\n"):
                    head.clear()  # ignore leading blank lines (RFC 9112)
                continue
            if len(self._buf) > _MAX_HEAD_LINE:
                raise _HeadTooLong(
                    "line" if not head else "total"
                )
            scan_from = len(self._buf)
            first_byte_seen = bool(head) or bool(self._buf)
            if deadline is None:
                timeout = idle_timeout if not first_byte_seen else None
            else:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    raise _HeadAborted(
                        "deadline" if first_byte_seen else "idle"
                    )
            self._sock.settimeout(timeout)
            try:
                chunk = self._sock.recv(8192)
            except (TimeoutError, socket.timeout):
                raise _HeadAborted(
                    "deadline" if first_byte_seen else "idle"
                ) from None
            if not chunk:
                if first_byte_seen:
                    raise _HeadAborted("eof")  # half a request, then FIN
                return b""
            if deadline is None and header_timeout:
                deadline = time.monotonic() + header_timeout
            self._buf += chunk


class _Handler(WSGIRequestHandler):
    """HTTP/1.1 keep-alive so Prometheus reuses its scrape connection.

    Plain wsgiref serves ONE request per connection (its ``handle`` never
    loops) and stamps HTTP/1.0 status lines regardless of
    ``protocol_version`` — so this re-implements ``handle`` as the
    standard BaseHTTPRequestHandler loop and forces the handler's HTTP
    version. Every response carries an exact Content-Length (see
    ``_make_app``), which persistent connections require.

    The request head is read through :class:`_DeadlineReader` (overall
    header deadline = the slowloris kill; idle timeout = keep-alive
    eviction; hard line/head byte caps → 414/431), with the budgets
    coming from ``server.ingress_guard`` when the exporter runs guarded
    — the sidecar's unguarded server keeps None timeouts and only the
    byte caps.
    """

    protocol_version = "HTTP/1.1"
    # Persistent connections + Nagle + delayed ACK = ~40 ms stalls on every
    # scrape after the first (measured: keep-alive p50 44 ms without this,
    # ~1 ms with). Prometheus reuses its scrape connection, so this is the
    # production path.
    disable_nagle_algorithm = True

    #: Niceness for guarded serving threads: under CPU starvation (the
    #: DaemonSet runs at a 250m limit) the kernel must prefer the 1 Hz
    #: poll thread over scrape serving, or a scrape storm converts into
    #: missed poll beats. Raising nice needs no privileges; one syscall
    #: per connection thread. Overridable per server (`serve_niceness`
    #: on the server object): the fleet aggregator inverts the priority
    #: — ITS headline is scrape latency, and its collect/ingest work is
    #: the elastic side, so it serves at nice 0 and demotes ingest.
    SERVE_NICENESS = 10

    def setup(self) -> None:
        super().setup()
        self._reader = _DeadlineReader(self.connection)
        niceness = getattr(self.server, "serve_niceness", None)
        if niceness is None:
            # Default: demote only when guarded (the standalone
            # exporter); the sidecar's unguarded server stays at 0. An
            # EXPLICIT serve_niceness applies regardless of guard —
            # guard presence is an admission-control choice, not a
            # scheduling one.
            niceness = (
                self.SERVE_NICENESS
                if getattr(self.server, "ingress_guard", None) is not None
                else 0
            )
        if niceness:
            try:
                import os

                os.setpriority(
                    os.PRIO_PROCESS, threading.get_native_id(), niceness
                )
            except (AttributeError, OSError):
                pass  # non-Linux or denied: serving just stays at nice 0

    def handle(self) -> None:
        self.close_connection = True
        try:
            self.handle_one_request()
            while not self.close_connection:
                self.handle_one_request()
        except OSError as exc:
            # Half-closed peers, write deadlines, and close races are
            # routine client behavior, not exporter errors: close
            # quietly, never leak a traceback (or the serving thread —
            # it exits right here).
            log.debug("connection error from %s: %s", self.client_address, exc)

    def handle_one_request(self) -> None:
        guard = getattr(self.server, "ingress_guard", None)
        idle_t = guard.idle_timeout_s if guard is not None else 0.0
        header_t = guard.header_timeout_s if guard is not None else 0.0
        try:
            head = self._reader.read_head(idle_t or None, header_t or None)
        except _HeadAborted as err:
            if err.kind == "deadline" and guard is not None:
                # Mid-head stall past the deadline: the slowloris shape.
                # ("eof" — peer hung up mid-head — closes quietly; "idle"
                # is routine keep-alive eviction.)
                guard.count_shed("connection", "slowloris")
                self._best_effort_error(408)
            self.close_connection = True
            return
        except _HeadTooLong as err:
            self._best_effort_error(414 if err.kind == "line" else 431)
            self.close_connection = True
            return
        if not head:
            self.close_connection = True
            return
        stream = io.BytesIO(head)
        self.raw_requestline = stream.readline(_MAX_HEAD_LINE + 1)
        self.rfile = stream  # parse_request reads the headers from here
        if not self.parse_request():  # sets close_connection itself
            return
        if self.headers.get("Content-Length") or self.headers.get(
            "Transfer-Encoding"
        ):
            # No endpoint reads a body; rather than parse/drain one, stop
            # reusing the connection so its bytes can't be misread as the
            # next request line.
            self.close_connection = True
        if guard is not None:
            # Response-write deadline: a peer that stops reading can park
            # this thread for at most the write budget per send. ALWAYS
            # re-armed — the head reader leaves whatever remained of the
            # header budget on the socket, and "0 disables" must mean
            # blocking writes, not an arbitrary leftover deadline.
            self.connection.settimeout(guard.write_timeout_s or None)
        handler = _QuietServerHandler(
            self.rfile,
            self.wfile,
            self.get_stderr(),
            self.get_environ(),
            multithread=True,
        )
        handler.http_version = "1.1"
        handler.request_handler = self
        handler.run(self.server.get_app())

    def _best_effort_error(self, code: int) -> None:
        """send_error against a possibly-dead socket, quietly."""
        self.requestline = ""
        self.request_version = ""
        self.command = ""
        try:
            self.send_error(code)
        except (ConnectionError, TimeoutError, socket.timeout, OSError):
            pass

    def log_message(self, *args) -> None:  # keep scrape noise out of logs
        pass


class _QuietServerHandler(ServerHandler):
    """wsgiref's ServerHandler prints tracebacks to stderr on any failure
    mid-response; this routes them through logging instead — connection
    drops and write timeouts at DEBUG (routine client behavior), real
    app bugs at ERROR — and never tries to write an error body to a
    socket that just failed a write."""

    _CLIENT_GONE = (ConnectionError, TimeoutError, socket.timeout)

    def run(self, application) -> None:
        # wsgiref's run() silently swallows ConnectionAborted/BrokenPipe/
        # ConnectionReset WITHOUT reaching handle_error — which would
        # leave the keep-alive loop free to reuse a connection whose
        # response was truncated mid-write. Route every failure through
        # handle_error instead, which ends the connection.
        try:
            self.setup_environ()
            self.result = application(self.environ, self.start_response)
            self.finish_response()
        except BaseException:
            try:
                self.handle_error()
            except BaseException:
                self.close()
                raise

    def log_exception(self, exc_info) -> None:
        if isinstance(exc_info[1], self._CLIENT_GONE):
            log.debug("client connection lost mid-response: %s", exc_info[1])
        else:
            log.error("unhandled error serving request", exc_info=exc_info)

    def handle_error(self) -> None:
        self.log_exception(sys.exc_info())
        # Whatever failed, this response is not trustworthy framing for
        # a persistent connection: a truncated body or a Content-Length
        # -less error page would corrupt the next pipelined exchange,
        # and a half-dead peer must not park this thread for another
        # idle-timeout. End the connection after this request.
        if self.request_handler is not None:
            self.request_handler.close_connection = True
        if isinstance(sys.exc_info()[1], self._CLIENT_GONE):
            self.close()
            return
        if not self.headers_sent:
            self.result = self.error_output(self.environ, self.start_response)
            self.finish_response()


class _ThreadingWSGIServer(ThreadingMixIn, WSGIServer):
    daemon_threads = True
    allow_reuse_address = True  # SO_REUSEADDR: fast rebind across restarts
    address_family = socket.AF_INET
    #: Set by ExporterServer when the exporter runs guarded; the handler
    #: and middleware read their budgets from it. None = unguarded.
    ingress_guard = None

    def server_bind(self) -> None:
        super().server_bind()
        # Close-on-exec (redundantly with PEP 446, but explicit): a
        # backend recovery that ever exec()s must not leak the scrape
        # listener into the child.
        self.socket.set_inheritable(False)

    def handle_error(self, request, client_address) -> None:
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, TimeoutError, socket.timeout)):
            log.debug("connection error from %s: %s", client_address, exc)
        else:
            log.exception("error processing request from %s", client_address)


#: Prometheus text exposition format 0.0.4.
_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _finite(raw: str) -> float | None:
    """Shared query-param validation for /history and /anomalies: a
    finite, non-negative float, else None (the endpoints answer 400
    instead of silently coercing NaN/inf/negative time values)."""
    import math

    try:
        v = float(raw)
    except ValueError:
        return None
    return v if math.isfinite(v) and v >= 0 else None


def _json_dump(doc) -> bytes:
    """RFC-strict JSON body shared by /history and /anomalies: device
    anomalies can produce NaN samples, and json.dumps would happily emit
    the non-RFC `NaN` token that jq / JSON.parse reject. Map non-finite
    floats to null instead."""
    import json
    import math

    def clean(o):
        if isinstance(o, float) and not math.isfinite(o):
            return None
        if isinstance(o, dict):
            return {k: clean(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return [clean(v) for v in o]
        return o

    return json.dumps(
        clean(doc), sort_keys=True, allow_nan=False
    ).encode() + b"\n"


#: Replay-response bounds (items / payload bytes) for /debug/traces and
#: /anomalies — defaults for unguarded embedders (sidecar); the exporter
#: passes its TPUMON_GUARD_REPLAY_* knobs.
DEFAULT_REPLAY_MAX_ITEMS = 256
DEFAULT_REPLAY_MAX_BYTES = 1 << 20


def _make_app(
    render_body, telemetry: SelfTelemetry, health, history=None,
    device_health=None, post_scrape=None, anomalies=None, tracer=None,
    debug_vars=None, hostcorr=None, lifecycle=None,
    replay_max_items=DEFAULT_REPLAY_MAX_ITEMS,
    replay_max_bytes=DEFAULT_REPLAY_MAX_BYTES,
    negotiated=None,
):
    """WSGI app. ``render_body(want_gzip: bool) -> bytes`` produces the
    /metrics payload (already gzip-encoded when asked); the exporter
    passes cached-bytes + self-telemetry concatenation, the sidecar a
    plain registry render. ``negotiated`` (a NegotiatedRenderer), when
    given, takes over /metrics entirely: content negotiation across the
    enabled exposition formats with per-encoding response caches —
    ``render_body`` then only backs embedders that skip negotiation.
    ``history`` (a tpumon.history.History) enables
    the /history JSON endpoint; ``device_health`` (a () -> dict callable)
    enables /health/devices (the dcgmi-health analogue); ``anomalies``
    (a tpumon.anomaly.AnomalyEngine) enables /anomalies; ``tracer``
    (a tpumon.trace.Tracer) enables /debug/traces[/slow],
    ``debug_vars`` (a () -> dict callable) /debug/vars, and ``hostcorr``
    (a tpumon.hostcorr.HostCorrPlane) /hostcorr. ``post_scrape``
    (if set) runs after the duration observation — the exporter uses it
    to poke the off-path self-telemetry renderer."""

    def app(environ, start_response):
        path = environ.get("PATH_INFO", "/")
        if path in ("/debug/traces", "/debug/traces/slow") and tracer is not None:
            body, status = _traces_response(
                tracer, environ.get("QUERY_STRING", ""),
                slow=path.endswith("/slow"),
                max_items=replay_max_items, max_bytes=replay_max_bytes,
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/debug/vars" and debug_vars is not None:
            body = _json_dump(debug_vars())
            start_response(
                "200 OK",
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/hostcorr" and hostcorr is not None:
            body, status = _hostcorr_response(
                hostcorr, environ.get("QUERY_STRING", ""),
                max_items=replay_max_items, max_bytes=replay_max_bytes,
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/lifecycle" and lifecycle is not None:
            body, status = _lifecycle_response(
                lifecycle, environ.get("QUERY_STRING", ""),
                max_items=replay_max_items, max_bytes=replay_max_bytes,
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/anomalies" and anomalies is not None:
            body, status = _anomalies_response(
                anomalies, environ.get("QUERY_STRING", ""),
                max_items=replay_max_items, max_bytes=replay_max_bytes,
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/health/devices" and device_health is not None:
            import json

            doc = device_health()
            body = json.dumps(doc, sort_keys=True).encode() + b"\n"
            status = (
                "200 OK" if doc.get("status") != "crit"
                else "503 Service Unavailable"
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path == "/history" and history is not None:
            body, status = _history_response(
                history, environ.get("QUERY_STRING", "")
            )
            start_response(
                status,
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path in ("/healthz", "/livez", "/readyz"):
            ok, detail = health()
            status = "200 OK" if ok else "503 Service Unavailable"
            body = detail.encode()
            start_response(
                status,
                [
                    ("Content-Type", "text/plain; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]
        if path in ("/metrics", "/"):
            t0 = time.perf_counter()
            try:
                if negotiated is not None:
                    body, headers = negotiated.respond(environ)
                else:
                    # Prometheus sends Accept-Encoding: gzip on every
                    # scrape; at 1 Hz × full families the ~10x shrink
                    # matters on the pod network.
                    want_gzip = "gzip" in environ.get(
                        "HTTP_ACCEPT_ENCODING", ""
                    )
                    body = render_body(want_gzip)
                    headers = [("Content-Type", _CONTENT_TYPE)]
                    if want_gzip:
                        headers.append(("Content-Encoding", "gzip"))
                    headers.append(("Content-Length", str(len(body))))
                start_response("200 OK", headers)
                return [body]
            finally:
                telemetry.scrape_duration.observe(time.perf_counter() - t0)
                if post_scrape is not None:
                    post_scrape()
        body = b"not found; try /metrics, /healthz, or /debug/vars\n"
        start_response(
            "404 Not Found",
            [
                ("Content-Type", "text/plain; charset=utf-8"),
                ("Content-Length", str(len(body))),
            ],
        )
        return [body]

    return app


def _history_response(history, query_string: str) -> tuple[bytes, str]:
    """The /history JSON API (off the scrape hot path).

    - ``GET /history`` → windowed summaries for every live series:
      ``{"window": s, "now": ts, "native": bool, "series": {key: {count,
      min, max, avg, first, last, first_ts, last_ts, rate}}}``
    - ``GET /history?window=30`` → same with a custom window.
    - ``GET /history?series=<key>[&since=<ts>]`` → raw 1 Hz points for one
      series: ``{"series": key, "points": [[ts, value], ...]}``. The key
      is the exact string from the summary view (URL-encoded).

    ``since`` and ``window`` share one validator (module-level
    ``_finite``): NaN/inf/negative values are a 400, never coerced.
    """
    params, since = _parse_since(query_string)
    now = time.time()
    key = params.get("series", [None])[0]
    if key is not None:
        if since is None:
            return b'{"error": "bad since"}\n', "400 Bad Request"
        points = history.query(key, since)
        body = _json_dump(
            {"series": key, "now": now, "points": [[t, v] for t, v in points]}
        )
        return body, "200 OK"
    window = _finite(params.get("window", [str(history.max_age)])[0])
    if window is None:
        return b'{"error": "bad window"}\n', "400 Bad Request"
    summaries = history.summarize_all(window, now)
    body = _json_dump(
        {
            "window": window,
            "now": now,
            "native": history.is_native,
            "series": summaries,
        }
    )
    return body, "200 OK"


def _parse_since(query_string: str):
    """(params, since) for the replay endpoints — one ``_finite``
    validator so /debug/traces, /anomalies, and /hostcorr can't drift on
    what a bad ``since`` means (``None`` = caller answers 400)."""
    from urllib.parse import parse_qs

    params = parse_qs(query_string)
    return params, _finite(params.get("since", ["0"])[0])


def _bounded_replay(
    doc: dict, items: list, items_key: str,
    max_items: int, max_bytes: int, resume,
) -> tuple[bytes, str]:
    """Shared tail of every replay endpoint: bound the item list, stamp
    ``now``/``truncated`` and the continuation token, serialize.
    ``resume(kept, items)`` returns the ``(key, value)`` continuation
    field for a truncated response."""
    doc["now"] = time.time()
    kept, truncated = _bounded_items(items, max_items, max_bytes)
    doc[items_key] = kept
    if truncated:
        doc["truncated"] = True
        key, value = resume(kept, items)
        doc[key] = value
    return _json_dump(doc), "200 OK"


def _bounded_items(items: list, max_items: int, max_bytes: int):
    """Truncate a replay item list to the response bounds; returns
    (kept, truncated?). At least one item is always kept so an oversized
    single item stays fetchable. Byte accounting serializes per item —
    exact enough, and these endpoints are off every hot path."""
    kept: list = []
    total = 0
    for item in items:
        size = len(_json_dump(item))
        if kept and (
            len(kept) >= max(1, max_items) or total + size > max_bytes
        ):
            return kept, True
        kept.append(item)
        total += size
    return kept, False


def _traces_response(
    tracer, query_string: str, slow: bool,
    max_items: int = DEFAULT_REPLAY_MAX_ITEMS,
    max_bytes: int = DEFAULT_REPLAY_MAX_BYTES,
) -> tuple[bytes, str]:
    """The /debug/traces[/slow] JSON API (poll-thread state, rendered
    lazily here — never on the scrape path).

    - ``GET /debug/traces`` → the completed-cycle ring: per-cycle span
      trees with trace id, stage names, monotonic start/duration, and
      the PollStats scalars.
    - ``GET /debug/traces/slow`` → only the cycles that overran the
      TPUMON_TRACE_SLOW_CYCLE_MS budget — the exporter's own flight
      recorder.
    - ``?since=<ts>`` replays traces ending at/after ``ts`` — the same
      replay semantics (and the same ``_finite`` validator) as /history
      and /anomalies.
    - Responses are BOUNDED: at most ``max_items`` traces /
      ``max_bytes`` payload per response. A truncated response carries
      ``"truncated": true`` and ``"next_since"`` — pass it back as
      ``?since=`` to continue; a stale ``since`` can therefore never
      serialize the whole ring in one allocation.
    """
    _, since = _parse_since(query_string)
    if since is None:
        return b'{"error": "bad since"}\n', "400 Bad Request"
    doc = tracer.counts()
    doc["slow_cycle_ms"] = tracer.slow_cycle_ms
    return _bounded_replay(
        doc, tracer.traces(slow=slow, since=since), "traces",
        max_items, max_bytes,
        # Traces are oldest-first with monotonically increasing end_ts;
        # the first excluded item's end_ts is an exact resume point for
        # the >= since filter.
        lambda kept, items: ("next_since", items[len(kept)]["end_ts"]),
    )


def _anomalies_response(
    engine, query_string: str,
    max_items: int = DEFAULT_REPLAY_MAX_ITEMS,
    max_bytes: int = DEFAULT_REPLAY_MAX_BYTES,
) -> tuple[bytes, str]:
    """The /anomalies JSON API (poll-thread state, no device calls).

    - ``GET /anomalies`` → every retained event (bounded per-device
      rings) plus the engine envelope: ``{"now": ts, "detectors": [...],
      "cycles": n, "active": n, "total": n, "status": ok|warn|crit,
      "events": [{id, detector, severity, device, signal, message,
      value, onset_ts, clear_ts, updated_ts, window}, ...]}`` —
      id-ordered, so replays are deterministic.
    - ``GET /anomalies?since=<ts>`` → only events updated (onset OR
      clear) at/after ``ts`` — the same replay semantics as /history.
    - Responses are BOUNDED: at most ``max_items`` events /
      ``max_bytes`` payload per response. A truncated response carries
      ``"truncated": true`` and ``"next_cursor"`` (the last included
      event id) — pass it back as ``?cursor=`` (combinable with
      ``since``) to fetch events with a greater id.
    """
    params, since = _parse_since(query_string)
    if since is None:
        return b'{"error": "bad since"}\n', "400 Bad Request"
    cursor_raw = params.get("cursor", ["0"])[0]
    try:
        cursor = int(cursor_raw)
    except ValueError:
        cursor = -1
    if cursor < 0:
        return b'{"error": "bad cursor"}\n', "400 Bad Request"
    events = [e for e in engine.events(since) if e["id"] > cursor]
    return _bounded_replay(
        engine.summary(), events, "events", max_items, max_bytes,
        lambda kept, items: ("next_cursor", kept[-1]["id"]),
    )


def _hostcorr_response(
    plane, query_string: str,
    max_items: int = DEFAULT_REPLAY_MAX_ITEMS,
    max_bytes: int = DEFAULT_REPLAY_MAX_BYTES,
) -> tuple[bytes, str]:
    """The /hostcorr JSON API (poll-thread state, no device calls).

    - ``GET /hostcorr`` → the correlation-ring replay plus the plane
      envelope: ``{"now": ts, "cycles": n, "available": bool, "groups":
      {psi: bool, ...}, "straggler": {active, skew_pct, chip, cause?},
      "events_total": {cause: n}, "records": [{ts, host, device,
      straggler}, ...]}`` — each record is one poll cycle's time-aligned
      host+device join, oldest first.
    - ``GET /hostcorr?since=<ts>`` → only records at/after ``ts`` — the
      same replay semantics (and ``_finite`` validator) as /history and
      /anomalies.
    - Responses are BOUNDED: at most ``max_items`` records /
      ``max_bytes`` payload. A truncated response carries
      ``"truncated": true`` and ``"next_since"`` — pass it back as
      ``?since=`` to continue.
    """
    _, since = _parse_since(query_string)
    if since is None:
        return b'{"error": "bad since"}\n', "400 Bad Request"
    doc, records = plane.replay(since)
    return _bounded_replay(
        doc, records, "records", max_items, max_bytes,
        # Records are oldest-first with monotonically increasing ts; the
        # first excluded record's ts resumes the >= since filter exactly.
        lambda kept, items: ("next_since", items[len(kept)]["ts"]),
    )


def _lifecycle_response(
    plane, query_string: str,
    max_items: int = DEFAULT_REPLAY_MAX_ITEMS,
    max_bytes: int = DEFAULT_REPLAY_MAX_BYTES,
) -> tuple[bytes, str]:
    """The /lifecycle JSON API (poll-thread state, no device calls).

    - ``GET /lifecycle`` → the lifecycle-ring replay plus the plane
      envelope: ``{"now": ts, "cycles": n, "workloads": {configured,
      available}, "transition": bool, "kinds": [...], "events_total":
      {kind: n}, "records": [{ts, transition, kinds, signals,
      new_events, workloads, step_rate, ...}, ...]}`` — each record is
      one poll cycle's time-aligned step+device join, oldest first.
    - ``GET /lifecycle?since=<ts>`` → only records at/after ``ts`` —
      the same replay semantics (and ``_finite`` validator) as
      /history, /anomalies, and /hostcorr.
    - Responses are BOUNDED: at most ``max_items`` records /
      ``max_bytes`` payload. A truncated response carries
      ``"truncated": true`` and ``"next_since"`` — pass it back as
      ``?since=`` to continue.
    """
    _, since = _parse_since(query_string)
    if since is None:
        return b'{"error": "bad since"}\n', "400 Bad Request"
    doc, records = plane.replay(since)
    return _bounded_replay(
        doc, records, "records", max_items, max_bytes,
        # Records are oldest-first with monotonically increasing ts; the
        # first excluded record's ts resumes the >= since filter exactly.
        lambda kept, items: ("next_since", items[len(kept)]["ts"]),
    )


def registry_renderer(registry: CollectorRegistry):
    """Plain registry renderer (sidecar, workload harness): render per
    scrape, but compress per *change* — the gzip of an unchanged page is
    reused via a one-entry cache keyed on the identity bytes, so a
    scraper polling a quiet registry costs a render + memcmp, not a
    render + deflate every time."""
    from tpumon.exporter.encodings import EncodedPageCache, gzip_page

    cache = EncodedPageCache()

    def render(want_gzip: bool) -> bytes:
        body = exposition.generate_latest(registry)
        if not want_gzip:
            return body
        return cache.get(("registry", "gzip"), (body,), lambda: gzip_page(body))

    return render


class NegotiatedRenderer:
    """/metrics response builder for the exporter: content negotiation
    (text / OpenMetrics / compact snapshot) + per-(format, encoding)
    response caches keyed on the page-version pair.

    The page has two halves with independent versions — the device page
    (SampleCache, bumped per poll) and the self-telemetry page (bumped
    per refresh). A cache hit means the exact response bytes for the
    current (device, self) version pair already exist: the scrape is two
    dict lookups and a socket write, zero render/encode/compress work.
    Every builder below runs at most once per version pair per slot, no
    matter how many scrapers are asking.
    """

    def __init__(
        self, cache, selfpage, formats, telemetry=None, tracer=None,
        self_registry=None, delta_resync_frames: int = 300,
    ) -> None:
        from tpumon.exporter.encodings import (
            DeltaHistory,
            EncodedPageCache,
            parse_formats,
        )

        self._cache = cache
        self._selfpage = selfpage
        #: Registry behind the self-telemetry half; the OpenMetrics body
        #: re-renders it in OM syntax (the cached text bytes are the
        #: wrong format to reuse).
        self._self_registry = self_registry
        self.formats = parse_formats(tuple(formats))
        self._telemetry = telemetry
        self._tracer = tracer
        #: Server half of the delta protocol (bounded seq→snapshot
        #: history + per-(base,seq) frame cache), shared by the HTTP
        #: conditional-GET path and every gRPC Watch stream — one seq
        #: space per exporter, so a consumer can switch transports
        #: without a resync.
        self.delta = DeltaHistory()
        #: Watch streams force a full-snapshot resync frame after this
        #: many consecutive deltas (bounds worst-case divergence from an
        #: undetected consumer bug to one resync window).
        self.delta_resync_frames = max(1, int(delta_resync_frames))
        #: Decoded node snapshot for the current version pair — built at
        #: most once per pair, shared by the snapshot AND delta formats
        #: (the delta diff needs the dict, not the encoded bytes).
        self._snap_state: tuple | None = None  # guarded-by: self._snap_lock
        self._snap_lock = threading.Lock()
        observe = None
        if telemetry is not None:
            saves = telemetry.render_encode_saves

            def observe(slot, hit):
                if hit:
                    saves.labels(format=slot[0], encoding=slot[1]).inc()

        self.encoded = EncodedPageCache(observe=observe)

    def _span(self, name: str):
        from contextlib import nullcontext

        if self._tracer is None:
            return nullcontext()
        # Serving-side encode spans (cache misses only): same
        # self-metric funnel as the gRPC serve spans — never attached
        # to a poll cycle's tree.
        return self._tracer.span(name, stage="scrape_encode")

    def _openmetrics(self, snap) -> bytes:
        """OpenMetrics body from an atomically captured device snapshot.
        The self half re-renders live from the registry: its content may
        be newer than the self_version component of the cache key (the
        registry only ever moves forward — a cached body can carry
        fresher self-telemetry than its key, never staler)."""
        from tpumon.exporter.encodings import (
            openmetrics_join,
            openmetrics_render,
        )

        parts = [openmetrics_render(snap)]
        if self._self_registry is not None:
            from prometheus_client.openmetrics.exposition import (
                generate_latest,
            )

            parts.append(generate_latest(self._self_registry))
        return openmetrics_join(parts)

    def _identity_source(self, fmt: str):
        """(cache key, builder) for the identity-encoded body of ``fmt``
        — the ONE place that maps a format to its bytes, shared by HTTP
        negotiation and gRPC Get/Watch: both transports store into the
        same (fmt, "identity") cache slot, so a second dispatch copy
        drifting would poison the other transport's cached responses."""
        from tpumon.exporter.encodings import (
            FORMAT_OPENMETRICS,
            FORMAT_SNAPSHOT,
            encode_snapshot,
        )

        if fmt == FORMAT_OPENMETRICS:
            # The OM body builds from the family snapshot, so the
            # version captured WITH that snapshot is the key: a body
            # cached for version N is always built from N's families.
            selfb, self_version = self._selfpage.latest_with_version()
            snap, dev_version = self._cache.snapshot_with_version()

            def build() -> bytes:
                with self._span("encode:openmetrics"):
                    return self._openmetrics(snap)

            return (dev_version, self_version), build
        if fmt == FORMAT_SNAPSHOT:
            node, key = self._node_snapshot()

            def build() -> bytes:
                with self._span("encode:snapshot"):
                    return encode_snapshot(node)

            return key, build
        selfb, self_version = self._selfpage.latest_with_version()
        dev, dev_version = self._cache.rendered_with_version()

        def build() -> bytes:
            return dev + selfb

        return (dev_version, self_version), build

    def _node_snapshot(self) -> tuple[dict, tuple]:
        """(decoded node snapshot, page-version key) — the dict the
        snapshot encoding serializes and the delta protocol diffs. The
        page parse runs at most once per version pair (it IS the
        per-change cost of both binary formats); a racing build for an
        older pair never clobbers a newer one (same stance as
        EncodedPageCache)."""
        selfb, self_version = self._selfpage.latest_with_version()
        dev, dev_version = self._cache.rendered_with_version()
        key = (dev_version, self_version)
        with self._snap_lock:
            state = self._snap_state
        if state is not None and state[0] == key:
            return state[1], key
        from tpumon.fleet.ingest import node_snapshot_from_text

        with self._span("encode:snapshot_parse"):
            snap = node_snapshot_from_text((dev + selfb).decode())
        with self._snap_lock:
            stored = self._snap_state
            if stored is None or key >= stored[0]:
                self._snap_state = (key, snap)
        return snap, key

    def delta_frame(
        self, base: int | None, sub: bool = False
    ) -> tuple[bytes, int, str]:
        """One delta-protocol payload: a patch against ``base`` when the
        history can honestly produce one (base retained AND the patch is
        smaller than a resync), else the full snapshot frame. Returns
        ``(payload, seq, kind)`` with kind ∈ delta/snapshot — shared by
        the HTTP conditional-GET path and the gRPC Watch push loop.
        ``sub`` is the consumer-advertised sub-segment capability
        (per-chip patches instead of the whole chips map)."""
        from tpumon.exporter.encodings import (
            FORMAT_DELTA,
            FORMAT_SNAPSHOT,
            encode_snapshot,
        )

        node, key = self._node_snapshot()
        full = self.encoded.get(
            (FORMAT_SNAPSHOT, "identity"), key, lambda: encode_snapshot(node)
        )
        self.delta.record(key, node, full)
        payload, seq, kind = self.delta.frame_from(base, sub=sub)
        if self._telemetry is not None:
            self._telemetry.exposition_requests.labels(
                format=FORMAT_DELTA
            ).inc()
        return payload, seq, kind

    def respond(self, environ) -> tuple[bytes, list[tuple[str, str]]]:
        """(body, headers) for one /metrics request."""
        from tpumon.exporter.encodings import (
            CONTENT_TYPES,
            FORMAT_DELTA,
            FORMAT_SNAPSHOT,
            gzip_page,
            negotiate,
        )

        fmt = negotiate(environ.get("HTTP_ACCEPT", ""), self.formats)
        if fmt == FORMAT_DELTA:
            return self._delta_respond(environ)
        # The snapshot encoding is already compact; gzip applies to the
        # text formats only (Prometheus sends Accept-Encoding: gzip on
        # every scrape — at 1 Hz × full families the ~10x shrink matters
        # on the pod network).
        want_gzip = (
            fmt != FORMAT_SNAPSHOT
            and "gzip" in environ.get("HTTP_ACCEPT_ENCODING", "")
        )
        key, build = self._identity_source(fmt)
        body = self.encoded.get((fmt, "identity"), key, build)
        headers = [("Content-Type", CONTENT_TYPES[fmt])]
        if want_gzip:
            identity = body

            def build_gzip() -> bytes:
                with self._span("encode:gzip"):
                    return gzip_page(identity)

            body = self.encoded.get((fmt, "gzip"), key, build_gzip)
            headers.append(("Content-Encoding", "gzip"))
        # The response varies on negotiation inputs: any cache between
        # scraper and exporter must key on both headers.
        headers.append(("Vary", "Accept, Accept-Encoding"))
        headers.append(("Content-Length", str(len(body))))
        if self._telemetry is not None:
            self._telemetry.exposition_requests.labels(format=fmt).inc()
        return body, headers

    def _delta_respond(self, environ) -> tuple[bytes, list[tuple[str, str]]]:
        """The conditional-GET form of the delta protocol: the poller
        names its base via ``X-Tpumon-Delta-Base: <epoch>:<seq>`` (the
        values a previous response stamped); the response is a delta
        frame when that base is usable, else a full snapshot frame — a
        wrong or missing epoch (server restart, first fetch) always
        resyncs. Binary formats never gzip."""
        from tpumon.exporter.encodings import (
            CONTENT_TYPES,
            DELTA_BASE_HEADER,
            DELTA_SEQ_HEADER,
            accept_delta_sub,
        )

        environ_key = "HTTP_" + DELTA_BASE_HEADER.upper().replace("-", "_")
        base = self._parse_base(environ.get(environ_key, ""))
        body, seq, kind = self.delta_frame(
            base, sub=accept_delta_sub(environ.get("HTTP_ACCEPT", ""))
        )
        headers = [
            ("Content-Type", CONTENT_TYPES[kind]),
            (DELTA_SEQ_HEADER, f"{self.delta.epoch}:{seq}"),
            ("Vary", "Accept, Accept-Encoding"),
            ("Content-Length", str(len(body))),
        ]
        return body, headers

    def _parse_base(self, raw: str) -> int | None:
        """``<epoch>:<seq>`` → seq when the epoch is THIS process's
        delta stream; anything else (other epoch, garbage, absent) is
        no base — the server resyncs rather than guess."""
        epoch_s, _, seq_s = raw.strip().partition(":")
        try:
            if int(epoch_s) != self.delta.epoch:
                return None
            return int(seq_s)
        except ValueError:
            return None

    def page_with_version(self, fmt: str) -> tuple[bytes, int]:
        """Current page in ``fmt`` (identity encoding) plus the device
        cache version — the gRPC Get/Watch payload. Unknown/disabled
        formats serve text, mirroring HTTP negotiation's fallback —
        except a disabled DELTA ask degrades to the snapshot frame when
        that is enabled (the nearest ask, exactly what the same client's
        HTTP Accept chain would have negotiated), so turning delta off
        never silently reverts Watch fan-in to full text pages."""
        from tpumon.exporter.encodings import (
            FORMAT_DELTA,
            FORMAT_SNAPSHOT,
            FORMAT_TEXT,
        )

        if fmt == FORMAT_DELTA:
            if fmt in self.formats:
                # Unary Get names no base: serve the full resync frame,
                # with the delta SEQ as the response version so a
                # consumer can seed stream state from a one-shot fetch.
                body, seq, _kind = self.delta_frame(None)
                return body, seq
            fmt = (
                FORMAT_SNAPSHOT
                if FORMAT_SNAPSHOT in self.formats
                else FORMAT_TEXT
            )
        if fmt not in self.formats:
            fmt = FORMAT_TEXT
        key, build = self._identity_source(fmt)
        body = self.encoded.get((fmt, "identity"), key, build)
        if self._telemetry is not None:
            self._telemetry.exposition_requests.labels(format=fmt).inc()
        return body, key[0]


class _SelfTelemetryPage:
    """Cached render of the self-telemetry registry, refreshed OFF the
    scrape latency path.

    ``generate_latest`` over the self-telemetry registry costs ~0.3 ms
    (measured: median 0.26 ms, p99 0.46 ms on this host) — the dominant
    app-level cost of a scrape once the device page is pre-rendered bytes,
    and the driver of the r1→r3 p99 drift (0.641→0.965 ms). A scrape's own
    duration observation was never visible in its own response (the
    histogram is observed *after* rendering), so serving a render that is
    at most MIN_REFRESH_SPACING old loses nothing a monitoring consumer
    can see.

    Refresh triggers: ``poke()`` after each scrape (the refresher thread
    renders, off the latency path) and a synchronous ``refresh()`` from
    the poll cycle (so a poll's gauge updates are scrapeable the moment
    ``poll_once`` returns — tests rely on that determinism). Renders are
    serialized under a render mutex so the two callers cannot publish
    out of order; the scrape path takes only the publish lock, which a
    render holds just for the byte-swap.
    """

    #: Minimum spacing between poke-triggered renders. Back-to-back
    #: scrapes otherwise contend with their own telemetry render for the
    #: GIL (measured: p99 0.81 ms with per-scrape renders vs 0.33 ms
    #: without); Prometheus scrapes are >=1 s apart, so 250 ms staleness
    #: is invisible while bursts (soak tests, fan-in scrapers) coalesce.
    MIN_REFRESH_SPACING = 0.25

    def __init__(self, registry: CollectorRegistry) -> None:
        self._registry = registry
        self._lock = threading.Lock()
        self._render_lock = threading.Lock()
        self._bytes = exposition.generate_latest(registry)  # guarded-by: self._lock
        #: Bumped per publish — the self half of the response-cache key
        #: (tpumon/exporter/encodings.py EncodedPageCache).
        self._version = 1  # guarded-by: self._lock
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tpumon-selftel", daemon=True
        )
        self._thread.start()

    def latest(self) -> bytes:
        with self._lock:
            return self._bytes

    def latest_with_version(self) -> tuple[bytes, int]:
        """Atomic (page, version) pair — the response caches key on it."""
        with self._lock:
            return self._bytes, self._version

    def refresh(self) -> None:
        """One re-render (~0.3 ms), safe from any thread: the render
        mutex makes render+publish atomic w.r.t. other renderers, so a
        later render can never be overwritten by an earlier one."""
        with self._render_lock:
            body = exposition.generate_latest(self._registry)
            with self._lock:
                # Version bumps only when the bytes differ: an idle
                # registry re-rendering identical content keeps its
                # response-cache entries (and their gzip work) valid.
                if body != self._bytes:
                    self._bytes = body
                    self._version += 1

    def poke(self) -> None:
        self._wake.set()

    def _run(self) -> None:
        while True:
            self._wake.wait()  # deadline: close() sets _wake after _stop — guaranteed wakeup
            if self._stop.is_set():
                return
            self._wake.clear()
            try:
                self.refresh()
            except Exception:  # never let a render bug kill the refresher
                log.exception("self-telemetry render failed")
            # Coalesce bursts: all pokes during the pause fold into one
            # render when it ends.
            if self._stop.wait(self.MIN_REFRESH_SPACING):
                return

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=2.0)


class ExporterServer:
    """Owns the WSGI server thread; ``port`` is resolved after bind
    (port 0 → ephemeral, used heavily by tests). ``guard`` (an
    IngressGuard) arms the handler's request deadlines; None leaves the
    server unguarded (the sidecar)."""

    def __init__(
        self, app, addr: str, port: int, guard=None,
        serve_niceness: int | None = None,
    ) -> None:
        self._httpd = make_server(
            addr, port, app, server_class=_ThreadingWSGIServer, handler_class=_Handler
        )
        self._httpd.ingress_guard = guard
        if serve_niceness is not None:
            self._httpd.serve_niceness = serve_niceness
        self.addr = addr
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="tpumon-http",
            daemon=True,
        )
        self._started = False

    @property
    def url(self) -> str:
        host = "127.0.0.1" if self.addr in ("0.0.0.0", "") else self.addr
        return f"http://{host}:{self.port}"

    def start(self) -> None:
        self._thread.start()
        self._started = True

    def close(self) -> None:
        # shutdown() waits on an event only serve_forever() sets; calling it
        # on a never-started server would deadlock the failure path.
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()


_invariants_cache: dict | None = None


def _invariants_vars() -> dict:
    """The /debug/vars "invariants" block: analyzer version + baseline
    size (static per process) plus the last check stamp when one exists
    on this filesystem (a checkout; container images usually ship none)."""
    global _invariants_cache
    if _invariants_cache is None:
        from tpumon.analysis import ANALYZER_VERSION, baseline_count
        from tpumon.analysis.core import all_rules

        _invariants_cache = {
            "analyzer_version": ANALYZER_VERSION,
            "baseline_violations": baseline_count(),
            "rules": sorted(all_rules()),
        }
    doc = dict(_invariants_cache)
    from tpumon.analysis import stamp_info

    stamp = stamp_info()
    if stamp is not None:
        doc["last_check"] = stamp
    return doc


class Exporter:
    """Fully wired exporter: backend + poller + registry + HTTP server."""

    def __init__(self, cfg: Config, backend: Backend) -> None:
        self.cfg = cfg
        self.backend = backend
        self._started_at = time.time()
        # Self-telemetry lives in its own registry: the device families are
        # pre-rendered once per poll (SampleCache), so a scrape serves
        # cached bytes + this small registry's render.
        self.registry = CollectorRegistry()
        self.telemetry = SelfTelemetry(self.registry)
        self.cache = SampleCache(delta=cfg.render_delta)
        self.telemetry.render_delta.set(1.0 if cfg.render_delta else 0.0)
        # Start the native-renderer build off the poll path; renders use
        # the Python fallback until it's ready.
        from tpumon import _native

        _native.prewarm_async()
        attribution = None
        if cfg.pod_attribution:
            from tpumon.attribution import PodAttribution, PodResourcesClient

            attribution = PodAttribution(
                PodResourcesClient(cfg.kubelet_socket, cfg.grpc_timeout)
            )
        self.history = None
        if cfg.history_window > 0:
            from tpumon.history import History

            # Malformed knobs degrade to the default, never CrashLoopBackOff
            # (same stance as config._env_int).
            max_samples = cfg.history_max_samples
            if max_samples <= 0:
                max_samples = type(cfg)().history_max_samples
            self.history = History(
                max_age=cfg.history_window, max_samples=max_samples
            )
        self.histograms = None
        if cfg.histograms:
            from tpumon.exporter.histograms import PollHistograms

            self.histograms = PollHistograms()
        self.hostcorr = None
        if cfg.hostcorr:
            from tpumon.hostcorr import HostCorrPlane

            # Same malformed-knob stance as history_max_samples below.
            ring = cfg.hostcorr_ring
            if ring <= 0:
                ring = type(cfg)().hostcorr_ring
            self.hostcorr = HostCorrPlane(
                proc_root=cfg.hostcorr_proc_root, ring=ring
            )
        self.lifecycle = None
        if cfg.lifecycle:
            from tpumon.lifecycle import LifecyclePlane

            # Same malformed-knob stance as history_max_samples below.
            lc_ring = cfg.lifecycle_ring
            if lc_ring <= 0:
                lc_ring = type(cfg)().lifecycle_ring
            self.lifecycle = LifecyclePlane(
                step_urls=cfg.lifecycle_step_urls, ring=lc_ring,
                probe_timeout=min(1.0, max(0.2, cfg.interval / 2.0)),
            )
        self.energy = None
        if cfg.energy:
            from tpumon.energy import EnergyPlane

            self.energy = EnergyPlane()
        self.anomaly = None
        if cfg.anomaly:
            from tpumon.anomaly import AnomalyEngine
            from tpumon.anomaly.detectors import default_detectors

            # Same malformed-knob stance as history_max_samples above.
            max_events = cfg.anomaly_events_max
            if max_events <= 0:
                max_events = type(cfg)().anomaly_events_max
            detectors = default_detectors()
            if self.hostcorr is not None:
                # Cross-signal detectors (tpumon/hostcorr/detectors.py)
                # ride the same engine: onset/clear events, /anomalies
                # replay, history windows — fed by the hostcorr block
                # the plane injects into each cycle's snapshot.
                from tpumon.hostcorr import hostcorr_detectors

                detectors.extend(hostcorr_detectors())
            if self.lifecycle is not None:
                # Step-signal + lifecycle detectors (tpumon/lifecycle):
                # step-time regression, collective-wait contention, and
                # the transition event stream — fed by the lifecycle
                # block the plane injects into each cycle's snapshot.
                from tpumon.lifecycle import lifecycle_detectors

                detectors.extend(lifecycle_detectors())
            if self.energy is not None:
                # Efficiency detector (tpumon/energy): same-preset
                # tokens/joule EWMA regression, fed by the energy block
                # the plane injects into each cycle's snapshot.
                from tpumon.energy import energy_detectors

                detectors.extend(energy_detectors())
            self.anomaly = AnomalyEngine(
                history=self.history, max_events=max_events,
                detectors=detectors,
            )
        self.tracer = None
        if cfg.trace:
            from tpumon.trace import Tracer

            defaults = type(cfg)()
            slow_ms = cfg.trace_slow_cycle_ms
            if slow_ms <= 0:  # malformed-knob stance, as history/anomaly
                slow_ms = defaults.trace_slow_cycle_ms
            ring = cfg.trace_ring if cfg.trace_ring > 0 else defaults.trace_ring
            slow_ring = (
                cfg.trace_slow_ring
                if cfg.trace_slow_ring > 0
                else defaults.trace_slow_ring
            )
            stage_hist = self.telemetry.trace_stage_duration

            def observe_stage(stage: str, seconds: float) -> None:
                stage_hist.labels(stage=stage).observe(seconds)

            self.tracer = Tracer(
                slow_cycle_ms=slow_ms, ring=ring, slow_ring=slow_ring,
                observe=observe_stage,
            )
        self.resilience = None
        if cfg.resilience:
            from tpumon.resilience import PollResilience

            defaults = type(cfg)()
            self.resilience = PollResilience(
                breaker_failures=(
                    cfg.breaker_failures
                    if cfg.breaker_failures > 0
                    else defaults.breaker_failures
                ),
                breaker_open_s=(
                    cfg.breaker_open_s
                    if cfg.breaker_open_s > 0
                    else defaults.breaker_open_s
                ),
                breaker_probes=(
                    cfg.breaker_probes
                    if cfg.breaker_probes > 0
                    else defaults.breaker_probes
                ),
                stale_serve_s=max(0.0, cfg.stale_serve_s),
            )
        self.watchdog = None
        if cfg.watchdog_hang_s > 0:
            from tpumon.resilience import PollWatchdog

            self.watchdog = PollWatchdog(
                cfg.watchdog_hang_s, self._recover_backend
            )
        # Self-protection plane (tpumon/guard): ingress admission control,
        # per-family cardinality budget, and RSS watermarks. Built after
        # the ring-owning subsystems so the memory watchdog can register
        # its shrink/restore hooks against them.
        self.guard = None
        self.memwatch = None
        self.governor = None
        if cfg.guard:
            from tpumon.guard import (
                CardinalityGovernor,
                IngressGuard,
                MemoryWatch,
            )
            from tpumon.guard.memwatch import resolve_watermarks

            soft_bytes, hard_bytes = resolve_watermarks(
                cfg.guard_soft_rss_mb, cfg.guard_hard_rss_mb
            )
            self.memwatch = MemoryWatch(
                soft_bytes=soft_bytes, hard_bytes=hard_bytes
            )
            shed_counter = self.telemetry.shed_requests

            def observe_shed(endpoint: str, reason: str) -> None:
                shed_counter.labels(endpoint=endpoint, reason=reason).inc()

            self.guard = IngressGuard(
                metrics_inflight=cfg.guard_metrics_inflight,
                debug_inflight=cfg.guard_debug_inflight,
                metrics_rps=cfg.guard_metrics_rps,
                debug_rps=cfg.guard_debug_rps,
                header_timeout_s=cfg.guard_header_timeout_s,
                idle_timeout_s=cfg.guard_idle_timeout_s,
                write_timeout_s=cfg.guard_write_timeout_s,
                watch_per_client=cfg.guard_watch_per_client,
                memory_state=lambda: self.memwatch.state,
                observe_shed=observe_shed,
            )
            if cfg.guard_max_series_per_family > 0:
                drop_counter = self.telemetry.cardinality_dropped

                def observe_drop(family: str, n: int) -> None:
                    drop_counter.labels(family=family).inc(n)

                self.governor = CardinalityGovernor(
                    cfg.guard_max_series_per_family,
                    observe_drop=observe_drop,
                )
            # Soft-watermark degradation hooks: shrink each bounded ring
            # to a quarter (reversed when RSS recovers under hysteresis).
            if self.tracer is not None:
                self.memwatch.add_hooks(
                    self.tracer.degrade, self.tracer.restore
                )
            if self.history is not None:
                full_samples = self.history.max_samples

                def shrink_history() -> None:
                    self.history.resize(max(64, full_samples // 4))

                def restore_history() -> None:
                    self.history.resize(full_samples)

                self.memwatch.add_hooks(shrink_history, restore_history)
            if self.anomaly is not None:
                full_events = self.anomaly.max_events

                def shrink_anomaly() -> None:
                    self.anomaly.set_max_events(max(8, full_events // 4))

                def restore_anomaly() -> None:
                    self.anomaly.set_max_events(full_events)

                self.memwatch.add_hooks(shrink_anomaly, restore_anomaly)
            if self.hostcorr is not None:
                full_ring = self.hostcorr.ring_capacity

                def shrink_hostcorr() -> None:
                    self.hostcorr.resize(max(16, full_ring // 4))

                def restore_hostcorr() -> None:
                    self.hostcorr.resize(full_ring)

                self.memwatch.add_hooks(shrink_hostcorr, restore_hostcorr)
            if self.lifecycle is not None:
                full_lc_ring = self.lifecycle.ring_capacity

                def shrink_lifecycle() -> None:
                    self.lifecycle.resize(max(16, full_lc_ring // 4))

                def restore_lifecycle() -> None:
                    self.lifecycle.resize(full_lc_ring)

                self.memwatch.add_hooks(shrink_lifecycle, restore_lifecycle)
        self.poller = Poller(
            backend, cfg, self.cache, self.telemetry, attribution,
            history=self.history, histograms=self.histograms,
            anomaly=self.anomaly, tracer=self.tracer,
            resilience=self.resilience, watchdog=self.watchdog,
            governor=self.governor, hostcorr=self.hostcorr,
            lifecycle=self.lifecycle, energy=self.energy,
        )
        version_fn = getattr(backend, "version", None)
        self.telemetry.backend_info.labels(
            backend=backend.name,
            version=version_fn() if version_fn else "unknown",
        ).set(1)

        # Self-telemetry render cache: both page halves are now cached
        # bytes on the scrape path (device page per poll, self-telemetry
        # per scrape/poll via the off-path refresher).
        self._selfpage = _SelfTelemetryPage(self.registry)
        self.poller.on_cycle = self._on_cycle

        #: Negotiated /metrics renderer: text / OpenMetrics / compact
        #: snapshot, each response cached per (format, encoding) keyed
        #: on the (device, self) version pair — an unchanged page costs
        #: zero encode work regardless of scraper count.
        self.renderer = NegotiatedRenderer(
            self.cache, self._selfpage, cfg.exposition_formats,
            telemetry=self.telemetry, tracer=self.tracer,
            self_registry=self.registry,
            # Same malformed-knob stance as history_max_samples above.
            delta_resync_frames=(
                cfg.delta_resync_frames
                if cfg.delta_resync_frames > 0
                else type(cfg)().delta_resync_frames
            ),
        )

        def render(want_gzip: bool) -> bytes:
            # Single gzip member per response: multi-member concatenation
            # of a cached compressed part would be RFC-legal but silently
            # truncates on one-shot zlib decoders (browsers, naive
            # scrapers); level-1 over ~35 KB costs ~0.3 ms, a price worth
            # universal correctness. Embedder-facing — the HTTP app
            # itself goes through self.renderer.
            body = self.cache.rendered() + self._selfpage.latest()
            return gzip.compress(body, compresslevel=1) if want_gzip else body

        #: Full-page renderer (device cache + self-telemetry).
        self.render_page = lambda: render(False)

        def render_with_version() -> tuple[bytes, int]:
            # Atomic pair: the device page and the version it carries come
            # from one cache read, so gRPC change-detection can't tear.
            dev, version = self.cache.rendered_with_version()
            return dev + self._selfpage.latest(), version

        self.render_with_version = render_with_version
        defaults = type(cfg)()
        replay_items = (
            cfg.guard_replay_max_items
            if cfg.guard_replay_max_items > 0
            else defaults.guard_replay_max_items
        )
        replay_bytes = (
            cfg.guard_replay_max_bytes
            if cfg.guard_replay_max_bytes > 0
            else defaults.guard_replay_max_bytes
        )
        app = _make_app(
            render, self.telemetry, self._health, self.history,
            self._device_health, post_scrape=self._selfpage.poke,
            anomalies=self.anomaly, tracer=self.tracer,
            debug_vars=self._debug_vars, hostcorr=self.hostcorr,
            lifecycle=self.lifecycle,
            replay_max_items=replay_items, replay_max_bytes=replay_bytes,
            negotiated=self.renderer,
        )
        if self.guard is not None:
            # Admission control wraps the whole app; shedding answers
            # before any endpoint code runs.
            app = self.guard.wsgi(app)
        self.server = ExporterServer(app, cfg.addr, cfg.port, guard=self.guard)
        self.grpc_server = None
        if cfg.grpc_serve_port >= 0:  # -1 disables; 0 = ephemeral (tests)
            try:
                from tpumon.exporter.grpc_service import MetricsGrpcServer

                self.grpc_server = MetricsGrpcServer(
                    self.render_with_version, self.cache, cfg.addr,
                    cfg.grpc_serve_port, tracer=self.tracer,
                    guard=self.guard, renderer=self.renderer,
                )
            except Exception as exc:
                # grpcio missing or bind failure must not take down the
                # HTTP scrape plane.
                log.warning("grpc metrics service unavailable: %s", exc)

    def _on_cycle(self) -> None:
        """Post-cycle hook (poller thread): sample the memory watchdog,
        publish the guard gauges, then refresh the self-telemetry render
        so the new state rides the very next scrape."""
        if self.memwatch is not None:
            state = self.memwatch.check()
            self.telemetry.guard_state.set(float(state))
            self.telemetry.guard_rss.set(self.memwatch.last_rss)
        self._selfpage.refresh()

    def _recover_backend(self) -> None:
        """Watchdog hook: a poll cycle is stuck past the hang budget.

        Runs on the watchdog thread. ``interrupt()`` releases injected
        hangs (fault backend); ``reset()`` tears down transport state
        (the gRPC backend closes its channel, failing any in-flight RPC
        so the stuck call raises and the cycle completes). The flags are
        re-rendered immediately so the very next scrape shows the onset.
        """
        self.telemetry.watchdog_recoveries.inc()
        self.telemetry.up.set(0.0)
        self.telemetry.degraded.set(1.0)
        for method in ("interrupt", "reset"):
            fn = getattr(self.backend, method, None)
            if fn is None:
                continue
            try:
                fn()
            except Exception:
                log.exception("backend %s() failed during recovery", method)
        try:
            self._selfpage.refresh()
        except Exception:
            log.exception("self-telemetry refresh failed during recovery")

    def _debug_vars(self) -> dict:
        """The /debug/vars body (expvar analogue): process, config, and
        subsystem occupancy — O(1) in-process reads only, no device
        calls, nothing shared with the scrape path."""
        import dataclasses
        import gc
        import os
        import sys

        stats = self.poller.last_stats
        doc: dict = {
            "now": time.time(),
            "uptime_seconds": time.time() - self._started_at,
            "pid": os.getpid(),
            "python": sys.version.split()[0],
            "backend": self.backend.name,
            "config": dataclasses.asdict(self.cfg),
            "gc": {"counts": gc.get_count(), "enabled": gc.isenabled()},
            "threads": sorted(t.name for t in threading.enumerate()),
            "cache_version": self.cache.rendered_with_version()[1],
            "last_poll": {
                "families": stats.families,
                "points": stats.points,
                "coverage": stats.coverage,
                "backend_errors": stats.backend_errors,
                "parse_errors": stats.parse_errors,
                "degraded": stats.degraded,
                "breaker_open": stats.breaker_open,
                "stale_families": {
                    name: round(age, 3)
                    for name, age in stats.stale_families.items()
                },
            },
        }
        if self.resilience is not None:
            doc["resilience"] = self.resilience.snapshot()
        if self.watchdog is not None:
            doc.setdefault("resilience", {})["watchdog"] = {
                "hang_budget_s": self.watchdog.hang_budget_s,
                "recoveries": self.watchdog.recoveries,
            }
        if self.guard is not None:
            gdoc: dict = {"ingress": self.guard.snapshot()}
            if self.memwatch is not None:
                gdoc["memory"] = self.memwatch.snapshot()
            if self.governor is not None:
                gdoc["cardinality"] = self.governor.snapshot()
            doc["guard"] = gdoc
        if self.tracer is not None:
            doc["trace"] = {
                "slow_cycle_ms": self.tracer.slow_cycle_ms,
                **self.tracer.counts(),
            }
        encode_hits, encode_misses = self.renderer.encoded.stats()
        doc["render"] = {
            **self.cache.render_stats(),
            "formats": list(self.renderer.formats),
            "encode_cache_hits": encode_hits,
            "encode_cache_misses": encode_misses,
        }
        if self.history is not None:
            series, samples = self.history.stats()
            doc["history"] = {
                "series": series,
                "samples": samples,
                "native": self.history.is_native,
            }
        if self.anomaly is not None:
            doc["anomaly"] = self.anomaly.summary()
        if self.hostcorr is not None:
            doc["hostcorr"] = self.hostcorr.snapshot()
        if self.lifecycle is not None:
            doc["lifecycle"] = self.lifecycle.snapshot()
        if self.energy is not None:
            doc["energy"] = self.energy.snapshot()
        # Invariant-analyzer status (tpumon/analysis): operators can see
        # from the running exporter whether the shipped checkout's
        # cross-file discipline was proven, and against how many accepted
        # baseline entries. O(1): the baseline is read once and cached.
        doc["invariants"] = _invariants_vars()
        return doc

    def _device_health(self) -> dict:
        """The /health/devices body: the verdict the poll cycle already
        computed (PollStats.health) — O(1) per request, never touches the
        device backend. The poller primes synchronously at start, so the
        None fallback only covers a request racing construction."""
        health = self.poller.last_stats.health
        if health is None:
            return {"status": "ok", "findings": [], "chips": 0, "coverage": None}
        return health

    def _health(self) -> tuple[bool, str]:
        last = self.telemetry.last_poll._value.get()
        if last == 0:
            return False, "no poll completed yet\n"
        age = time.time() - last
        budget = self.cfg.interval * HEALTH_STALE_INTERVALS
        if age > budget:
            return False, f"poll loop stale: last poll {age:.1f}s ago\n"
        return True, "ok\n"

    def start(self) -> None:
        if self.watchdog is not None:
            self.watchdog.start()
        self.poller.start()
        self.server.start()
        log.info(
            "exporter serving %s/metrics (backend=%s, interval=%.2fs)",
            self.server.url,
            self.backend.name,
            self.cfg.interval,
        )

    def close(self) -> None:
        if self.grpc_server is not None:
            self.grpc_server.close()
        self.server.close()
        # Poller first: a cycle stuck in a device call still gets watchdog
        # recovery while stop() waits on the join.
        self.poller.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.lifecycle is not None:
            self.lifecycle.close()
        self._selfpage.close()
        self.backend.close()


def build_exporter(cfg: Config, backend: Backend | None = None) -> Exporter:
    if backend is None:
        from tpumon.backends import create_backend

        backend = create_backend(cfg)
    return Exporter(cfg, backend)
