"""Poll loop, sample cache, and the cached Prometheus collector.

The design rule distilled from the p99-scrape-latency headline
(SURVEY.md §3.2): **device queries live only in the poll loop; the scrape
path reads an immutable cached snapshot**. The two threads share exactly one
reference, swapped atomically under a lock (SURVEY.md §5.2).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from prometheus_client.core import GaugeMetricFamily
from prometheus_client.metrics_core import Metric

from tpumon.backends.base import Backend, BackendError
from tpumon.config import Config
from tpumon.exporter.telemetry import SelfTelemetry
from tpumon.parsing import parse
from tpumon.schema import coverage, spec_for
from tpumon.trace import trace_span

log = logging.getLogger(__name__)


@dataclass
class PollStats:
    backend_errors: int = 0
    parse_errors: int = 0
    families: int = 0
    points: int = 0
    unmapped: tuple[str, ...] = ()
    coverage: float = 1.0
    #: Node-constant base label keys this cycle (history recording strips
    #: them from series identity).
    base_keys: tuple[str, ...] = ()
    #: ...and their values, so post-cycle consumers (the anomaly engine's
    #: families) can label their own samples without re-querying topology.
    base_vals: tuple[str, ...] = ()
    #: Per-cycle device-health report (the /health/devices body), so the
    #: endpoint serves the poll's verdict instead of re-evaluating.
    health: dict | None = None
    #: Per-cycle parsed snapshot (tpumon.smi shape, coverage included) —
    #: consumers (smi standalone mode, doctor) reuse it instead of
    #: re-walking the families.
    snapshot: dict | None = None
    #: True when this cycle served anything other than fresh-complete
    #: data: stale-but-served families, an open breaker, or a recovered
    #: enumeration outage (tpumon/resilience). Drives tpumon_degraded.
    degraded: bool = False
    #: Queries skipped this cycle because their breaker was open.
    breaker_open: int = 0
    #: family name -> age seconds, for families served from the
    #: last-good cache this cycle (tpumon_family_staleness_seconds).
    stale_families: dict = field(default_factory=dict)


@dataclass
class RenderStats:
    """One publish's delta-render accounting (tpumon_render_* metrics)."""

    #: Families whose cached byte segment was reused unchanged.
    hits: int = 0
    #: Families (re-)rendered this cycle (dirty or new).
    rendered: int = 0
    #: Total families on the page.
    families: int = 0
    #: Whether the incremental path ran (False = full render).
    delta: bool = False


class SampleCache:
    """Atomic snapshot holder shared by the poller and HTTP threads.

    Holds both the family objects (for the registry/debug path) and the
    **pre-rendered text exposition**: rendering happens once per poll
    (1 Hz), so a scrape is a cached-bytes write instead of an O(samples)
    serialization — this is most of the p99 scrape-latency headline.

    With ``delta=True`` (TPUMON_RENDER_DELTA, the default) the render
    itself is incremental: each family's text segment is cached keyed on
    a flattened-sample fingerprint, only changed families re-render, and
    the page is assembled by buffer concatenation (the C fast path in
    ``tpumon/_native/_exposition.c`` when built). Most of a 1 Hz page is
    identical between polls — identity/info families, histogram buckets
    that received no in-range sample, health verdicts — so the per-cycle
    render cost tracks what *changed*, not page size. Byte equivalence
    with the full render is pinned by tests/test_render_delta.py.
    """

    def __init__(self, delta: bool = True) -> None:
        # One lock guards page, snapshot, AND version (the Condition wraps
        # it), so a page can never tear from the version it's labeled with.
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._snapshot: tuple[Metric, ...] = ()  # guarded-by: self._lock, self._cond
        self._rendered: bytes = b""  # guarded-by: self._lock, self._cond
        self._version = 0  # guarded-by: self._lock, self._cond
        self._delta = delta
        #: Per-family segment cache: (name, occurrence) -> (type, help,
        #: samples-copy, rendered bytes) — the first three are the
        #: change fingerprint, the fourth the cached segment. Touched
        #: only by the single publishing thread (the poller / the fleet
        #: collect loop), never by scrape threads — no lock needed.
        self._segments: dict[tuple, tuple] = {}
        #: Which renderer produced the cached segments (the native
        #: extension loads asynchronously; a py→native flip mid-run must
        #: invalidate every segment or the page would mix float styles).
        self._render_gen: object = None
        #: Family names whose flatten failed under the native renderer:
        #: while any of them is on the page, the Python pass owns the
        #: render (publisher thread only, like _segments).
        self._native_blocked: set[str] = set()
        self.last_render = RenderStats()  # guarded-by: self._lock, self._cond
        self.render_hits_total = 0  # guarded-by: self._lock, self._cond
        self.render_rendered_total = 0  # guarded-by: self._lock, self._cond

    def _render_page(self, snap: tuple[Metric, ...]) -> tuple[bytes, RenderStats]:
        """Full or incremental render of one page; publisher thread only."""
        from tpumon import _native

        if not self._delta:
            stats = RenderStats(families=len(snap), delta=False)
            stats.rendered = len(snap)
            return _native.render_families(snap), stats

        ext = _native.load_extension("_exposition")
        if ext is not None and self._native_blocked:
            # A family that resisted native flattening is (or was, last
            # cycle) on the page. Stay on the Python pass — its segment
            # cache keeps earning hits — instead of re-attempting native
            # every cycle, which would clear both caches and pay a
            # doomed partial native render per publish. Retry native
            # only once every blocking family has left the page.
            if self._native_blocked.intersection(f.name for f in snap):
                ext = None
            else:
                self._native_blocked.clear()
        if ext is not None:
            result = self._delta_pass(snap, ext)
            if result is not None:
                return result
            # A family the native renderer can't take appeared: mirror
            # render_families' all-or-nothing choice and render the
            # whole page via the Python renderer, so delta-assembled
            # bytes always match the full path.
        return self._delta_pass(snap, None)  # python pass cannot fail

    def _delta_pass(self, snap, ext):
        """One incremental render with a fixed renderer (native ``ext``
        or the Python fallback). Returns None when a family resists
        native flattening (caller retries with the Python renderer).

        The change test compares the cached cycle's raw sample objects
        against this cycle's (list/namedtuple/dict equality, all C
        loops, zero allocation): ``flatten_family`` — the dominant
        publish cost at high cardinality — runs only for families that
        actually changed. NaN-valued samples compare unequal to
        themselves and simply re-render every cycle: conservative, never
        wrong. Dict equality ignores label-insertion order, which is
        sound because rendering sorts label keys.
        """
        from tpumon import _native

        stats = RenderStats(families=len(snap), delta=True)
        gen = ("native", id(ext)) if ext is not None else ("python",)
        if gen != self._render_gen:
            self._segments.clear()
            self._render_gen = gen
        segments: list[bytes] = []
        new_cache: dict[tuple, tuple] = {}
        occurrence: dict[str, int] = {}
        for fam in snap:
            # Duplicate family names (malformed producer) disambiguate by
            # occurrence index so they cannot alias each other's segment.
            n = occurrence.get(fam.name, 0)
            occurrence[fam.name] = n + 1
            key = (fam.name, n)
            entry = self._segments.get(key)
            if (
                entry is not None
                and entry[0] == fam.type
                and entry[1] == fam.documentation
                and entry[2] == fam.samples
            ):
                segment = entry[3]
                new_cache[key] = entry
                stats.hits += 1
            else:
                if ext is not None:
                    flat = _native.flatten_family(fam)
                    if flat is None:
                        # Exotic family: the page goes Python, and stays
                        # there while this family keeps appearing.
                        self._native_blocked.add(fam.name)
                        return None
                    segment = ext.render([flat])
                else:
                    segment = _native._python_render([fam])
                # A COPY of the sample list: a producer that republishes
                # the same family object after appending/replacing
                # samples must compare unequal, not identical.
                new_cache[key] = (
                    fam.type, fam.documentation, list(fam.samples), segment,
                )
                stats.rendered += 1
            segments.append(segment)
        self._segments = new_cache
        if ext is not None:
            return ext.concat(segments), stats
        return b"".join(segments), stats

    def publish(self, families: list[Metric]) -> RenderStats:
        snap = tuple(families)
        # Child spans of the poller's "publish" stage: the exposition
        # render is the O(changed samples) half, the swap is a lock +
        # notify.
        with trace_span("render"):
            rendered, stats = self._render_page(snap)
        with self._cond:
            self._snapshot = snap
            self._rendered = rendered
            self._version += 1
            self.last_render = stats
            self.render_hits_total += stats.hits
            self.render_rendered_total += stats.rendered
            self._cond.notify_all()
        return stats

    def render_stats(self) -> dict:
        """Cumulative + last-cycle delta-render accounting (/debug/vars,
        bench hit-ratio evidence)."""
        with self._lock:
            last = self.last_render
            hits, rendered = self.render_hits_total, self.render_rendered_total
        total = hits + rendered
        return {
            "delta": self._delta,
            "last_hits": last.hits,
            "last_rendered": last.rendered,
            "families": last.families,
            "hits_total": hits,
            "rendered_total": rendered,
            "hit_ratio": round(hits / total, 4) if total else None,
        }

    def snapshot(self) -> tuple[Metric, ...]:
        with self._lock:
            return self._snapshot

    def snapshot_with_version(self) -> tuple[tuple[Metric, ...], int]:
        """Atomic (snapshot, version) pair — the OpenMetrics response
        cache keys on it, so a body cached for version N is always built
        from version N's families."""
        with self._lock:
            return self._snapshot, self._version

    def rendered(self) -> bytes:
        with self._lock:
            return self._rendered

    def rendered_with_version(self) -> tuple[bytes, int]:
        """Atomic (page, version) pair — change-detection safe."""
        with self._lock:
            return self._rendered, self._version

    def wait_newer(self, version: int, timeout: float) -> int:
        """Block until a publish newer than ``version`` lands (or timeout);
        returns the current version either way."""
        with self._cond:
            self._cond.wait_for(lambda: self._version > version, timeout)
            return self._version


class CachedCollector:
    """Optional adapter for embedding tpumon in an existing registry.

    The standalone exporter does NOT use this — it serves the pre-rendered
    bytes from SampleCache directly. Library users who already run a
    prometheus_client registry can ``registry.register(CachedCollector(
    exporter.cache))`` instead; ``collect()`` still only reads the cache,
    never the device backend (SURVEY.md §3.2).
    """

    def __init__(self, cache: SampleCache) -> None:
        self._cache = cache

    def collect(self):
        return self._cache.snapshot()


def topology_families(topo) -> list[Metric]:
    """Identity families for a topology — shared by exporter and sidecar."""
    base = topo.base_labels()
    return _topology_families(topo, tuple(base), tuple(base.values()))


def _topology_families(topo, base_keys, base_vals) -> list[Metric]:
    count = GaugeMetricFamily(
        "accelerator_device_count",
        "Number of accelerator chips visible to this exporter "
        "(0 on CPU-only nodes — BASELINE config 1).",
        labels=base_keys,
    )
    count.add_metric(base_vals, topo.num_chips)

    cores = GaugeMetricFamily(
        "accelerator_core_count",
        "Number of accelerator compute cores visible to this exporter.",
        labels=base_keys,
    )
    cores.add_metric(base_vals, topo.num_cores)

    hosts = GaugeMetricFamily(
        "accelerator_slice_host_count",
        "Number of hosts in this accelerator slice.",
        labels=base_keys,
    )
    hosts.add_metric(base_vals, topo.num_hosts)

    info = GaugeMetricFamily(
        "accelerator_info",
        "Per-chip identity: slice/host/chip plus physical coords — the "
        "TPU-native replacement for PCIe-BDF identity (SURVEY.md §3.4).",
        labels=base_keys + ("chip", "coords", "device_id", "cores"),
    )
    for chip in topo.chips:
        coords = ",".join(str(c) for c in chip.coords) if chip.coords else ""
        info.add_metric(
            base_vals
            + (str(chip.index), coords, chip.device_id, str(chip.num_cores)),
            1.0,
        )
    return [count, cores, hosts, info]


def _serve_stale(resilience, name: str, families: list, stats: PollStats) -> None:
    """Append the last-good family for ``name`` (if fresh enough) with
    staleness bookkeeping — the stale-but-served degradation path."""
    if resilience is None:
        return
    entry = resilience.stale(name)
    if entry is None:
        return
    fam, fam_name, age = entry
    families.append(fam)
    stats.stale_families[fam_name] = age
    stats.degraded = True


def build_families(
    backend: Backend, cfg: Config, attribution=None, histograms=None,
    resilience=None, watchdog=None,
) -> tuple[list[Metric], PollStats]:
    """One poll cycle: query every enabled metric, parse, build families.

    Runs only on the poller thread. Every failure mode degrades to a
    dropped sample plus a counter increment (SURVEY.md §5.3).
    ``histograms`` (a PollHistograms) accumulates the 1 Hz utilization
    distribution across polls — state outlives this call. ``resilience``
    (a tpumon.resilience.PollResilience) adds per-query circuit breakers
    and stale-but-served degradation: failed/refused queries serve the
    last good family with freshness metadata instead of going absent.
    """
    stats = PollStats()

    def beat() -> None:
        # Per-device-call progress heartbeat: a cycle that is slow
        # because calls keep completing (at their bounded deadlines)
        # must not read as a hang — only a single stuck call may let
        # the watchdog budget elapse without a beat.
        if watchdog is not None:
            watchdog.beat()

    with trace_span("topology"):
        topo = backend.topology()
    beat()
    base = topo.base_labels()
    base_keys = tuple(base)
    stats.base_keys = base_keys
    base_vals = tuple(base.values())
    stats.base_vals = base_vals
    families: list[Metric] = _topology_families(topo, base_keys, base_vals)

    list_failed = False
    supported: tuple[str, ...] = ()
    list_br = (
        resilience.breakers.get("list_metrics")
        if resilience is not None
        else None
    )
    if list_br is not None and not list_br.allow():
        # Open breaker: the enumeration outage is established — don't pay
        # a device call per poll to reconfirm it (probe schedule applies).
        list_failed = True
        stats.breaker_open += 1
        stats.degraded = True
    else:
        try:
            with trace_span("list_metrics"):
                supported = tuple(backend.list_metrics())
        except Exception as exc:
            log.warning("list_metrics failed: %s", exc)
            stats.backend_errors += 1
            list_failed = True
            if list_br is not None:
                list_br.record(False)
        else:
            if list_br is not None:
                list_br.record(True)
            if resilience is not None:
                resilience.store_supported(supported)
        beat()
    if list_failed and resilience is not None:
        # Keep sampling from the last good enumeration so data flows
        # through the outage; coverage still reads 0.0 below, so the
        # enumeration alert fires exactly while this is happening.
        entry = resilience.stale_supported()
        if entry is not None:
            supported = entry[0]
            stats.degraded = True

    # A failed enumeration is 0% coverage, not a vacuous 100%: an alert on
    # the coverage gauge must fire during exactly this outage.
    stats.coverage = 0.0 if list_failed else coverage(supported)
    unmapped = []

    for name in supported:
        if not cfg.metric_enabled(name):
            continue
        if name == "ici_link_health" and not cfg.ici_per_link:
            continue  # skip before the device query, not after
        spec = spec_for(name)
        if spec is None:
            unmapped.append(name)
            continue
        br = (
            resilience.breakers.get(f"sample:{name}")
            if resilience is not None
            else None
        )
        if br is not None and not br.allow():
            stats.breaker_open += 1
            stats.degraded = True
            _serve_stale(resilience, name, families, stats)
            continue
        try:
            with trace_span(f"query:{name}"):
                raw = backend.sample(name)
        except BackendError as exc:
            log.debug("sample(%s) failed: %s", name, exc)
            stats.backend_errors += 1
            if br is not None:
                br.record(False)
            beat()
            _serve_stale(resilience, name, families, stats)
            continue
        except Exception as exc:  # never let a device bug kill the poller
            log.warning("sample(%s) raised unexpectedly: %s", name, exc)
            stats.backend_errors += 1
            if br is not None:
                br.record(False)
            beat()
            _serve_stale(resilience, name, families, stats)
            continue
        beat()
        if br is not None:
            br.record(True)

        with trace_span(f"parse:{name}"):
            result = parse(raw, spec)
            stats.parse_errors += result.errors
            if result.empty:
                # Runtime-detached / no data: family absent, not zero
                # (SURVEY.md §2.2 caveat). Absence is the truth now —
                # drop the last-good entry so stale serving can never
                # mask a detach.
                if resilience is not None:
                    resilience.forget(name)
                continue
            if histograms is not None:
                # Cumulative distribution of the 1 Hz series (BASELINE
                # config 3 "histograms"); no-op for non-distribution
                # sources.
                histograms.observe(name, result.points)

            fam = GaugeMetricFamily(
                spec.family, spec.help, labels=base_keys + spec.label_keys
            )
            for point in result.points:
                fam.add_metric(
                    base_vals
                    + tuple(point.labels.get(k, "") for k in spec.label_keys),
                    point.value,
                )
            families.append(fam)
            if resilience is not None:
                resilience.store(name, fam)
            stats.points += len(result.points)

    if histograms is not None:
        with trace_span("histograms"):
            families.extend(histograms.families(base_keys, base_vals))

    # Per-core state via the tpuz surface (SURVEY.md §2.2) — optional on the
    # protocol; degrades to absent when the runtime is down.
    core_states = getattr(backend, "core_states", None)
    if core_states is not None:
        try:
            with trace_span("core_states"):
                states = core_states()
        except Exception as exc:
            log.debug("core_states failed: %s", exc)
            states = {}
        if states:
            fam = GaugeMetricFamily(
                "accelerator_core_state",
                "Per-core runtime state reported by the device monitoring "
                "service (value is 1; state in the label).",
                labels=base_keys + ("core", "state"),
            )
            for core, state in states.items():
                fam.add_metric(base_vals + (str(core), str(state)), 1.0)
            families.append(fam)

    # Transport state of the runtime monitoring watch streams (grpc
    # backend only): scrapeable so "pushes stopped, polling carries it"
    # is a dashboard fact, not a doctor-only one.
    watch_states_fn = getattr(backend, "watch_states", None)
    if watch_states_fn is not None:
        try:
            watch_states = watch_states_fn()
        except Exception as exc:
            log.debug("watch_states failed: %s", exc)
            watch_states = {}
        if watch_states:
            from collections import Counter as _Counter

            from tpumon.families import IDENTITY_FAMILIES

            help_text, extra = IDENTITY_FAMILIES[
                "accelerator_monitor_watch_streams"
            ]
            fam = GaugeMetricFamily(
                "accelerator_monitor_watch_streams",
                help_text,
                labels=base_keys + extra,
            )
            for state, n in sorted(
                _Counter(watch_states.values()).items()
            ):
                fam.add_metric(base_vals + (state,), float(n))
            families.append(fam)

    # Host context gauges (CPU/mem/load/net): the host-side-telemetry
    # companion signals for diagnosing accelerator symptoms.
    if cfg.host_metrics:
        from tpumon.exporter.host import host_families

        with trace_span("host_metrics"):
            families.extend(host_families(base_keys, base_vals))

    # Derived health verdicts as scrapeable families (dcgmi-health
    # analogue): alerts can fire on the verdict without re-encoding the
    # thresholds in PromQL. Same evaluator as /health/devices and doctor;
    # names/help/labels come from the HEALTH_FAMILIES registry so docs and
    # exposition cannot drift.
    from collections import Counter

    from tpumon import health as health_mod
    from tpumon.families import HEALTH_FAMILIES
    from tpumon.smi import snapshot_from_families

    with trace_span("health"):
        snap = snapshot_from_families(families)
        snap["coverage"] = stats.coverage
        findings = health_mod.evaluate(snap)
        stats.health = health_mod.report(snap, findings)
        stats.snapshot = snap

        status_help, status_labels = HEALTH_FAMILIES[
            "accelerator_health_status"
        ]
        status = GaugeMetricFamily(
            "accelerator_health_status",
            status_help,
            labels=base_keys + status_labels,
        )
        status.add_metric(
            base_vals, float(health_mod.severity_value(stats.health["status"]))
        )
        families.append(status)
        if findings:
            counts = Counter((f.severity, f.code) for f in findings)
            find_help, find_labels = HEALTH_FAMILIES[
                "accelerator_health_findings"
            ]
            fam = GaugeMetricFamily(
                "accelerator_health_findings",
                find_help,
                labels=base_keys + find_labels,
            )
            for (sev, code), n in sorted(counts.items()):
                fam.add_metric(base_vals + (sev, code), float(n))
            families.append(fam)

    # Chip→pod attribution (kubelet pod-resources API, SURVEY §7(d)):
    # optional, never fatal, absent off-cluster.
    if attribution is not None:
        try:
            with trace_span("attribution"):
                families.extend(
                    attribution.families(base_keys, base_vals, topo)
                )
        except Exception as exc:
            log.debug("pod attribution failed: %s", exc)

    stats.unmapped = tuple(unmapped)
    stats.families = len(families)
    if unmapped:
        log.debug("unmapped device metrics (coverage gap): %s", unmapped)
    return families, stats


class Poller:
    """The 1 Hz poll thread (SURVEY.md §3.1-3.2)."""

    def __init__(
        self,
        backend: Backend,
        cfg: Config,
        cache: SampleCache,
        telemetry: SelfTelemetry,
        attribution=None,
        history=None,
        histograms=None,
        anomaly=None,
        tracer=None,
        resilience=None,
        watchdog=None,
        governor=None,
        hostcorr=None,
        lifecycle=None,
        energy=None,
    ) -> None:
        self._backend = backend
        self._cfg = cfg
        self._cache = cache
        self._telemetry = telemetry
        self._attribution = attribution
        self._history = history
        self._histograms = histograms
        self._anomaly = anomaly
        self._tracer = tracer
        self._resilience = resilience
        self._watchdog = watchdog
        self._governor = governor
        self._hostcorr = hostcorr
        self._lifecycle = lifecycle
        self._energy = energy
        #: Staleness-gauge label reconciliation (tpumon/resilience).
        self._stale_labeled: set[str] = set()
        #: Last-seen backend retry counters (delta-fed into telemetry).
        self._retry_seen: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tpumon-poller", daemon=True
        )
        self.last_stats: PollStats = PollStats()
        #: Optional post-cycle hook, called after the telemetry gauges are
        #: updated (the exporter refreshes its self-telemetry render here).
        self.on_cycle = None

    def poll_once(self) -> PollStats:
        # The watchdog heartbeat brackets the whole cycle: a device call
        # stuck past the hang budget triggers backend interrupt/teardown
        # from the watchdog thread, which makes the stuck call raise and
        # the cycle complete as a counted backend error.
        if self._watchdog is not None:
            self._watchdog.cycle_started()
        try:
            # The traced path wraps the cycle in a tpumon.trace span tree
            # (recorded on this thread, rendered lazily on /debug reads);
            # tracing disabled runs the identical pipeline bare.
            if self._tracer is None:
                return self._poll_cycle()
            with self._tracer.cycle() as cycle:
                stats = self._poll_cycle()
                if cycle is not None:
                    cycle.set_stats(stats)
                return stats
        finally:
            if self._watchdog is not None:
                self._watchdog.cycle_finished()

    def _poll_cycle(self) -> PollStats:
        t0 = time.monotonic()
        # Backends with a time dimension (the fake) advance one step per
        # poll cycle so live data evolves; real backends don't define this.
        advance = getattr(self._backend, "advance", None)
        if advance is not None:
            with trace_span("advance"):
                advance()
        with trace_span("build_families"):
            families, stats = build_families(
                self._backend, self._cfg, self._attribution,
                self._histograms, resilience=self._resilience,
                watchdog=self._watchdog,
            )
        now = time.time()
        if self._lifecycle is not None:
            # Workload-lifecycle plane (tpumon/lifecycle): probe the
            # workload step feeds (localhost HTTP — zero device queries),
            # classify preemption/resize/restore against THIS cycle's
            # device snapshot, and inject the suppression list + step
            # telemetry the anomaly pass consumes. Runs FIRST among the
            # snapshot-bus planes: the hostcorr straggler judge reads
            # this cycle's per-feed step telemetry (step-skew evidence)
            # and the energy plane reads the step/token rates, so both
            # need the lifecycle block already injected. Before the
            # governor/history/anomaly so tpu_lifecycle_* series ride
            # the budget, the 1 Hz flight recorder, and the same page.
            with trace_span("lifecycle") as sp:
                try:
                    families.extend(self._lifecycle.cycle(now, stats))
                except Exception:
                    log.exception("lifecycle plane failed")
                    if sp is not None:
                        sp.status = "error"
                    self._telemetry.poll_stage_errors.labels(
                        stage="lifecycle"
                    ).inc()
        if self._hostcorr is not None:
            # Host-correlation plane (tpumon/hostcorr): procfs/cgroupfs
            # sampling time-aligned with THIS cycle's device snapshot —
            # zero device queries. Runs after lifecycle (its straggler
            # judge consumes the injected step telemetry), before the
            # governor (its per-pod series ride the same cardinality
            # budget), before history (so tpu_hostcorr_*/tpu_straggler_*
            # series are in the 1 Hz flight recorder), and before
            # anomaly (the cross-signal detectors read the hostcorr
            # block it injects into stats.snapshot).
            with trace_span("hostcorr") as sp:
                try:
                    families.extend(self._hostcorr.cycle(now, stats))
                except Exception:
                    log.exception("host correlation failed")
                    if sp is not None:
                        sp.status = "error"
                    self._telemetry.poll_stage_errors.labels(
                        stage="hostcorr"
                    ).inc()
        if self._energy is not None:
            # Energy/cost plane (tpumon/energy): power where the device
            # library exposed it this cycle (already sampled by
            # build_families — zero queries added here), duty×TDP model
            # everywhere else; joules integration, pod-energy split,
            # and the tokens-per-joule join against the lifecycle block
            # injected above. Before the governor/history/anomaly so
            # the tpu_energy_*/tpu_step_* efficiency series ride the
            # budget, the flight recorder, and the same page — and so
            # the efficiency_regression detector sees this cycle's
            # tokens/J in the same anomaly pass.
            with trace_span("energy") as sp:
                try:
                    families.extend(self._energy.cycle(now, stats))
                except Exception:
                    log.exception("energy plane failed")
                    if sp is not None:
                        sp.status = "error"
                    self._telemetry.poll_stage_errors.labels(
                        stage="energy"
                    ).inc()
        if self._governor is not None:
            # Per-family cardinality budget (tpumon/guard/cardinality):
            # runs BEFORE history/anomaly/publish so an exploding family
            # is bounded everywhere downstream, not just on the page.
            with trace_span("guard"):
                self._governor.govern(families, stats.base_keys)
        if self._history is not None:
            # Flight recorder (DCGM field-cache analogue): keep the 1 Hz
            # series Prometheus's 15-60 s scrape interval aliases away.
            # Recorded BEFORE the anomaly pass so an event onsetting this
            # cycle can extract a window that includes this cycle's sample.
            with trace_span("history_record") as sp:
                try:
                    self._history.record_families(
                        now, families, stats.base_keys
                    )
                except Exception:
                    log.exception("history record failed")
                    if sp is not None:
                        sp.status = "error"
                    self._telemetry.poll_stage_errors.labels(
                        stage="history_record"
                    ).inc()
        if self._anomaly is not None:
            # Streaming detection over the snapshot this cycle already
            # parsed (tpumon.anomaly): zero extra device queries, and the
            # tpu_anomaly_* families ride the same published page.
            with trace_span("anomaly") as sp:
                try:
                    families.extend(self._anomaly.cycle(now, stats))
                except Exception:
                    log.exception("anomaly detection failed")
                    if sp is not None:
                        sp.status = "error"
                    self._telemetry.poll_stage_errors.labels(
                        stage="anomaly"
                    ).inc()
        with trace_span("publish"):
            render_stats = self._cache.publish(families)
        elapsed = time.monotonic() - t0

        t = self._telemetry
        # Delta-render accounting (tpumon/exporter/encodings.py plane):
        # cumulative segment-cache hits + how much of this cycle's page
        # actually re-rendered.
        if render_stats.hits:
            t.render_cache_hits.inc(render_stats.hits)
        t.render_invalidated.set(render_stats.rendered)
        t.poll_duration.observe(elapsed)
        if stats.backend_errors:
            t.poll_errors.labels(kind="backend").inc(stats.backend_errors)
        if stats.parse_errors:
            t.poll_errors.labels(kind="parse").inc(stats.parse_errors)
        t.polls.inc()
        t.last_poll.set(time.time())
        t.poll_lag.set(max(0.0, elapsed - self._cfg.interval))
        t.coverage.set(stats.coverage)
        self._update_resilience_telemetry(stats)
        self.last_stats = stats
        if self.on_cycle is not None:
            self.on_cycle()
        return stats

    def _update_resilience_telemetry(self, stats: PollStats) -> None:
        """Post-cycle freshness/breaker/retry gauges (tpumon/resilience):
        the degradation the page carries must be flagged on the same page."""
        t = self._telemetry
        t.up.set(1.0)
        t.degraded.set(1.0 if stats.degraded else 0.0)
        # Staleness gauge: one series per stale-served family, removed
        # again the cycle the family turns fresh (absent = fresh).
        stale = stats.stale_families
        for fam_name, age in stale.items():
            t.family_staleness.labels(family=fam_name).set(age)
        for fam_name in self._stale_labeled - set(stale):
            try:
                t.family_staleness.remove(fam_name)
            except KeyError:
                pass
        self._stale_labeled = set(stale)
        if self._resilience is not None:
            from tpumon.resilience.breaker import STATE_VALUES

            for key, state in self._resilience.breakers.states().items():
                t.breaker_state.labels(query=key).set(STATE_VALUES[state])
        # Retry counts accumulate inside the backends (transport-level
        # bounded retries); fold the deltas into the shared counter.
        rc_fn = getattr(self._backend, "retry_counts", None)
        if rc_fn is not None:
            try:
                counts = rc_fn()
            except Exception as exc:
                log.debug("backend retry_counts() failed: %s", exc)
                counts = {}
            for call, n in counts.items():
                delta = n - self._retry_seen.get(call, 0)
                if delta > 0:
                    t.retries.labels(call=call).inc(delta)
                    self._retry_seen[call] = n

    def start(self) -> None:
        # Prime the cache synchronously so the first scrape is never empty.
        self.poll_once()
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        interval = self._cfg.interval
        next_tick = time.monotonic() + interval
        while not self._stop.is_set():
            delay = next_tick - time.monotonic()
            if delay > 0 and self._stop.wait(timeout=delay):
                break
            next_tick += interval
            try:
                self.poll_once()
            except Exception:
                # Last-ditch guard: the poller thread must never die.
                log.exception("poll cycle failed")
                self._telemetry.poll_errors.labels(kind="backend").inc()
                # A wholesale-failed cycle published nothing fresh.
                self._telemetry.up.set(0.0)
                self._telemetry.degraded.set(1.0)
                if self.on_cycle is not None:
                    # poll_once died before its own on_cycle: re-render
                    # anyway so the error counter is scrapeable now, not
                    # one scrape-interval late.
                    try:
                        self.on_cycle()
                    except Exception:
                        log.exception("on_cycle hook failed")
            # If we overran badly, resynchronize rather than burst-poll.
            now = time.monotonic()
            if next_tick < now:
                next_tick = now + interval
