"""The chaos fleet: one live 2-shard aggregator pair over fleetsim,
driven by a :class:`~tpumon.chaos.schedule.FaultSchedule`, observed by
an :class:`~tpumon.chaos.invariants.InvariantChecker`.

One :func:`run_schedule` call is one experiment: spawn a fleetsim
subprocess (N node identities, one process), build two peer-probing
aggregator shards in-process (spool + ledger + actuation enabled, so
every surface the invariants cover exists), warm up until both shards
see their full target set, then walk wall-clock time applying schedule
steps at their offsets while sampling every surface (/metrics, /fleet,
/hints, the External Metrics adapter, /ledger) through the checker.

The engine maintains a small mirror of fleetsim's node state (live /
dead counts) because the control protocol acks one line per victim —
the mirror predicts exactly how many ack lines each command produces,
which is what makes arbitrary generated or minimized schedules safe to
drive over the same stdin protocol the hand-written soaks use.

Kills are no longer absorbing (fleetsim ``revive``), shard 1 can die
and warm-restart from its spool, and ENOSPC/EIO inject into the spools
via their ``inject_errno`` test hook — the full fault surface of the
grammar, against entirely real tiers.
"""

from __future__ import annotations

import errno as errno_mod
import http.client
import json
import logging
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse

from tpumon.chaos.invariants import InvariantChecker, SurfaceSample
from tpumon.chaos.schedule import SERVE_PROFILES, SIM_OPS, FaultSchedule

log = logging.getLogger(__name__)

#: Every Nth sample tick also queries the ledger surfaces (goodput
#: view, a range query, and a deliberately malformed query) — they cost
#: a JSON encode of the whole store, so not every 300 ms.
LEDGER_SAMPLE_EVERY = 3

#: Post-schedule settle before the final sample round: recovery-shaped
#: state (heals, restarts) gets at least this long to land.
SETTLE_S = 1.5

EM_PATH = (
    "/apis/external.metrics.k8s.io/v1beta1/namespaces/default/"
    "tpumon_serve_queue_depth"
)


class ChaosRunError(RuntimeError):
    """The experiment itself failed (warmup, sim death) — distinct from
    an invariant violation, which is a RESULT."""


#: Ports handed out this process-life. Concurrent trials (chaos-search
#: --chaos-jobs > 1) each probe for free ports BEFORE binding their
#: shards; without the claim set two trials can race to the same port
#: and one dies on EADDRINUSE at fleet.start().
_CLAIMED_PORTS: set[int] = set()
_CLAIMED_LOCK = threading.Lock()


def _free_port() -> int:
    for _ in range(64):
        with socket.socket() as sock:
            sock.bind(("127.0.0.1", 0))
            port = sock.getsockname()[1]
        with _CLAIMED_LOCK:
            if port not in _CLAIMED_PORTS:
                _CLAIMED_PORTS.add(port)
                return port
    raise ChaosRunError("could not claim a free port in 64 probes")


def _spawn_fleetsim(nodes: int, node_interval: float):
    """A fleetsim subprocess (own GIL — simulation work never shares
    the shards' interpreter). Returns (proc, urls)."""
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "tpumon.tools.fleetsim",
            "--nodes", str(nodes), "--node-interval", str(node_interval),
        ],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    line = proc.stdout.readline()  # deadline: fleetsim prints PORTS immediately on startup or dies (the driver's outer timeout bounds the run)
    if not line.startswith("PORTS "):
        proc.kill()
        raise ChaosRunError(f"fleetsim failed to start: {line!r}")
    ports = [int(p) for p in line.split()[1:]]
    return proc, [f"http://127.0.0.1:{port}" for port in ports]


class _Fleet:
    """The live experiment: sim subprocess + two shards + HTTP plumbing."""

    def __init__(
        self, schedule: FaultSchedule, interval: float,
        node_interval: float,
    ) -> None:
        from tpumon.fleet.config import FleetConfig

        self.schedule = schedule
        self.interval = interval
        self.node_interval = node_interval
        self.ports = [_free_port(), _free_port()]
        self.peers = ",".join(f"http://127.0.0.1:{p}" for p in self.ports)
        self.spools = [
            tempfile.mkdtemp(prefix=f"tpumon-chaos-spool-{i}-")
            for i in range(2)
        ]
        self.ledger_spools = [
            tempfile.mkdtemp(prefix=f"tpumon-chaos-ledger-{i}-")
            for i in range(2)
        ]
        self.takeover_s = max(2.0, 4 * interval)
        #: How long ownership may churn (takeover then hand-back) after
        #: a shard-lifecycle fault: detection deadline + a few
        #: membership/collect cycles for the epoch rebase to publish.
        self.epoch_settle_s = self.takeover_s + 4 * interval + 2.0
        self.sim_proc = None
        self.urls: list[str] = []
        self.shards: list = [None, None]
        self.conns: dict[int, http.client.HTTPConnection] = {}
        self.sim_log: list[str] = []
        #: Engine-side mirror of fleetsim's node state: predicts the
        #: per-command ack line count (one line per victim).
        self.live = schedule.nodes
        self.dead = 0
        self._cfg_cls = FleetConfig

    def shard_cfg(self, index: int):
        return self._cfg_cls(
            port=self.ports[index], addr="127.0.0.1",
            targets=",".join(self.urls),
            shard_index=index, shard_count=2,
            interval=self.interval,
            stale_s=max(2.0, 3.0 * self.interval),
            evict_s=max(self.schedule.duration_s * 4, 120.0),
            peers=self.peers,
            probe_interval=max(0.25, self.takeover_s / 4.0),
            takeover_s=self.takeover_s,
            spool_dir=self.spools[index],
            spool_every_s=self.interval,
            ledger_spool_dir=self.ledger_spools[index],
            ledger_spool_every_s=self.interval,
            poll_backoff_max_s=2.0,
            # Hint-band decay is designed behavior that the do-no-harm
            # style checks would misread mid-run (same stance as the
            # actuate-chaos soak).
            hint_decay_s=max(self.schedule.duration_s * 4, 300.0),
            history_window=0.0,
        )

    def start(self) -> None:
        from tpumon.fleet.server import build_aggregator

        self.sim_proc, self.urls = _spawn_fleetsim(
            self.schedule.nodes, self.node_interval
        )
        self.sim_cmd("serve " + SERVE_PROFILES["calm"], 1)
        for i in range(2):
            self.shards[i] = build_aggregator(self.shard_cfg(i))
            self.shards[i].start()
        self._build_aggregator = build_aggregator

    def warmup(self) -> None:
        deadline = time.time() + max(30.0, 2.0 * self.schedule.nodes)
        while time.time() < deadline:
            docs = [self.get_json(i, "/fleet")[1] for i in range(2)]
            if all(
                d is not None
                and d.get("fleet", {}).get("hosts", {}).get("up", 0)
                >= len(self.shards[i].targets)
                for i, d in enumerate(docs)
            ):
                return
            time.sleep(0.25)
        raise ChaosRunError(
            "chaos fleet warmup timed out: shards never saw their full "
            "target set"
        )

    # -- fault application -------------------------------------------------

    def sim_cmd(self, command: str, expect_lines: int) -> None:
        self.sim_proc.stdin.write(command + "\n")
        self.sim_proc.stdin.flush()
        for _ in range(expect_lines):
            line = self.sim_proc.stdout.readline()  # deadline: fleetsim acks every command immediately or died (the driver's outer timeout bounds the run)
            if not line:
                self.sim_log.append(f"{command}: sim died mid-ack")
                return
            self.sim_log.append(line.strip())

    def _sim_step(self, op: str, args: dict) -> None:
        n = int(args.get("n", 0))
        if op == "kill":
            victims = min(n, self.live)
            self.sim_cmd(f"kill {n}", victims)
            self.live -= victims
            self.dead += victims
        elif op == "revive":
            revived = min(n, self.dead)
            self.sim_cmd(f"revive {n}", max(1, revived))
            self.dead -= revived
            self.live += revived
        elif op in ("partition", "corrupt", "flap"):
            self.sim_cmd(f"{op} {n}", min(n, self.live))
        elif op == "slow":
            self.sim_cmd(
                f"slow {n} {args['ms']:g}", min(n, self.live)
            )
        elif op == "creep":
            self.sim_cmd(
                f"creep {n} {args['ms']:g} {args.get('ramp_s', 10.0):g}",
                min(n, self.live),
            )
        elif op == "skew":
            self.sim_cmd(f"skew {n} {args['s']:g}", min(n, self.live))
        elif op == "churn":
            self.sim_cmd(f"churn {args['f']:g}", 1)
        elif op == "serve":
            self.sim_cmd(
                "serve " + SERVE_PROFILES[args.get("profile", "calm")], 1
            )
        elif op == "faults":
            self.sim_cmd(f"faults {args['spec']}", 1)
        elif op == "heal":
            self.sim_cmd("heal", 1)
        else:
            raise ChaosRunError(f"unknown sim op {op!r}")

    def apply(
        self, op: str, args: dict, checker: InvariantChecker,
        t: float = 0.0,
    ) -> None:
        if op in SIM_OPS:
            self._sim_step(op, args)
            return
        if op == "shard_kill":
            if self.shards[1] is not None:
                self.shards[1].close()
                self.shards[1] = None
                self.conns.pop(1, None)
                checker.reset_shard(1)
                checker.note_ownership_disruption(t, self.epoch_settle_s)
            return
        if op == "shard_restart":
            if self.shards[1] is None:
                self.shards[1] = self._build_aggregator(self.shard_cfg(1))
                self.shards[1].start()
                self.conns.pop(1, None)
                # The hand-back that follows legitimately LOWERS the
                # survivor's per-scope epoch maxima (adopted members
                # leave its claim) — give the checker the churn window.
                checker.note_ownership_disruption(t, self.epoch_settle_s)
            return
        if op in ("spool_enospc", "spool_eio"):
            code = (
                errno_mod.ENOSPC if op == "spool_enospc" else errno_mod.EIO
            )
            shard = self.shards[int(args.get("shard", 0)) % 2]
            if shard is not None:
                if shard.spool is not None:
                    shard.spool.inject_errno = code
                if shard.ledger is not None and shard.ledger.spool is not None:
                    shard.ledger.spool.inject_errno = code
            return
        if op == "spool_heal":
            for shard in self.shards:
                if shard is None:
                    continue
                if shard.spool is not None:
                    shard.spool.inject_errno = None
                if shard.ledger is not None and shard.ledger.spool is not None:
                    shard.ledger.spool.inject_errno = None
            return
        if op == "query_burst":
            # Sampling already queries every surface; the burst exists
            # to hammer the ledger with a spread of valid and malformed
            # queries back to back (the 200-or-400-never-5xx predicate
            # gets its evidence from the recorded statuses).
            return
        raise ChaosRunError(f"unknown op {op!r}")

    # -- surface access ----------------------------------------------------

    def get(self, index: int, path: str) -> tuple[int | None, bytes | None]:
        if self.shards[index] is None:
            return None, None
        conn = self.conns.get(index)
        if conn is None:
            conn = self.conns[index] = http.client.HTTPConnection(
                "127.0.0.1", self.ports[index], timeout=10
            )
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except (OSError, http.client.HTTPException):
            conn.close()
            self.conns.pop(index, None)
            return None, None

    def get_json(self, index: int, path: str) -> tuple[int | None, dict | None]:
        status, body = self.get(index, path)
        if body is None:
            return status, None
        try:
            return status, json.loads(body)
        except ValueError:
            return status, None

    def em_items(self, index: int, selector: str = "") -> list | None:
        path = EM_PATH
        if selector:
            path += "?labelSelector=" + urllib.parse.quote(selector)
        _status, doc = self.get_json(index, path)
        if doc is None:
            return None
        items = doc.get("items")
        return items if isinstance(items, list) else []

    def ledger_queries(
        self, index: int, t0: float, burst: int = 0
    ) -> tuple[list, dict | None]:
        """(recorded (desc, status) pairs, goodput doc) for one shard:
        the standing valid queries, the standing malformed one, plus
        ``burst`` extra alternating valid/hostile queries."""
        queries = [
            (
                "goodput view",
                "/ledger?view=goodput",
            ),
            (
                "range query",
                "/ledger?family=tpu_fleet_duty_cycle_percent&scope=fleet"
                f"&start={t0:.3f}&end={time.time():.3f}",
            ),
            (
                "malformed range (400 expected)",
                "/ledger?family=tpu_fleet_duty_cycle_percent&start=never",
            ),
        ]
        for k in range(burst):
            if k % 2 == 0:
                queries.append((
                    f"burst valid {k}",
                    "/ledger?family=tpu_fleet_chips&scope=fleet"
                    f"&start={t0:.3f}&end={time.time():.3f}",
                ))
            else:
                queries.append((
                    f"burst malformed {k} (400 expected)",
                    f"/ledger?view=bogus-{k}",
                ))
        recorded: list = []
        goodput_doc = None
        for desc, path in queries:
            status, body = self.get(index, path)
            if status is None:
                continue  # dead shard: absence, not an answer
            recorded.append((desc, status))
            if desc == "goodput view" and status == 200 and body:
                try:
                    goodput_doc = json.loads(body)
                except ValueError:
                    goodput_doc = None
        return recorded, goodput_doc

    def close(self) -> None:
        for conn in self.conns.values():
            conn.close()
        self.conns.clear()
        for i, shard in enumerate(self.shards):
            if shard is not None:
                try:
                    shard.close()
                except Exception:
                    log.exception("chaos shard %d close failed", i)
                self.shards[i] = None
        if self.sim_proc is not None:
            try:
                self.sim_proc.terminate()
                self.sim_proc.wait(timeout=5)
            except (OSError, subprocess.TimeoutExpired):
                self.sim_proc.kill()
        for d in self.spools + self.ledger_spools:
            shutil.rmtree(d, ignore_errors=True)


def run_schedule(
    schedule: FaultSchedule,
    interval: float = 0.5,
    node_interval: float | None = None,
    sample_every: float = 0.35,
    checker: InvariantChecker | None = None,
) -> dict:
    """One experiment: the schedule against a live fleet, every surface
    through the checker. Returns the run record (violations included);
    raises :class:`ChaosRunError` only when the experiment itself could
    not run."""
    checker = checker if checker is not None else InvariantChecker()
    fleet = _Fleet(
        schedule, interval,
        node_interval if node_interval is not None else interval,
    )
    applied: list[dict] = []
    pending_burst = 0
    sample_no = 0
    try:
        fleet.start()
        fleet.warmup()
        t0 = time.time()
        step_iter = iter(sorted(schedule.steps, key=lambda s: s.at))
        next_step = next(step_iter, None)
        deadline = t0 + schedule.duration_s
        next_sample = t0
        while True:
            now = time.time()
            if now >= deadline and next_step is None:
                break
            t = now - t0
            while next_step is not None and t >= next_step.at:
                fleet.apply(next_step.op, next_step.args, checker, t)
                if next_step.op == "query_burst":
                    pending_burst = int(next_step.args.get("n", 10))
                applied.append(
                    {"t_s": round(t, 2), **next_step.to_doc()}
                )
                next_step = next(step_iter, None)
            if now >= deadline:
                break
            sample_no += 1
            _sample_round(
                fleet, checker, t, t0, sample_no, pending_burst
            )
            pending_burst = 0
            next_sample += sample_every
            time.sleep(max(0.0, next_sample - time.time()))
        # Settle, then one final full round including the ledger.
        time.sleep(SETTLE_S)
        _sample_round(
            fleet, checker, time.time() - t0, t0,
            LEDGER_SAMPLE_EVERY, 0,
        )
    finally:
        fleet.close()
    summary = checker.summary()
    return {
        "schedule": schedule.to_doc(),
        "interval_s": interval,
        "applied": applied,
        "checker": summary,
        "violations": [v.to_doc() for v in checker.violations],
        "sim_log_tail": fleet.sim_log[-20:],
        "failed": bool(checker.violations),
    }


def _sample_round(
    fleet: _Fleet,
    checker: InvariantChecker,
    t: float,
    t0: float,
    sample_no: int,
    burst: int,
) -> None:
    for i in range(2):
        if fleet.shards[i] is None:
            continue
        _status, metrics = fleet.get(i, "/metrics")
        _status, fleet_doc = fleet.get_json(i, "/fleet")
        _status, hints = fleet.get_json(i, "/hints")
        em = fleet.em_items(i)
        ledger_q: list = []
        goodput = None
        if sample_no % LEDGER_SAMPLE_EVERY == 0 or burst:
            ledger_q, goodput = fleet.ledger_queries(i, t0, burst)
        checker.observe(
            SurfaceSample(
                t=t, shard=i, metrics=metrics, fleet=fleet_doc,
                hints=hints, em_items=em, goodput=goodput,
                ledger_queries=ledger_q,
            )
        )


__all__ = ["ChaosRunError", "run_schedule"]
