"""The fault-schedule grammar: every fault the repo knows, as data.

A :class:`FaultSchedule` is a seed plus a sorted list of
:class:`FaultStep` (at-time, op, args) covering the whole fault
vocabulary the hand-written drills exercise piecemeal:

- **transport** (fleetsim): ``kill`` / ``revive`` / ``partition`` /
  ``slow`` / ``creep`` / ``corrupt`` / ``flap`` / ``heal``;
- **content** (FaultSpec via fleetsim ``faults``): error / latency /
  garbage / partial degradation of what every node republishes;
- **clock** (fleetsim ``skew``): wall-clock skew and step, future and
  past — the data timestamp lies, the transport doesn't;
- **load** (fleetsim ``serve``): the serving-burst dial the actuation
  tier reacts to (the one LEGITIMATE cause of hint movement);
- **shard** (engine): aggregator shard kill and warm restart from its
  spool — the split-brain / ownership-epoch axis;
- **spool** (engine): ENOSPC and EIO injected into the warm-restart
  journals — a full or dying emptyDir mid-run;
- **client** (engine): query bursts against /ledger and the External
  Metrics adapter, valid and deliberately malformed.

Schedules are plain data: :meth:`FaultSchedule.generate` derives one
deterministically from a seed (``random.Random(seed)`` — same seed,
same schedule, forever), :meth:`to_doc`/:meth:`from_doc` round-trip
through JSON so a failing schedule is a replayable artifact, and
:meth:`subset` supports the minimizer's delta-debugging over steps.

Generation is STATEFUL so random schedules stay meaningful: ``revive``
is only emitted when nodes are dead, ``shard_restart`` only when the
shard is down, kills are capped below the whole fleet, and step times
land inside the observable window (after warmup, before the final
settle) — the grammar encodes the same legality rules a human drill
author applies by hand.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field

#: Ops applied by rendering a fleetsim stdin command.
SIM_OPS = frozenset({
    "kill", "revive", "partition", "heal", "slow", "creep", "skew",
    "corrupt", "flap", "churn", "serve", "faults",
})
#: Ops applied by the engine against its aggregator shards.
SHARD_OPS = frozenset({"shard_kill", "shard_restart"})
#: Ops applied to a shard's warm-restart spools.
SPOOL_OPS = frozenset({"spool_enospc", "spool_eio", "spool_heal"})
#: Client-side ops (the engine is the client).
CLIENT_OPS = frozenset({"query_burst"})

ALL_OPS = SIM_OPS | SHARD_OPS | SPOOL_OPS | CLIENT_OPS

#: Serving-profile presets (the fleetsim ``serve`` arguments): the calm
#: baseline and the burst the actuation drills use.
SERVE_PROFILES = {
    "calm": "8 1 120 1.0",
    "burst": "80 16 900 0.55",
    "off": "off",
}

#: FaultSpec presets for the ``faults`` op. Bounded on purpose: no
#: ``hang_every`` (a hang stalls the sim's shared ticker — full-fleet
#: staleness is already covered by ``partition`` of everything) and
#: latency small enough that page fetches still complete inside the
#: aggregator's deadline budget.
FAULT_SPECS = (
    "error_rate=0.4",
    "garbage_rate=0.5",
    "partial_rate=0.5",
    "latency_ms=60",
    "error_rate=0.2,garbage_rate=0.3",
)

#: Clock-skew magnitudes (seconds): inside the 1 h clamp, at its edge,
#: and far beyond it — both signs are drawn at generation time.
SKEW_STEPS_S = (120.0, 900.0, 3600.0, 7200.0, 86400.0)


@dataclass(frozen=True)
class FaultStep:
    """One scheduled fault: apply ``op(**args)`` at ``at`` seconds."""

    at: float
    op: str
    args: dict = field(default_factory=dict)

    def to_doc(self) -> dict:
        return {"at": round(self.at, 3), "op": self.op, "args": dict(self.args)}

    @classmethod
    def from_doc(cls, doc: dict) -> "FaultStep":
        op = str(doc["op"])
        if op not in ALL_OPS:
            raise ValueError(f"unknown fault op {op!r}")
        args = doc.get("args") or {}
        if not isinstance(args, dict):
            raise ValueError(f"step args must be an object, got {args!r}")
        return cls(at=float(doc["at"]), op=op, args=dict(args))

    def describe(self) -> str:
        inner = ",".join(f"{k}={v}" for k, v in sorted(self.args.items()))
        return f"t+{self.at:.1f}s {self.op}({inner})"


@dataclass(frozen=True)
class FaultSchedule:
    """A seeded, replayable fault interleaving over one chaos fleet."""

    seed: int
    nodes: int
    duration_s: float
    steps: tuple[FaultStep, ...]
    #: Set on minimized reproducers: which generated step indices
    #: survived shrinking (provenance back to the parent schedule).
    parent_steps: tuple[int, ...] | None = None

    # -- round trip --------------------------------------------------------

    def to_doc(self) -> dict:
        doc = {
            "version": 1,
            "seed": self.seed,
            "nodes": self.nodes,
            "duration_s": round(self.duration_s, 3),
            "steps": [s.to_doc() for s in self.steps],
        }
        if self.parent_steps is not None:
            doc["parent_steps"] = list(self.parent_steps)
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "FaultSchedule":
        if doc.get("version") != 1:
            raise ValueError(f"unknown schedule version {doc.get('version')!r}")
        parent = doc.get("parent_steps")
        return cls(
            seed=int(doc["seed"]),
            nodes=int(doc["nodes"]),
            duration_s=float(doc["duration_s"]),
            steps=tuple(FaultStep.from_doc(s) for s in doc["steps"]),
            parent_steps=tuple(int(i) for i in parent) if parent else None,
        )

    def to_json(self) -> str:
        return json.dumps(self.to_doc(), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        return cls.from_doc(json.loads(text))

    # -- minimizer support -------------------------------------------------

    def subset(self, indices: list[int] | tuple[int, ...]) -> "FaultSchedule":
        """The schedule keeping only ``indices`` of :attr:`steps`
        (sorted; duplicates dropped) — the minimizer's shrink move."""
        keep = sorted(set(indices))
        return FaultSchedule(
            seed=self.seed,
            nodes=self.nodes,
            duration_s=self.duration_s,
            steps=tuple(self.steps[i] for i in keep),
            parent_steps=tuple(
                (self.parent_steps[i] if self.parent_steps else i)
                for i in keep
            ),
        )

    def describe(self) -> str:
        head = f"seed={self.seed} nodes={self.nodes} {self.duration_s:g}s"
        return head + ": " + "; ".join(s.describe() for s in self.steps)

    # -- generation --------------------------------------------------------

    @classmethod
    def generate(
        cls,
        seed: int,
        nodes: int = 16,
        duration_s: float = 20.0,
        max_steps: int = 8,
        min_steps: int = 3,
    ) -> "FaultSchedule":
        """A random legal schedule, deterministic in ``seed``."""
        rng = random.Random(seed)
        n_steps = rng.randint(min_steps, max(min_steps, max_steps))
        # Step times inside the observable window: the engine samples
        # from warmup to duration, and the tail 15% is the settle the
        # recovery-shaped invariants need to see.
        # Rounded to the serialization precision so a generated
        # schedule and its JSON round trip are the SAME value.
        times = sorted(
            round(rng.uniform(0.05 * duration_s, 0.85 * duration_s), 3)
            for _ in range(n_steps)
        )
        state = {
            "dead": 0,          # fleetsim nodes currently killed
            "shard1_down": False,
            "spool_faulted": False,
        }
        steps = [
            cls._random_step(rng, at, nodes, state) for at in times
        ]
        return cls(
            seed=seed, nodes=nodes, duration_s=duration_s,
            steps=tuple(steps),
        )

    @staticmethod
    def _random_step(
        rng: random.Random, at: float, nodes: int, state: dict
    ) -> FaultStep:
        """One legal random step given the generation state."""
        ops = [
            ("kill", 3), ("partition", 4), ("slow", 2), ("creep", 2),
            ("skew", 3), ("corrupt", 2), ("flap", 2), ("faults", 2),
            ("heal", 3), ("serve", 2), ("churn", 1), ("query_burst", 2),
            ("spool_enospc", 2), ("spool_eio", 1),
        ]
        if state["dead"]:
            ops.append(("revive", 4))
        if state["shard1_down"]:
            # A down shard strongly prefers coming back: the restart
            # path (spool restore, epoch re-claim) is where the bugs
            # live, not in staying down.
            ops.append(("shard_restart", 8))
        else:
            ops.append(("shard_kill", 2))
        if state["spool_faulted"]:
            ops.append(("spool_heal", 4))
        total = sum(w for _, w in ops)
        pick = rng.uniform(0.0, total)
        acc = 0.0
        op = ops[-1][0]
        for name, w in ops:
            acc += w
            if pick <= acc:
                op = name
                break

        args: dict = {}
        if op == "kill":
            n = rng.randint(1, max(1, nodes // 3))
            state["dead"] = min(nodes, state["dead"] + n)
            args = {"n": n}
        elif op == "revive":
            n = rng.randint(1, max(1, state["dead"]))
            state["dead"] = max(0, state["dead"] - n)
            args = {"n": n}
        elif op == "partition":
            args = {"n": rng.randint(1, max(1, nodes // 2))}
        elif op == "slow":
            args = {
                "n": rng.randint(1, max(1, nodes // 3)),
                "ms": rng.choice((50, 150, 300)),
            }
        elif op == "creep":
            args = {
                "n": rng.randint(1, max(1, nodes // 3)),
                "ms": rng.choice((150, 300, 500)),
                "ramp_s": rng.choice((2.0, 5.0, 8.0)),
            }
        elif op == "skew":
            args = {
                "n": rng.randint(1, max(1, nodes // 3)),
                "s": rng.choice(SKEW_STEPS_S) * rng.choice((-1.0, 1.0)),
            }
        elif op == "corrupt":
            args = {"n": rng.randint(1, max(1, nodes // 4))}
        elif op == "flap":
            args = {"n": rng.randint(1, max(1, nodes // 4))}
        elif op == "faults":
            args = {"spec": rng.choice(FAULT_SPECS) + f",seed={rng.randint(1, 1 << 30)}"}
        elif op == "serve":
            args = {"profile": rng.choice(("calm", "burst", "off"))}
        elif op == "churn":
            args = {"f": rng.choice((0.1, 0.5, 1.0))}
        elif op == "shard_kill":
            state["shard1_down"] = True
        elif op == "shard_restart":
            state["shard1_down"] = False
        elif op in ("spool_enospc", "spool_eio"):
            state["spool_faulted"] = True
            args = {"shard": rng.randint(0, 1)}
        elif op == "spool_heal":
            state["spool_faulted"] = False
        elif op == "query_burst":
            args = {"n": rng.choice((5, 10, 20))}
        return FaultStep(at=at, op=op, args=args)


__all__ = [
    "ALL_OPS",
    "CLIENT_OPS",
    "FAULT_SPECS",
    "FaultSchedule",
    "FaultStep",
    "SERVE_PROFILES",
    "SHARD_OPS",
    "SIM_OPS",
    "SKEW_STEPS_S",
    "SPOOL_OPS",
]
