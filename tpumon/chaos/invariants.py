"""First-class honesty invariants: the checks the soaks kept re-typing.

Every predicate here was previously an inline assertion in one soak
driver (``fleet_chaos_soak``, ``ledger_soak``, ``actuate_chaos_soak``)
— meaning every OTHER drill silently skipped it. This module lifts
them into one :class:`InvariantChecker` evaluated continuously against
every surface sample during any chaos run, and names them in a
machine-readable :data:`INVARIANT_CATALOG` (mirrored in
docs/INVARIANTS.md) so CI, docs, and reproducer JSON all speak the
same vocabulary.

Design stance on flakiness: a chaos search runs hundreds of schedules
and the acceptance bar is ZERO violations on a healthy tree, so every
predicate is either **same-snapshot** (evaluated inside one atomic
page/doc — race-free by construction) or **debounced** (cross-surface
comparisons only convict when the disagreement is STABLE across
consecutive samples — a value changing between two fetches 50 ms apart
is a race, the same two different values three samples in a row is a
lie).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

#: invariant name -> what it asserts (the machine-readable catalog;
#: docs/INVARIANTS.md documents the same names, tests pin the match).
INVARIANT_CATALOG = {
    "missing_host_unflagged": (
        "A shard seeing fewer fresh hosts than targets must say so: "
        "up < targets on one /metrics page requires the stale-rollup "
        "flag set or visibility < 1.0 — degradation is never silent."
    ),
    "per_node_series_leak": (
        "Per-node exporter series (accelerator_*, tpu_serve_*) must "
        "not re-export through the aggregator page — the tier exposes "
        "rollups, not N nodes' cardinality."
    ),
    "goodput_conservation": (
        "Per job in /ledger?view=goodput, the accounting buckets sum "
        "exactly to the reported chip-seconds — classification moves "
        "time between buckets, never creates or destroys it."
    ),
    "ledger_query_5xx": (
        "Ledger queries answer 200 (data) or 400 (malformed request), "
        "never 5xx — hostile or unlucky queries cost an error body, "
        "not the plane."
    ),
    "em_absent_below_trust_floor": (
        "A scope the trust gate withholds (per /hints, two consecutive "
        "snapshots) must be ABSENT from the External Metrics answer — "
        "degraded telemetry looks partial, never complete-but-stale."
    ),
    "epoch_monotonic": (
        "A scope's ownership epoch on /hints never decreases within "
        "one shard process life, outside an ownership-churn settling "
        "window after a shard kill/restart (a hand-back legitimately "
        "lowers the survivor's member-max) — claims are re-minted "
        "strictly newer, so the split-brain double-answer window "
        "resolves newest-epoch-wins."
    ),
    "visibility_consistency": (
        "The fleet-scope visibility ratio agrees between /fleet and "
        "/metrics: a STABLE disagreement across consecutive samples "
        "means one surface renormalized what the other flags."
    ),
}

#: Per-node family prefixes that must never appear on an aggregator
#: page (the series-leak scan, lifted from fleet_soak/serve_burst).
_LEAK_PATTERNS = (
    re.compile(rb"^accelerator_duty_cycle_percent", re.M),
    re.compile(rb"^tpu_serve_", re.M),
)

#: Consecutive stable samples a cross-surface disagreement must survive
#: before it convicts (the race-vs-lie debounce).
VISIBILITY_DEBOUNCE = 3

#: Exact-identity tolerance for goodput bucket conservation (float
#: accumulation across buckets; the ledger's own soak pins ~1e-9).
GOODPUT_TOLERANCE = 1e-6


def page_stats(body: bytes) -> dict:
    """Fleet-scope honesty numbers off one aggregator /metrics page
    (the ``_page_stats`` idiom from tools/soak.py, re-homed where every
    driver can reach it)."""
    def g(name: str, labels: bytes) -> float | None:
        m = re.search(
            rb"^" + name.encode() + rb"\{" + labels + rb"\} (\S+)",
            body, re.M,
        )
        return float(m.group(1)) if m else None

    fleet = rb'pool="",scope="fleet",slice=""'
    out = {
        "up": g("tpu_fleet_hosts", fleet + rb',state="up"'),
        "stale": g("tpu_fleet_hosts", fleet + rb',state="stale"'),
        "dark": g("tpu_fleet_hosts", fleet + rb',state="dark"'),
        "visibility": g("tpu_fleet_visibility_ratio", fleet),
        "stale_flag": g("tpu_fleet_stale_rollup", fleet),
    }
    m = re.search(rb"^tpu_fleet_shard_targets (\S+)", body, re.M)
    out["targets"] = float(m.group(1)) if m else None
    return out


@dataclass(frozen=True)
class Violation:
    """One invariant breach at one sampling instant."""

    invariant: str
    t: float
    shard: int
    detail: str

    def to_doc(self) -> dict:
        return {
            "invariant": self.invariant,
            "t_s": round(self.t, 2),
            "shard": self.shard,
            "detail": self.detail,
        }


@dataclass
class SurfaceSample:
    """Everything the engine scraped from ONE shard at one instant.
    ``None`` fields mean the surface was unreachable (a dead shard is
    absence, not evidence) or not sampled this tick."""

    t: float
    shard: int
    metrics: bytes | None = None
    fleet: dict | None = None
    hints: dict | None = None
    #: External Metrics item list; None = adapter unreachable.
    em_items: list | None = None
    #: /ledger?view=goodput document, when sampled this tick.
    goodput: dict | None = None
    #: (query description, HTTP status) for every ledger query fired
    #: this tick; status None = transport failure, not an answer.
    ledger_queries: list = field(default_factory=list)


class InvariantChecker:
    """Evaluates the catalog against a stream of surface samples.

    Single-threaded by contract: the engine's sampling loop feeds it in
    order. Cross-sample state (epoch high-water marks, withheld-scope
    history, visibility debounce) is keyed by shard; a shard RESTART
    must be announced via :meth:`reset_shard` — a fresh process mints
    fresh epochs and the withheld history of the old life is void.
    """

    def __init__(self) -> None:
        self.violations: list[Violation] = []
        self.samples_checked = 0
        #: Per-invariant evaluation counts: proof each predicate ran
        #: (a checker that silently never fired is worse than none).
        self.evaluated: dict[str, int] = {k: 0 for k in INVARIANT_CATALOG}
        #: (shard, pool, slice) -> highest ownership epoch observed.
        self._epoch_high: dict[tuple, int] = {}
        #: Rel-time horizon of the ownership-churn settling window: a
        #: scope epoch on /hints is the max over the shard's OWNED
        #: member targets, so a hand-back (shard restart reclaiming its
        #: half) legitimately LOWERS the survivor's published max. Epoch
        #: decreases inside the window rebase; outside it they convict.
        self._epoch_settle_until = float("-inf")
        #: shard -> scopes withheld in the previous /hints snapshot.
        self._prev_withheld: dict[int, set] = {}
        #: shard -> run of consecutive identical (page, fleet)
        #: visibility pairs that disagree with each other.
        self._vis_run: dict[int, tuple[tuple, int]] = {}

    # -- lifecycle ---------------------------------------------------------

    def reset_shard(self, shard: int) -> None:
        """Forget cross-sample state for a killed/restarted shard."""
        self._epoch_high = {
            k: v for k, v in self._epoch_high.items() if k[0] != shard
        }
        self._prev_withheld.pop(shard, None)
        self._vis_run.pop(shard, None)

    def note_ownership_disruption(self, t: float, settle_s: float) -> None:
        """A shard-lifecycle fault (kill/restart) at rel-time ``t``:
        target ownership will churn — takeover, then hand-back — for up
        to ``settle_s`` seconds, during which EVERY shard's per-scope
        epoch maxima may legitimately rebase downward as adopted
        members leave. Monotonicity stays enforced outside the window."""
        self._epoch_settle_until = max(
            self._epoch_settle_until, t + settle_s
        )

    # -- the checks --------------------------------------------------------

    def observe(self, sample: SurfaceSample) -> list[Violation]:
        """Run every applicable predicate; returns (and records) the
        violations this sample produced."""
        found: list[Violation] = []
        self.samples_checked += 1
        if sample.metrics is not None:
            self._check_page(sample, found)
        if sample.metrics is not None and sample.fleet is not None:
            self._check_visibility_consistency(sample, found)
        if sample.hints is not None:
            self._check_epochs(sample, found)
            self._check_em_vs_withheld(sample, found)
        if sample.goodput is not None:
            self._check_goodput(sample, found)
        if sample.ledger_queries:
            self._check_ledger_statuses(sample, found)
        self.violations.extend(found)
        return found

    def _emit(
        self, found: list, name: str, sample: SurfaceSample, detail: str
    ) -> None:
        found.append(
            Violation(
                invariant=name, t=sample.t, shard=sample.shard,
                detail=detail,
            )
        )

    def _check_page(self, sample: SurfaceSample, found: list) -> None:
        stats = page_stats(sample.metrics)
        self.evaluated["missing_host_unflagged"] += 1
        if (
            stats["up"] is not None
            and stats["targets"] is not None
            and stats["up"] < stats["targets"]
            and stats["stale_flag"] == 0.0
            and (stats["visibility"] is None or stats["visibility"] >= 1.0)
        ):
            self._emit(
                found, "missing_host_unflagged", sample,
                f"up={stats['up']:g} < targets={stats['targets']:g} with "
                f"stale_flag=0 and visibility="
                f"{stats['visibility'] if stats['visibility'] is not None else 'absent'}",
            )
        self.evaluated["per_node_series_leak"] += 1
        for pat in _LEAK_PATTERNS:
            m = pat.search(sample.metrics)
            if m:
                self._emit(
                    found, "per_node_series_leak", sample,
                    f"per-node series {m.group(0).decode()!r} on the "
                    "aggregator page",
                )
                break
        self._last_page_stats = stats

    def _check_visibility_consistency(
        self, sample: SurfaceSample, found: list
    ) -> None:
        self.evaluated["visibility_consistency"] += 1
        page_vis = page_stats(sample.metrics)["visibility"]
        fleet_vis = (sample.fleet.get("fleet") or {}).get("visibility")
        if page_vis is None or not isinstance(fleet_vis, (int, float)):
            self._vis_run.pop(sample.shard, None)
            return
        pair = (round(page_vis, 6), round(float(fleet_vis), 6))
        if pair[0] == pair[1]:
            self._vis_run.pop(sample.shard, None)
            return
        last, run = self._vis_run.get(sample.shard, (None, 0))
        run = run + 1 if pair == last else 1
        self._vis_run[sample.shard] = (pair, run)
        if run >= VISIBILITY_DEBOUNCE:
            self._emit(
                found, "visibility_consistency", sample,
                f"/metrics visibility {pair[0]} vs /fleet {pair[1]}, "
                f"stable for {run} consecutive samples",
            )

    def _hints_rows(self, sample: SurfaceSample) -> list:
        rows = sample.hints.get("slices")
        return rows if isinstance(rows, list) else []

    def _check_epochs(self, sample: SurfaceSample, found: list) -> None:
        self.evaluated["epoch_monotonic"] += 1
        for row in self._hints_rows(sample):
            epoch = row.get("epoch")
            if not isinstance(epoch, (int, float)) or epoch <= 0:
                continue
            key = (sample.shard, row.get("pool"), row.get("slice"))
            high = self._epoch_high.get(key, 0)
            if epoch < high and sample.t > self._epoch_settle_until:
                self._emit(
                    found, "epoch_monotonic", sample,
                    f"scope {key[1]}/{key[2]} epoch regressed "
                    f"{high} -> {int(epoch)}",
                )
            else:
                # Inside the settling window a decrease REBASES the
                # high-water mark (hand-back shrank the member set);
                # monotonicity re-arms from the rebased value.
                self._epoch_high[key] = int(epoch)

    def _check_em_vs_withheld(
        self, sample: SurfaceSample, found: list
    ) -> None:
        self.evaluated["em_absent_below_trust_floor"] += 1
        withheld_now = {
            (row.get("pool"), row.get("slice"))
            for row in self._hints_rows(sample)
            if row.get("withheld")
        }
        if sample.em_items is not None:
            prev = self._prev_withheld.get(sample.shard, set())
            for item in sample.em_items:
                labels = item.get("metricLabels") or {}
                scope = (labels.get("pool"), labels.get("slice"))
                # Withheld across two consecutive /hints snapshots and
                # still served as an item: the trust gate leaked a
                # value it was withholding (one-snapshot overlap is the
                # fetch race between the two surfaces).
                if scope in withheld_now and scope in prev:
                    self._emit(
                        found, "em_absent_below_trust_floor", sample,
                        f"scope {scope[0]}/{scope[1]} served by the EM "
                        "adapter while withheld on /hints",
                    )
        self._prev_withheld[sample.shard] = withheld_now

    def _check_goodput(self, sample: SurfaceSample, found: list) -> None:
        self.evaluated["goodput_conservation"] += 1
        for job in sample.goodput.get("jobs") or []:
            buckets = job.get("buckets")
            total = job.get("chip_seconds")
            if not isinstance(buckets, dict) or not isinstance(
                total, (int, float)
            ):
                continue
            drift = abs(sum(buckets.values()) - total)
            if drift > GOODPUT_TOLERANCE:
                self._emit(
                    found, "goodput_conservation", sample,
                    f"job {job.get('job')!r} buckets sum to "
                    f"{sum(buckets.values()):.6f} but chip_seconds="
                    f"{total:.6f} (drift {drift:.2e})",
                )

    def _check_ledger_statuses(
        self, sample: SurfaceSample, found: list
    ) -> None:
        self.evaluated["ledger_query_5xx"] += 1
        for desc, status in sample.ledger_queries:
            if status is not None and int(status) >= 500:
                self._emit(
                    found, "ledger_query_5xx", sample,
                    f"{desc} answered {status}",
                )

    # -- reporting ---------------------------------------------------------

    def summary(self) -> dict:
        by_invariant: dict[str, int] = {}
        for v in self.violations:
            by_invariant[v.invariant] = by_invariant.get(v.invariant, 0) + 1
        return {
            "samples_checked": self.samples_checked,
            "evaluated": dict(self.evaluated),
            "violations": len(self.violations),
            "by_invariant": by_invariant,
        }


__all__ = [
    "GOODPUT_TOLERANCE",
    "INVARIANT_CATALOG",
    "InvariantChecker",
    "SurfaceSample",
    "VISIBILITY_DEBOUNCE",
    "Violation",
    "page_stats",
]
