"""The chaos search driver: N seeded schedules, check, shrink, persist.

This is the property-based loop the hand-written drills approximate one
scenario at a time: *for all fault interleavings, the honesty
invariants hold*. Each trial generates a random legal
:class:`FaultSchedule` from its seed, runs it against a FRESH two-shard
fleet (:func:`tpumon.chaos.engine.run_schedule`) under the
:class:`InvariantChecker`, and on failure shrinks the schedule with
:func:`tpumon.chaos.minimize.minimize` to a 1-minimal reproducer,
persisted as replayable JSON (same seed + surviving steps = same run).

The driver is the CI surface: ``python -m tpumon.tools.soak
--chaos-search`` runs a bounded seeded search, and the mutation canary
job sets ``TPUMON_CHAOS_MUTATE`` to plant a known honesty bug — the
search MUST then fail, catch it under the right invariant name, and
minimize it, or CI fails. The record carries the active mutation so
evidence can't silently conflate canary runs with clean ones.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

from tpumon.chaos.engine import ChaosRunError, run_schedule
from tpumon.chaos.invariants import InvariantChecker
from tpumon.chaos.minimize import minimize
from tpumon.chaos.schedule import FaultSchedule

log = logging.getLogger(__name__)

#: Per-trial generation shape: enough steps that interleavings get
#: interesting, few enough that ddmin stays cheap.
MAX_STEPS = 8
MIN_STEPS = 3


def _progress(msg: str) -> None:
    """Progress to stderr — stdout is the JSON record, nothing else."""
    print(msg, file=sys.stderr, flush=True)


def _quiet_fleet_logs() -> None:
    """The search spins up dozens of aggregators; their INFO startup
    chatter would swamp the trial-per-line progress stream."""
    for name in ("tpumon.fleet", "tpumon.ledger", "tpumon.actuate",
                 "tpumon.history", "tpumon.guard"):
        logging.getLogger(name).setLevel(logging.WARNING)


def run_trial(
    schedule: FaultSchedule,
    interval: float = 0.5,
    node_interval: float | None = None,
) -> dict:
    """One schedule against one fresh fleet; the engine record.

    A bind-race loss (two concurrent trials probing the same port, or
    an unrelated process grabbing it first) retries with fresh ports —
    it says nothing about the schedule — then aborts as a
    :class:`ChaosRunError`, never an unhandled crash of the search.
    """
    last: OSError | None = None
    for _attempt in range(3):
        checker = InvariantChecker()
        try:
            return run_schedule(
                schedule, interval=interval, node_interval=node_interval,
                checker=checker,
            )
        except OSError as exc:
            last = exc
            log.warning(
                "trial seed=%d infra error (retrying): %s",
                schedule.seed, exc,
            )
    raise ChaosRunError(
        f"trial seed={schedule.seed} could not start a fleet: {last}"
    )


def shrink_failure(
    schedule: FaultSchedule,
    record: dict,
    interval: float = 0.5,
    node_interval: float | None = None,
    max_probes: int = 24,
) -> dict:
    """Minimize a failing schedule and verify the reproducer replays.

    Returns the failure document persisted as the replayable artifact:
    the original schedule + violations, the minimized schedule + ddmin
    stats, and whether the minimized schedule still fails when replayed
    from scratch (``replay_failed`` — the determinism proof).
    """

    def still_fails(candidate: FaultSchedule) -> bool:
        try:
            probe = run_trial(
                candidate, interval=interval, node_interval=node_interval
            )
        except ChaosRunError as exc:
            # A fleet that can't even warm up under the candidate is
            # a failure of the harness, not of the invariants — treat
            # as non-reproducing so ddmin keeps the step that allows
            # warmup.
            log.warning("ddmin probe aborted: %s", exc)
            return False
        return bool(probe["failed"])

    minimized, stats = minimize(schedule, still_fails, max_probes=max_probes)
    replay = run_trial(
        minimized, interval=interval, node_interval=node_interval
    )
    return {
        "schedule": schedule.to_doc(),
        "violations": record["violations"],
        "checker": record["checker"],
        "minimized": minimized.to_doc(),
        "minimized_describe": minimized.describe(),
        "ddmin": stats,
        "replay_failed": bool(replay["failed"]),
        "replay_violations": replay["violations"],
    }


def chaos_search(
    schedules: int = 20,
    seed0: int = 1,
    nodes: int = 16,
    duration_s: float = 20.0,
    interval: float = 0.5,
    node_interval: float | None = None,
    jobs: int = 1,
    out_dir: str | None = None,
    max_probes: int = 24,
    stop_after_failures: int = 3,
) -> dict:
    """Search seeds ``[seed0, seed0+schedules)``; shrink what fails.

    Failing schedules (original + 1-minimal reproducer + replay proof)
    are written to ``out_dir`` as ``failing-schedule-seed<seed>.json``
    when given. The search stops early after ``stop_after_failures``
    distinct failing seeds — minimization is the expensive part, and
    one planted bug does not need twenty reproducers.
    """
    _quiet_fleet_logs()
    t0 = time.monotonic()
    mutation = os.environ.get("TPUMON_CHAOS_MUTATE") or None
    seeds = list(range(seed0, seed0 + schedules))
    results: dict[int, dict] = {}
    aborted: dict[int, str] = {}

    def trial(seed: int) -> None:  # thread: chaos-trial — pool.map target; map() is not a spawn shape the analyzer resolves
        schedule = FaultSchedule.generate(
            seed, nodes=nodes, duration_s=duration_s,
            max_steps=MAX_STEPS, min_steps=MIN_STEPS,
        )
        try:
            record = run_trial(
                schedule, interval=interval, node_interval=node_interval
            )
        except ChaosRunError as exc:
            # Harness abort (fleet never warmed up): recorded apart
            # from invariant verdicts — an aborted trial proves
            # nothing either way and must not count as "passed".
            aborted[seed] = str(exc)
            _progress(f"chaos-search seed={seed} ABORTED: {exc}")
            return
        results[seed] = record
        verdict = "FAIL" if record["failed"] else "ok"
        _progress(
            f"chaos-search seed={seed} {verdict} "
            f"steps={len(schedule.steps)} "
            f"violations={len(record['violations'])} "
            f"samples={record['checker']['samples_checked']}"
        )

    if jobs > 1:
        # Each trial owns its fleetsim subprocess, its aggregator
        # ports, and its tempdir spools — trials share nothing but the
        # machine, so a small pool is safe and shortens wall clock.
        with ThreadPoolExecutor(max_workers=jobs) as pool:
            list(pool.map(trial, seeds))
    else:
        for seed in seeds:
            trial(seed)

    failing = sorted(s for s, r in results.items() if r["failed"])
    failures = []
    for seed in failing[:stop_after_failures]:
        _progress(f"chaos-search minimizing seed={seed} ...")
        doc = shrink_failure(
            FaultSchedule.from_doc(results[seed]["schedule"]),
            results[seed], interval=interval,
            node_interval=node_interval, max_probes=max_probes,
        )
        doc["seed"] = seed
        failures.append(doc)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, f"failing-schedule-seed{seed}.json")
            with open(path, "w") as fh:
                json.dump(doc, fh, indent=2, sort_keys=True)
            _progress(f"chaos-search wrote {path}")

    by_invariant: dict[str, int] = {}
    op_coverage: dict[str, int] = {}
    for record in results.values():
        for violation in record["violations"]:
            name = violation["invariant"]
            by_invariant[name] = by_invariant.get(name, 0) + 1
        for step in record["schedule"]["steps"]:
            op_coverage[step["op"]] = op_coverage.get(step["op"], 0) + 1

    return {
        "mode": "chaos-search",
        "schedules": schedules,
        "seed0": seed0,
        "nodes": nodes,
        "duration_s": duration_s,
        "interval_s": interval,
        "jobs": jobs,
        "mutation": mutation,
        "ran": len(results),
        "aborted": {str(s): e for s, e in sorted(aborted.items())},
        "passed": len(results) - len(failing),
        "failed": len(failing),
        "failing_seeds": failing,
        "violations_by_invariant": dict(sorted(by_invariant.items())),
        "op_coverage": dict(sorted(op_coverage.items())),
        "samples_checked": sum(
            r["checker"]["samples_checked"] for r in results.values()
        ),
        "failures": failures,
        "elapsed_s": round(time.monotonic() - t0, 1),
        "ok": not failing and not aborted,
    }


def chaos_replay(
    path: str, interval: float = 0.5, node_interval: float | None = None
) -> dict:
    """Replay a persisted failing-schedule artifact (or a bare schedule
    JSON) once and report — the game-day / bug-triage entry point."""
    _quiet_fleet_logs()
    with open(path) as fh:
        doc = json.load(fh)
    # Accept either a bare schedule or a shrink_failure artifact; the
    # artifact replays its MINIMIZED schedule (that is the reproducer).
    sched_doc = doc.get("minimized") or doc.get("schedule") or doc
    schedule = FaultSchedule.from_doc(sched_doc)
    _progress(f"chaos-replay {schedule.describe()}")
    record = run_trial(
        schedule, interval=interval, node_interval=node_interval
    )
    record["mode"] = "chaos-replay"
    record["source"] = path
    return record


__all__ = ["chaos_replay", "chaos_search", "run_trial", "shrink_failure"]
