"""Property-based chaos search: seeded fault schedules, first-class
invariants, minimized reproducers.

Every robustness claim the repo makes used to come from a hand-scripted
drill exercising ONE fault interleaving its author thought of; the
honesty checks backing the claim were re-implemented ad hoc inside each
soak. This package turns both into first-class objects:

- :mod:`tpumon.chaos.schedule` — the full fault vocabulary (fleetsim
  transport faults, FaultSpec content faults, clock skew, shard
  kill/warm-restart, spool ENOSPC/EIO, query bursts) as one declarative
  seeded :class:`FaultSchedule` grammar with a JSON round-trip, so any
  fault interleaving is a value: generatable from a seed, replayable
  from a file, shrinkable by a minimizer.
- :mod:`tpumon.chaos.invariants` — the honesty predicates the paper
  stakes the system on (absent-not-zero, stale-flagged-never-silent,
  goodput conservation, trust-gated actuation, ...) as a checker
  evaluated continuously against every surface during any run.
- :mod:`tpumon.chaos.engine` — a live 2-shard aggregator fleet over
  fleetsim that applies a schedule and samples every surface through
  the checker.
- :mod:`tpumon.chaos.minimize` — delta-debugging over schedule steps:
  a failing schedule shrinks to a minimal reproducer worth reading.

``tools/soak.py --chaos-search`` drives the loop: generate N seeded
random schedules, run each, shrink the failures, persist reproducers.
"""

from tpumon.chaos.invariants import INVARIANT_CATALOG, InvariantChecker, Violation
from tpumon.chaos.schedule import FaultSchedule, FaultStep

__all__ = [
    "FaultSchedule",
    "FaultStep",
    "INVARIANT_CATALOG",
    "InvariantChecker",
    "Violation",
]
