"""Failing-schedule minimization: delta debugging over fault steps.

A random schedule that breaks an invariant usually breaks it with most
of its steps irrelevant — the reproducer worth committing is the 1-2
step core. :func:`minimize` is classic ddmin (Zeller) over the step
list: try removing chunks at increasing granularity, keep any removal
that still fails, stop when no single step can be removed. Each probe
re-runs the candidate subset against a FRESH fleet (the test function
is an experiment, not a lookup), so the probe budget is explicit and
capped — minimization must never cost more than the search that found
the failure.

The result keeps the parent schedule's seed and per-step provenance
(:attr:`FaultSchedule.parent_steps`), so a minimized reproducer names
exactly which generated steps survived and replays deterministically:
same seed, same steps, same fleet shape.
"""

from __future__ import annotations

import logging
from typing import Callable

from tpumon.chaos.schedule import FaultSchedule

log = logging.getLogger(__name__)


def minimize(
    schedule: FaultSchedule,
    still_fails: Callable[[FaultSchedule], bool],
    max_probes: int = 24,
) -> tuple[FaultSchedule, dict]:
    """Shrink ``schedule`` to a 1-minimal failing subset of its steps.

    ``still_fails(candidate)`` re-runs the candidate and returns True
    when the failure reproduces. Returns ``(minimized, stats)``;
    ``minimized`` is the original schedule when nothing could be
    removed (or the probe budget ran out before anything reproduced).
    The result is 1-minimal when ``stats["minimal"]`` is True: removing
    any single remaining step no longer fails.
    """
    indices = list(range(len(schedule.steps)))
    probes = 0
    reduced = False

    def probe(keep: list[int]) -> bool:
        nonlocal probes
        probes += 1
        candidate = schedule.subset(keep)
        failed = still_fails(candidate)
        log.info(
            "ddmin probe %d: %d/%d steps -> %s",
            probes, len(keep), len(schedule.steps),
            "fails (keep)" if failed else "passes (revert)",
        )
        return failed

    granularity = 2
    minimal = False
    while len(indices) >= 2 and probes < max_probes:
        chunk = max(1, len(indices) // granularity)
        removed_any = False
        start = 0
        while start < len(indices) and probes < max_probes:
            keep = indices[:start] + indices[start + chunk:]
            if not keep:
                start += chunk
                continue
            if probe(keep):
                indices = keep
                reduced = True
                removed_any = True
                granularity = max(2, granularity - 1)
                # Restart the sweep over the shrunk list.
                start = 0
                chunk = max(1, len(indices) // granularity)
            else:
                start += chunk
        if not removed_any:
            if chunk == 1:
                minimal = True
                break
            granularity = min(len(indices), granularity * 2)

    stats = {
        "probes": probes,
        "original_steps": len(schedule.steps),
        "minimized_steps": len(indices),
        "minimal": minimal or len(indices) == 1,
        "reduced": reduced,
    }
    return schedule.subset(indices), stats


__all__ = ["minimize"]
