"""Kubernetes External Metrics API served off the actuation read model.

``GET /apis/external.metrics.k8s.io/v1beta1/namespaces/{ns}/{metric}``
is what an HPA with an ``External`` metric source asks (via the API
server's APIService proxy); this module answers it — plus the two
discovery documents the aggregator layer needs (APIGroup and
APIResourceList) — straight from the :class:`ActuatePlane`'s
pre-computed per-slice rows. A metrics query therefore touches **no
raw per-node series**: the adapter reads what the collect cycle
already rolled up, the same read-model discipline as /fleet.

Freshness is honest: a row backed by a stale rollup bucket (or an
aggregator that hasn't completed a collect cycle recently) is served
with ``metricLabels["tpumon_stale"] = "true"`` and the timestamp of the
cycle that produced it — never re-stamped as current. An HPA reads the
timestamp; a stale value that claims to be fresh would actuate on
fiction, which is exactly the failure mode the exporter's
absent-not-zero rule exists to prevent.

Trust goes one step further (tpumon/actuate/trust.py): a row whose
trust fell below the configured floor — or whose scope a peer shard
claims at a newer ownership epoch — contributes NO item at all. Absent
is the Kubernetes-correct "no data": the HPA holds at current size
instead of scaling on degraded or double-owned telemetry.
"""

from __future__ import annotations

import json
import re
import time

API_GROUP = "external.metrics.k8s.io"
API_VERSION = "v1beta1"
API_PREFIX = "/apis/" + API_GROUP

#: metric name -> (description, extractor over one ActuatePlane row).
#: Extractors return None when the row doesn't carry the signal — the
#: row then contributes no item (absent-not-zero, per slice).
EXTERNAL_METRICS: dict = {
    "tpumon_duty_cycle_percent": (
        "Mean accelerator duty cycle of the slice's chips (percent)",
        lambda row: (row["bucket"].get("duty") or {}).get("mean"),
    ),
    "tpumon_hbm_headroom_ratio": (
        "Unused fraction of the slice's HBM",
        lambda row: row["bucket"].get("hbm_headroom_ratio"),
    ),
    "tpumon_step_latency_seconds": (
        "Mean wall seconds per optimizer step over the slice's feeds "
        "(1 / step rate)",
        lambda row: (
            1.0 / row["bucket"]["step_rate"]
            if row["bucket"].get("step_rate")
            else None
        ),
    ),
    "tpumon_serve_queue_depth": (
        "Admitted-but-incomplete inference requests across the "
        "slice's serving feeds — the canonical HPA scale signal",
        lambda row: (row.get("serve") or {}).get("queue_depth"),
    ),
    "tpumon_serve_requests_per_second": (
        "Completed inference requests per second across the slice's "
        "serving feeds",
        lambda row: (row.get("serve") or {}).get("requests_per_second"),
    ),
    "tpumon_serve_ttft_seconds": (
        "Worst time-to-first-token proxy across the slice's serving "
        "feeds",
        lambda row: (row.get("serve") or {}).get("ttft_seconds"),
    ),
    "tpumon_goodput_slo_ratio": (
        "Fraction of inference requests meeting the serving SLO "
        "across the slice's feeds — goodput under SLO",
        lambda row: (row.get("serve") or {}).get("slo_attainment_ratio"),
    ),
    "tpumon_hint_headroom_score": (
        "Placement-hint headroom score in [0, 1] (higher = better "
        "placement target)",
        lambda row: row.get("score"),
    ),
    # Pool-scope metric: answered from the ledger's capacity forecast
    # (tpumon/ledger/forecast.py) via the adapter's forecast provider,
    # not from per-slice rows — the extractor slot is None and _items
    # branches. Pools below the minimum-history gate (or with no
    # saturating trend) contribute NO item: an HPA must never scale on
    # a fabricated date (absent-not-zero, pool scope).
    "tpumon_days_to_saturation": (
        "Days until the pool saturates (duty rising to 95% or HBM "
        "headroom falling to 5%) per the ledger's linear-trend "
        "capacity forecast; absent for pools whose history or trend "
        "cannot support a date",
        None,
    ),
}

_SET_RE = re.compile(
    r"^\s*([A-Za-z0-9._/-]+)\s+(in|notin)\s+\(([^)]*)\)\s*$"
)
_EQ_RE = re.compile(
    r"^\s*([A-Za-z0-9._/-]+)\s*(==|!=|=)\s*([A-Za-z0-9._/-]*)\s*$"
)


def parse_label_selector(raw: str) -> list[tuple[str, str, set[str]]]:
    """Kubernetes label-selector string -> [(key, op, values)] with op
    ∈ {in, notin} (equality folds into a one-element set). Raises
    ValueError on syntax the grammar doesn't cover — the adapter turns
    that into a 400, never a silent match-all."""
    requirements: list[tuple[str, str, set[str]]] = []
    if not raw or not raw.strip():
        return requirements
    # Split on commas OUTSIDE parens ("k in (a,b),pool=v5p" is one
    # selector with two requirements).
    parts: list[str] = []
    depth = 0
    current = ""
    for ch in raw:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            parts.append(current)
            current = ""
        else:
            current += ch
    parts.append(current)
    for part in parts:
        if not part.strip():
            continue
        m = _SET_RE.match(part)
        if m:
            key, op, values = m.group(1), m.group(2), m.group(3)
            requirements.append(
                (key, op, {v.strip() for v in values.split(",") if v.strip()})
            )
            continue
        m = _EQ_RE.match(part)
        if m:
            key, op, value = m.group(1), m.group(2), m.group(3)
            requirements.append(
                (key, "notin" if op == "!=" else "in", {value})
            )
            continue
        raise ValueError(f"unparseable selector requirement: {part!r}")
    return requirements


def selector_matches(
    requirements: list[tuple[str, str, set[str]]], labels: dict[str, str]
) -> bool:
    """Evaluate parsed requirements against one row's labels
    (Kubernetes semantics: ``in`` on a missing key never matches,
    ``notin`` on a missing key matches)."""
    for key, op, values in requirements:
        value = labels.get(key)
        if op == "in":
            if value is None or value not in values:
                return False
        else:
            if value is not None and value in values:
                return False
    return True


def quantity(value: float) -> str:
    """A Kubernetes resource.Quantity for a metric value: integral
    values serialize bare, everything else at milli precision (the
    API's conventional granularity for external metrics)."""
    value = float(value)
    if value == int(value):
        return str(int(value))
    return f"{int(round(value * 1000))}m"


def rfc3339(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


class ExternalMetricsAdapter:
    """Routes the three External Metrics API paths against a plane.

    ``handle`` returns ``(status, body, metric, result)`` so the WSGI
    layer can respond and the telemetry counter can label the request
    without re-parsing anything. Thread-safe: reads only the plane's
    lock-published read model.
    """

    def __init__(self, plane, forecast_provider=None) -> None:
        self._plane = plane
        #: Optional () -> (pool -> forecast doc, computed_at_ts) from
        #: the ledger plane; None (no ledger) keeps the pool-scope
        #: forecast metric answering an empty item list.
        self._forecast_provider = forecast_provider

    def handle(
        self, path: str, query_string: str, now: float | None = None
    ) -> tuple[str, bytes, str, str]:
        now = time.time() if now is None else now
        path = path.rstrip("/") or "/"
        if path == API_PREFIX:
            return "200 OK", _json(self._api_group()), "", "ok"
        if path == f"{API_PREFIX}/{API_VERSION}":
            return "200 OK", _json(self._resource_list()), "", "ok"
        m = re.match(
            f"^{re.escape(API_PREFIX)}/{API_VERSION}"
            r"/namespaces/([^/]+)/([^/]+)$",
            path,
        )
        if not m:
            return (
                "404 Not Found",
                _json(_status(404, f"unknown path {path}")),
                "",
                "not_found",
            )
        metric = m.group(2)
        if metric not in EXTERNAL_METRICS:
            return (
                "404 Not Found",
                _json(_status(404, f"unknown external metric {metric}")),
                metric,
                "not_found",
            )
        params = _query_params(query_string)
        try:
            requirements = parse_label_selector(
                params.get("labelSelector", "")
            )
        except ValueError as exc:
            return (
                "400 Bad Request",
                _json(_status(400, str(exc))),
                metric,
                "bad_request",
            )
        items, any_stale, any_withheld = self._items(
            metric, requirements, now
        )
        body = {
            "kind": "ExternalMetricValueList",
            "apiVersion": f"{API_GROUP}/{API_VERSION}",
            "metadata": {},
            "items": items,
        }
        result = "ok"
        if any_withheld:
            result = "withheld"
        elif any_stale:
            result = "stale"
        return "200 OK", _json(body), metric, result

    def _items(
        self,
        metric: str,
        requirements: list[tuple[str, str, set[str]]],
        now: float,
    ) -> tuple[list[dict], bool, bool]:
        _, extract = EXTERNAL_METRICS[metric]
        if extract is None:
            items, any_stale = self._forecast_items(
                metric, requirements, now
            )
            return items, any_stale, False
        items: list[dict] = []
        any_stale = False
        any_withheld = False
        for row in self._plane.rows():
            labels = {
                "pool": row["pool"],
                "slice": row["slice"],
                # An HPA selecting on job identity uses the slice name
                # — the ledger's job key is (pool, slice) too.
                "job": row["slice"],
            }
            if not selector_matches(requirements, labels):
                continue
            if row.get("withheld"):
                # Trust-gated (or epoch-conflicted) scope: the
                # Kubernetes-correct "no data" is an ABSENT item — the
                # HPA holds at current size. Never last-good, never a
                # fabricated value a controller would scale on.
                any_withheld = True
                continue
            value = extract(row)
            if value is None:
                continue
            stale = bool(row.get("stale")) or self._plane.is_stale(now)
            metric_labels = {
                "pool": row["pool"],
                "slice": row["slice"],
                "job": row["slice"],
            }
            if stale:
                # Served, but honestly: the HPA (or a human) sees both
                # the flag and the true age via the cycle timestamp.
                metric_labels["tpumon_stale"] = "true"
                any_stale = True
            items.append(
                {
                    "metricName": metric,
                    "metricLabels": metric_labels,
                    "timestamp": rfc3339(row["ts"]),
                    "value": quantity(value),
                }
            )
        return items, any_stale, any_withheld

    def _forecast_items(
        self,
        metric: str,
        requirements: list[tuple[str, str, set[str]]],
        now: float,
    ) -> tuple[list[dict], bool]:
        """Pool-scope items off the ledger's forecast snapshot. One
        item per pool WITH a supported date; gated / trendless pools
        are absent, and the timestamp is the forecast's compute time —
        never re-stamped as current."""
        if self._forecast_provider is None:
            return [], False
        forecasts, computed_at = self._forecast_provider()
        items: list[dict] = []
        any_stale = False
        for pool, doc in sorted(forecasts.items()):
            days = doc.get("days_to_saturation")
            if days is None:
                continue
            labels = {"pool": pool}
            if not selector_matches(requirements, labels):
                continue
            stale = self._plane.is_stale(now)
            metric_labels = {
                "pool": pool,
                "tpumon_forecast_status": doc["status"],
            }
            if stale:
                metric_labels["tpumon_stale"] = "true"
                any_stale = True
            items.append(
                {
                    "metricName": metric,
                    "metricLabels": metric_labels,
                    "timestamp": rfc3339(computed_at),
                    "value": quantity(days),
                }
            )
        return items, any_stale

    @staticmethod
    def _api_group() -> dict:
        return {
            "kind": "APIGroup",
            "apiVersion": "v1",
            "name": API_GROUP,
            "versions": [
                {
                    "groupVersion": f"{API_GROUP}/{API_VERSION}",
                    "version": API_VERSION,
                }
            ],
            "preferredVersion": {
                "groupVersion": f"{API_GROUP}/{API_VERSION}",
                "version": API_VERSION,
            },
        }

    @staticmethod
    def _resource_list() -> dict:
        return {
            "kind": "APIResourceList",
            "apiVersion": "v1",
            "groupVersion": f"{API_GROUP}/{API_VERSION}",
            "resources": [
                {
                    "name": name,
                    "singularName": "",
                    "namespaced": True,
                    "kind": "ExternalMetricValueList",
                    "verbs": ["get"],
                }
                for name in sorted(EXTERNAL_METRICS)
            ],
        }


def _query_params(query_string: str) -> dict[str, str]:
    from urllib.parse import parse_qs

    return {
        k: v[-1]
        for k, v in parse_qs(query_string or "", keep_blank_values=True).items()
    }


def _status(code: int, message: str) -> dict:
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "metadata": {},
        "status": "Failure",
        "message": message,
        "code": code,
    }


def _json(doc: dict) -> bytes:
    return json.dumps(doc, sort_keys=True).encode()
