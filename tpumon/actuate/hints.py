"""Placement-hint engine: per-slice headroom scores with hysteresis.

The score answers the scheduler's question — "where does new work land
well?" — from signals the fleet tier already rolls up: duty headroom
(an idle slice absorbs load), HBM headroom (a full slice OOMs it), ICI
health (a degraded fabric slows collectives), straggler state (a slice
dragging a straggler drags new work too), and the goodput ledger's
contended/idle history (a slice that historically burns chip-seconds in
``contended`` is a bad neighbor even when instantaneously idle).

Everything here is pure functions plus one small stateful hysteresis
class, so the scoring semantics are testable without an aggregator:
the :class:`~tpumon.actuate.plane.ActuatePlane` wires them into the
collect cycle.

Missing inputs renormalize rather than defaulting: a slice with no HBM
series is scored on the signals it HAS, not on an invented 0.5 — the
absent-not-zero rule applied to scoring. A slice with no scoreable
signal at all gets no score (hint absent, never neutral-by-fiat).
"""

from __future__ import annotations

#: Signal weights (renormalized over the inputs a slice actually has).
WEIGHT_DUTY = 0.35
WEIGHT_HBM = 0.25
WEIGHT_ICI = 0.15
WEIGHT_GOODPUT = 0.25

#: Score subtracted while the slice carries an active straggler.
STRAGGLER_PENALTY = 0.2

#: The hysteresis bands, best placement target first.
BANDS = ("prefer", "neutral", "avoid")


def headroom_score(
    bucket: dict, goodput: dict | None = None
) -> tuple[float | None, dict]:
    """One slice's headroom score in [0, 1] from its rollup bucket
    (:meth:`tpumon.fleet.rollup._Agg.to_dict` shape) and, when the
    ledger runs, its goodput bucket totals (chip-seconds by bucket).

    Returns ``(score | None, inputs)`` — inputs is the per-signal
    breakdown published on /hints so a hint is always explainable.
    """
    parts: list[tuple[float, float]] = []
    inputs: dict = {}

    duty = bucket.get("duty")
    if duty and duty.get("n"):
        duty_headroom = min(1.0, max(0.0, 1.0 - duty["mean"] / 100.0))
        parts.append((WEIGHT_DUTY, duty_headroom))
        inputs["duty_headroom"] = duty_headroom

    hbm = bucket.get("hbm_headroom_ratio")
    if hbm is not None:
        hbm = min(1.0, max(0.0, hbm))
        parts.append((WEIGHT_HBM, hbm))
        inputs["hbm_headroom_ratio"] = hbm

    ici = bucket.get("ici")
    if ici and ici.get("links"):
        score = min(1.0, max(0.0, ici.get("score", 0.0)))
        parts.append((WEIGHT_ICI, score))
        inputs["ici_score"] = score

    goodput_factor = _goodput_factor(goodput)
    if goodput_factor is not None:
        parts.append((WEIGHT_GOODPUT, goodput_factor))
        inputs["goodput_factor"] = goodput_factor

    if not parts:
        return None, inputs

    total_weight = sum(w for w, _ in parts)
    score = sum(w * v for w, v in parts) / total_weight

    straggling = bool(bucket.get("stragglers"))
    inputs["straggler_active"] = straggling
    if straggling:
        score -= STRAGGLER_PENALTY
    return min(1.0, max(0.0, score)), inputs


def _goodput_factor(goodput: dict | None) -> float | None:
    """1 minus the slice's historical contended+idle share of VISIBLE
    chip-seconds (unaccounted windows are honesty, not evidence — they
    join neither numerator nor denominator). None until the ledger has
    accounted anything visible for the job."""
    if not goodput:
        return None
    visible = sum(
        v for k, v in goodput.items() if k != "unaccounted"
    )
    if visible <= 0:
        return None
    wasted = goodput.get("contended", 0.0) + goodput.get("idle", 0.0)
    return min(1.0, max(0.0, 1.0 - wasted / visible))


def band_of(score: float, prefer: float, avoid: float) -> str:
    """Raw (pre-hysteresis) band for a score against the configured
    thresholds: ≥ prefer → prefer, ≤ avoid → avoid, else neutral."""
    if score >= prefer:
        return "prefer"
    if score <= avoid:
        return "avoid"
    return "neutral"


class HintHysteresis:
    """Band publication with a hold window so hints don't flap.

    The first computed band publishes immediately (a new slice needs a
    hint now, not in ``hold_cycles``); after that, a band change only
    publishes once the candidate band has held for ``hold_cycles``
    CONSECUTIVE cycles — a transient duty spike that dips a slice into
    ``avoid`` for one rollup interval never reaches the scheduler.

    Collect-cycle thread only (the plane publishes results under its
    own lock), so no locking here.
    """

    def __init__(self, hold_cycles: int = 3) -> None:
        self.hold_cycles = max(1, int(hold_cycles))
        #: slice key -> published band.
        self._published: dict[tuple[str, str], str] = {}
        #: slice key -> (candidate band, consecutive cycles seen).
        self._pending: dict[tuple[str, str], tuple[str, int]] = {}
        #: slice key -> published transitions since start.
        self.transitions: dict[tuple[str, str], int] = {}

    def update(self, key: tuple[str, str], band: str) -> str:
        """Feed one cycle's raw band; returns the published band."""
        published = self._published.get(key)
        if published is None:
            self._published[key] = band
            self.transitions.setdefault(key, 0)
            return band
        if band == published:
            self._pending.pop(key, None)
            return published
        candidate, streak = self._pending.get(key, (band, 0))
        if candidate != band:
            candidate, streak = band, 0
        streak += 1
        if streak >= self.hold_cycles:
            self._published[key] = band
            self._pending.pop(key, None)
            self.transitions[key] = self.transitions.get(key, 0) + 1
            return band
        self._pending[key] = (candidate, streak)
        return published

    def published_band(self, key: tuple[str, str]) -> str | None:
        """The currently published band for a slice (None before its
        first score) — the value the trust layer freezes at while the
        slice's telemetry is degraded."""
        return self._published.get(key)

    def export_state(self) -> list[list]:
        """Spool-serializable published-band state:
        ``[[pool, slice, band], ...]`` (JSON-safe — tuple keys don't
        survive a round trip)."""
        return [
            [pool, slc, band]
            for (pool, slc), band in sorted(self._published.items())
        ]

    def seed(self, state) -> int:
        """Warm-start published bands from :meth:`export_state` output
        (a spool restore, or an alive peer's /hints on takeover). Only
        MISSING keys seed — a band this instance already published is
        live truth and never regresses to journaled state. Tolerant of
        junk rows (an old or foreign spool shape seeds nothing, never
        raises). Returns the number of bands seeded."""
        seeded = 0
        for row in state or []:
            if not (
                isinstance(row, (list, tuple))
                and len(row) == 3
                and all(isinstance(v, str) for v in row)
                and row[2] in BANDS
            ):
                continue
            key = (row[0], row[1])
            if key not in self._published:
                self._published[key] = row[2]
                self.transitions.setdefault(key, 0)
                seeded += 1
        return seeded

    def forget(self, live: set[tuple[str, str]]) -> None:
        """Drop state for slices no longer in the rollup (identity
        churn must not leak hysteresis state forever). Transition
        counters stay — they are history, and counters never regress."""
        for store in (self._published, self._pending):
            for key in [k for k in store if k not in live]:
                del store[key]
