"""The actuation plane: fleet rollups → Kubernetes control signals.

Three pieces close the observe→act loop (ISSUE 16):

- :mod:`tpumon.actuate.plane` — :class:`ActuatePlane` rides the
  aggregator's collect cycle like the ledger does, rolling the
  lifecycle plane's serving join up per slice/pool/fleet and running
  the placement-hint engine, all into a pre-computed read model so a
  query never touches raw per-node series;
- :mod:`tpumon.actuate.adapter` — the Kubernetes External Metrics API
  (``/apis/external.metrics.k8s.io/v1beta1/...``) served straight off
  that read model, so an HPA can scale serving fleets on duty cycle,
  HBM headroom, queue depth, TTFT, or goodput-under-SLO;
- :mod:`tpumon.actuate.hints` — the per-slice headroom score
  (duty + HBM + ICI + straggler state + ledger goodput history) with
  hysteresis, published as ``/hints`` and as annotation patches a
  scheduler extender or descheduler can consume.
"""

from tpumon.actuate.adapter import (
    EXTERNAL_METRICS,
    ExternalMetricsAdapter,
    parse_label_selector,
    quantity,
)
from tpumon.actuate.hints import (
    HintHysteresis,
    band_of,
    headroom_score,
)
from tpumon.actuate.plane import ActuatePlane

__all__ = [
    "EXTERNAL_METRICS",
    "ActuatePlane",
    "ExternalMetricsAdapter",
    "HintHysteresis",
    "band_of",
    "headroom_score",
    "parse_label_selector",
    "quantity",
]
