"""Signal-integrity scoring for the actuation surfaces.

The fleet tier already KNOWS when it is degraded — visibility ratios,
stale-flagged rollups, the contested flag on a double-owned takeover
window, spool-restored feeds serving last-good data — but until this
module none of that honesty gated the control path: an External Metric
or a placement hint computed from a half-visible, contested rollup was
served with the same confidence as a healthy one. Trust scoring closes
that gap: every actuation answer carries a trust in [0, 1] derived from
the degradation signals of the scope it was computed from, and answers
below the configured floor are WITHHELD (the Kubernetes-correct "no
data" — an HPA holds at current size) rather than served as a number a
controller would act on. Degraded telemetry holds the world still; it
never steers it.

Everything here is pure functions (the :class:`ActuatePlane` wires them
into the collect cycle), so the trust semantics are testable without an
aggregator — the same stance as tpumon/actuate/hints.py.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

#: Default trust floor: answers scoring below this are withheld. The
#: deliberate midpoint — a scope at half visibility (or a stale rollup)
#: sits AT the floor, so any compounding degradation drops it under.
DEFAULT_MIN_TRUST = 0.5

#: Freshness factor applied while the scope's rollup bucket is stale
#: (serving last-good data past the staleness budget). Chosen below
#: the default floor on its own: a fully-stale scope must never steer.
FACTOR_STALE = 0.4

#: Ownership factor applied while the global rollup is CONTESTED (two
#: shards briefly both answering for the same targets during a
#: takeover / hand-back window). Double-counted totals are the least
#: trustworthy input an autoscaler could consume.
FACTOR_CONTESTED = 0.3

#: Trust lost when ALL of a scope's feeds serve spool-restored (warm
#: restart) snapshots instead of live fetches: restored data is
#: last-good by construction, honest but not current. Scales linearly
#: with the restored fraction — one warm feed in ten barely registers.
WARMTH_WEIGHT = 0.5


def _clamp(value: float) -> float:
    return min(1.0, max(0.0, float(value)))


def trust_score(
    *,
    visibility: float | None = None,
    stale: bool = False,
    contested: bool = False,
    restored_fraction: float = 0.0,
) -> tuple[float, dict]:
    """One scope's trust in [0, 1] from its degradation signals.

    Returns ``(trust, inputs)`` — inputs is the per-factor breakdown
    published on /hints and /debug/vars, so a withheld answer is always
    explainable (the same contract headroom_score keeps for hints).

    Multiplicative composition: each degradation scales trust down
    independently, so compounding failures (a stale AND half-visible
    scope) compound the distrust instead of averaging it away.
    """
    inputs: dict = {}
    trust = 1.0
    if visibility is not None:
        vis = _clamp(visibility)
        inputs["visibility"] = vis
        trust *= vis
    inputs["stale"] = bool(stale)
    if stale:
        trust *= FACTOR_STALE
    inputs["contested"] = bool(contested)
    if contested:
        trust *= FACTOR_CONTESTED
    warmth = _clamp(restored_fraction)
    if warmth > 0.0:
        inputs["restored_fraction"] = warmth
        trust *= 1.0 - WARMTH_WEIGHT * warmth
    return _clamp(trust), inputs


def is_trusted(trust: float | None, min_trust: float) -> bool:
    """The gate: ``None`` (no trust computed — a plane cycled without
    degradation inputs, e.g. unit fixtures) stays trusted for
    backward compatibility; a computed trust must meet the floor."""
    return trust is None or trust >= min_trust


def min_trust_from_env(default: float, environ=None) -> float:
    """Resolve the trust floor: the documented literal
    ``TPUMON_ACTUATE_MIN_TRUST`` wins over the FleetConfig-derived
    default (``TPUMON_FLEET_ACTUATE_MIN_TRUST``); a malformed value
    logs and keeps the default — never a crash loop on a typo."""
    env = os.environ if environ is None else environ
    raw = env.get("TPUMON_ACTUATE_MIN_TRUST")
    if raw is None or not raw.strip():
        return float(default)
    try:
        return _clamp(float(raw))
    except ValueError:
        log.warning("ignoring malformed TPUMON_ACTUATE_MIN_TRUST=%r", raw)
        return float(default)


__all__ = [
    "DEFAULT_MIN_TRUST",
    "FACTOR_CONTESTED",
    "FACTOR_STALE",
    "WARMTH_WEIGHT",
    "is_trusted",
    "min_trust_from_env",
    "trust_score",
]
