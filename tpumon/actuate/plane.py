"""The actuation plane wired into the aggregator's collect cycle.

One :meth:`ActuatePlane.cycle` call per collect pass, fed the SAME
rollup doc and feed entries the ledger gets — the plane:

1. rolls the lifecycle plane's serving join (``snap["serve"]``, parsed
   off node pages by tpumon/fleet/ingest.py) up per slice/pool/fleet;
2. scores every slice through the placement-hint engine
   (tpumon/actuate/hints.py) joining the rollup bucket with the goodput
   ledger's per-job history, and runs band hysteresis;
3. publishes the result as an immutable read model under one lock.

Every query surface — the External Metrics adapter, ``/hints``, the
``tpu_fleet_*`` families on the aggregator page — reads that model:
a query touches **no raw per-node series** and does no aggregation of
its own, the same read-model discipline as /fleet and the ledger.
"""

from __future__ import annotations

import json
import threading

from prometheus_client.core import CounterMetricFamily, GaugeMetricFamily

from tpumon.actuate.adapter import ExternalMetricsAdapter
from tpumon.actuate.hints import (
    BANDS,
    HintHysteresis,
    band_of,
    headroom_score,
)

#: Annotation keys published in the /hints patch shapes — what a
#: scheduler extender or descheduler reads off the node/pool objects.
ANNOTATION_SCORE = "tpumon.dev/headroom-score"
ANNOTATION_BAND = "tpumon.dev/placement-band"

_SERVE_KEYS = (
    "requests_per_second",
    "queue_depth",
    "ttft_seconds",
    "slo_attainment_ratio",
)


class _ServeAgg:
    """One scope's serving accumulator: throughput and queue SUM over
    feeds (independent request streams), TTFT takes the worst feed,
    SLO attainment and batch size average — the same merge the
    lifecycle plane applies across its feeds, one level up."""

    __slots__ = ("rps", "queue", "ttft", "slo_sum", "slo_n",
                 "batch_sum", "batch_n", "feeds")

    def __init__(self) -> None:
        self.rps: float | None = None
        self.queue: float | None = None
        self.ttft: float | None = None
        self.slo_sum = 0.0
        self.slo_n = 0
        self.batch_sum = 0.0
        self.batch_n = 0
        self.feeds = 0

    def add(self, serve: dict) -> None:
        self.feeds += 1
        rps = serve.get("requests_per_second")
        if rps is not None:
            self.rps = (self.rps or 0.0) + rps
        queue = serve.get("queue_depth")
        if queue is not None:
            self.queue = (self.queue or 0.0) + queue
        ttft = serve.get("ttft_seconds")
        if ttft is not None and (self.ttft is None or ttft > self.ttft):
            self.ttft = ttft
        slo = serve.get("slo_attainment_ratio")
        if slo is not None:
            self.slo_sum += slo
            self.slo_n += 1
        batch = serve.get("batch_size")
        if batch is not None:
            self.batch_sum += batch
            self.batch_n += 1

    def to_dict(self) -> dict | None:
        if not self.feeds:
            return None
        out: dict = {"feeds": self.feeds}
        if self.rps is not None:
            out["requests_per_second"] = self.rps
        if self.queue is not None:
            out["queue_depth"] = self.queue
        if self.ttft is not None:
            out["ttft_seconds"] = self.ttft
        if self.slo_n:
            out["slo_attainment_ratio"] = self.slo_sum / self.slo_n
        if self.batch_n:
            out["batch_size"] = self.batch_sum / self.batch_n
        return out


class ActuatePlane:
    """Thread model: ``cycle`` runs on the collect thread only;
    ``rows``/``families``/``hints_response``/``debug_block`` may be
    called from HTTP threads — the published read model swaps wholesale
    under one lock, readers get the reference (rows are never mutated
    after publish)."""

    def __init__(
        self,
        *,
        hint_prefer: float = 0.6,
        hint_avoid: float = 0.25,
        hint_hold_cycles: int = 3,
        stale_after_s: float = 30.0,
        forecast_provider=None,
    ) -> None:
        self.hint_prefer = float(hint_prefer)
        self.hint_avoid = float(hint_avoid)
        self.stale_after_s = float(stale_after_s)
        self._hysteresis = HintHysteresis(hint_hold_cycles)
        # forecast_provider: the ledger plane's forecast_snapshot (or
        # None without a ledger) — feeds the adapter's pool-scope
        # tpumon_days_to_saturation metric.
        self.adapter = ExternalMetricsAdapter(
            self, forecast_provider=forecast_provider
        )
        self._lock = threading.Lock()
        self._rows: list[dict] = []  # guarded-by: self._lock
        self._pool_serve: dict[str, dict] = {}  # guarded-by: self._lock
        self._fleet_serve: dict | None = None  # guarded-by: self._lock
        self._last_cycle_ts = 0.0  # guarded-by: self._lock
        self._cycles = 0  # guarded-by: self._lock

    # -- collect-cycle hook -------------------------------------------------

    def cycle(
        self,
        now: float,
        doc: dict,
        entries: list,
        goodput_jobs: dict | None = None,
    ) -> None:
        """One collect cycle: aggregate serve joins off the entries,
        score + hysterese every slice in the rollup doc, publish."""
        slice_serve: dict[tuple[str, str], _ServeAgg] = {}
        pool_serve: dict[str, _ServeAgg] = {}
        fleet_serve = _ServeAgg()
        for entry in entries:
            snap, state = entry[1], entry[2]
            if state != "up" or not snap:
                # A stale feed's serve numbers are old news; the slice
                # row still surfaces (marked stale) via the rollup
                # bucket below, so staleness is visible, not silent.
                continue
            serve = snap.get("serve")
            if not serve:
                continue
            ident = snap.get("identity") or {}
            pool = ident.get("accelerator") or "unknown"
            slc = ident.get("slice") or "?"
            slice_serve.setdefault((pool, slc), _ServeAgg()).add(serve)
            pool_serve.setdefault(pool, _ServeAgg()).add(serve)
            fleet_serve.add(serve)

        jobs = goodput_jobs or {}
        rows: list[dict] = []
        live: set[tuple[str, str]] = set()
        for (pool, slc), bucket in sorted(doc.get("slices", {}).items()):
            key = (pool, slc)
            live.add(key)
            score, inputs = headroom_score(bucket, jobs.get(key))
            band = None
            if score is not None:
                band = self._hysteresis.update(
                    key, band_of(score, self.hint_prefer, self.hint_avoid)
                )
            agg = slice_serve.get(key)
            rows.append(
                {
                    "pool": pool,
                    "slice": slc,
                    "bucket": bucket,
                    "serve": agg.to_dict() if agg else None,
                    "score": score,
                    "band": band,
                    "inputs": inputs,
                    "stale": bool(bucket.get("stale")),
                    "ts": now,
                }
            )
        self._hysteresis.forget(live)

        with self._lock:
            self._rows = rows
            self._pool_serve = {
                pool: agg.to_dict()
                for pool, agg in sorted(pool_serve.items())
                if agg.feeds
            }
            self._fleet_serve = fleet_serve.to_dict()
            self._last_cycle_ts = now
            self._cycles += 1

    # -- read model ---------------------------------------------------------

    def rows(self) -> list[dict]:
        """The published per-slice rows (immutable after publish —
        callers may hold the reference across their whole request)."""
        with self._lock:
            return self._rows

    def is_stale(self, now: float) -> bool:
        """True when no collect cycle has published recently — served
        values then carry the stale flag rather than posing as current."""
        with self._lock:
            last = self._last_cycle_ts
        return last <= 0.0 or (now - last) > self.stale_after_s

    # -- exposition ---------------------------------------------------------

    def families(self) -> list:
        from tpumon.families import ACTUATE_FAMILIES

        def gauge(name):
            _, help_text, extra = ACTUATE_FAMILIES[name]
            return GaugeMetricFamily(name, help_text, labels=extra)

        def counter(name):
            _, help_text, extra = ACTUATE_FAMILIES[name]
            # prometheus_client appends _total on render.
            return CounterMetricFamily(
                name[: -len("_total")], help_text, labels=extra
            )

        with self._lock:
            rows = self._rows
            pool_serve = self._pool_serve
            fleet_serve = self._fleet_serve

        serve_fams = {
            key: gauge(f"tpu_fleet_serve_{key}") for key in _SERVE_KEYS
        }

        def emit_serve(labels: tuple, serve: dict | None) -> None:
            if not serve:
                return
            for key, fam in serve_fams.items():
                value = serve.get(key)
                if value is not None:
                    fam.add_metric(labels, value)

        score_fam = gauge("tpu_fleet_hint_headroom_score")
        band_fam = gauge("tpu_fleet_hint_band")
        trans_fam = counter("tpu_fleet_hint_transitions_total")
        pool_scores: dict[str, tuple[float, float]] = {}
        fleet_weight = fleet_score = 0.0
        for row in rows:
            labels = ("slice", row["pool"], row["slice"])
            emit_serve(labels, row["serve"])
            if row["score"] is None:
                continue
            score_fam.add_metric(labels, row["score"])
            chips = float(row["bucket"].get("chips") or 0) or 1.0
            w, s = pool_scores.get(row["pool"], (0.0, 0.0))
            pool_scores[row["pool"]] = (w + chips, s + chips * row["score"])
            fleet_weight += chips
            fleet_score += chips * row["score"]
            if row["band"]:
                for band in BANDS:
                    band_fam.add_metric(
                        (row["pool"], row["slice"], band),
                        1.0 if band == row["band"] else 0.0,
                    )
        for pool, (w, s) in sorted(pool_scores.items()):
            score_fam.add_metric(("pool", pool, ""), s / w)
        if fleet_weight:
            score_fam.add_metric(("fleet", "", ""), fleet_score / fleet_weight)
        for pool, serve in pool_serve.items():
            emit_serve(("pool", pool, ""), serve)
        emit_serve(("fleet", "", ""), fleet_serve)
        for (pool, slc), count in sorted(self._hysteresis.transitions.items()):
            trans_fam.add_metric((pool, slc), float(count))

        out = []
        for fam in (*serve_fams.values(), score_fam, band_fam, trans_fam):
            if fam.samples:
                out.append(fam)
        return out

    # -- query surfaces -----------------------------------------------------

    def hints_response(self, query_string: str = "") -> tuple[bytes, str]:
        """``GET /hints``: the per-slice hint table plus the annotation
        patch shapes (``?pool=`` narrows to one pool)."""
        from urllib.parse import parse_qs

        params = {
            k: v[-1] for k, v in parse_qs(query_string or "").items()
        }
        pool_filter = params.get("pool")
        with self._lock:
            rows = self._rows
            last_ts = self._last_cycle_ts
            cycles = self._cycles
        slices = []
        for row in rows:
            if pool_filter and row["pool"] != pool_filter:
                continue
            entry: dict = {
                "pool": row["pool"],
                "slice": row["slice"],
                "score": row["score"],
                "band": row["band"],
                "stale": row["stale"],
                "inputs": row["inputs"],
            }
            if row["score"] is not None and row["band"] is not None:
                annotations = {
                    ANNOTATION_SCORE: f"{row['score']:.3f}",
                    ANNOTATION_BAND: row["band"],
                }
                entry["annotations"] = annotations
                # Ready-to-apply strategic-merge patch for a scheduler
                # extender / descheduler (kubectl patch --type merge).
                entry["patch"] = {"metadata": {"annotations": annotations}}
            slices.append(entry)
        doc = {
            "ts": last_ts,
            "cycles": cycles,
            "thresholds": {
                "prefer": self.hint_prefer,
                "avoid": self.hint_avoid,
                "hold_cycles": self._hysteresis.hold_cycles,
            },
            "slices": slices,
        }
        return json.dumps(doc, sort_keys=True).encode(), "200 OK"

    def debug_block(self) -> dict:
        """The /debug/vars "actuate" block: O(1) state, no rows."""
        with self._lock:
            rows = self._rows
            last_ts = self._last_cycle_ts
            cycles = self._cycles
        return {
            "cycles": cycles,
            "last_cycle_ts": last_ts,
            "slices": len(rows),
            "serving_slices": sum(1 for r in rows if r["serve"]),
            "scored_slices": sum(1 for r in rows if r["score"] is not None),
            "hint_transitions": sum(
                self._hysteresis.transitions.values()
            ),
        }
