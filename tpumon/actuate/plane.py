"""The actuation plane wired into the aggregator's collect cycle.

One :meth:`ActuatePlane.cycle` call per collect pass, fed the SAME
rollup doc and feed entries the ledger gets — the plane:

1. rolls the lifecycle plane's serving join (``snap["serve"]``, parsed
   off node pages by tpumon/fleet/ingest.py) up per slice/pool/fleet;
2. scores every slice through the placement-hint engine
   (tpumon/actuate/hints.py) joining the rollup bucket with the goodput
   ledger's per-job history, and runs band hysteresis;
3. publishes the result as an immutable read model under one lock.

Every query surface — the External Metrics adapter, ``/hints``, the
``tpu_fleet_*`` families on the aggregator page — reads that model:
a query touches **no raw per-node series** and does no aggregation of
its own, the same read-model discipline as /fleet and the ledger.
"""

from __future__ import annotations

import json
import threading

from prometheus_client.core import CounterMetricFamily, GaugeMetricFamily

from tpumon.actuate.adapter import ExternalMetricsAdapter
from tpumon.actuate.hints import (
    BANDS,
    HintHysteresis,
    band_of,
    headroom_score,
)
from tpumon.actuate.trust import DEFAULT_MIN_TRUST, trust_score

#: Annotation keys published in the /hints patch shapes — what a
#: scheduler extender or descheduler reads off the node/pool objects.
ANNOTATION_SCORE = "tpumon.dev/headroom-score"
ANNOTATION_BAND = "tpumon.dev/placement-band"

_SERVE_KEYS = (
    "requests_per_second",
    "queue_depth",
    "ttft_seconds",
    "slo_attainment_ratio",
)


class _ServeAgg:
    """One scope's serving accumulator: throughput and queue SUM over
    feeds (independent request streams), TTFT takes the worst feed,
    SLO attainment and batch size average — the same merge the
    lifecycle plane applies across its feeds, one level up."""

    __slots__ = ("rps", "queue", "ttft", "slo_sum", "slo_n",
                 "batch_sum", "batch_n", "feeds")

    def __init__(self) -> None:
        self.rps: float | None = None
        self.queue: float | None = None
        self.ttft: float | None = None
        self.slo_sum = 0.0
        self.slo_n = 0
        self.batch_sum = 0.0
        self.batch_n = 0
        self.feeds = 0

    def add(self, serve: dict) -> None:
        self.feeds += 1
        rps = serve.get("requests_per_second")
        if rps is not None:
            self.rps = (self.rps or 0.0) + rps
        queue = serve.get("queue_depth")
        if queue is not None:
            self.queue = (self.queue or 0.0) + queue
        ttft = serve.get("ttft_seconds")
        if ttft is not None and (self.ttft is None or ttft > self.ttft):
            self.ttft = ttft
        slo = serve.get("slo_attainment_ratio")
        if slo is not None:
            self.slo_sum += slo
            self.slo_n += 1
        batch = serve.get("batch_size")
        if batch is not None:
            self.batch_sum += batch
            self.batch_n += 1

    def to_dict(self) -> dict | None:
        if not self.feeds:
            return None
        out: dict = {"feeds": self.feeds}
        if self.rps is not None:
            out["requests_per_second"] = self.rps
        if self.queue is not None:
            out["queue_depth"] = self.queue
        if self.ttft is not None:
            out["ttft_seconds"] = self.ttft
        if self.slo_n:
            out["slo_attainment_ratio"] = self.slo_sum / self.slo_n
        if self.batch_n:
            out["batch_size"] = self.batch_sum / self.batch_n
        return out


class ActuatePlane:
    """Thread model: ``cycle`` runs on the collect thread only;
    ``rows``/``families``/``hints_response``/``debug_block`` may be
    called from HTTP threads — the published read model swaps wholesale
    under one lock, readers get the reference (rows are never mutated
    after publish)."""

    def __init__(
        self,
        *,
        hint_prefer: float = 0.6,
        hint_avoid: float = 0.25,
        hint_hold_cycles: int = 3,
        stale_after_s: float = 30.0,
        min_trust: float = DEFAULT_MIN_TRUST,
        hint_decay_s: float = 120.0,
        forecast_provider=None,
    ) -> None:
        self.hint_prefer = float(hint_prefer)
        self.hint_avoid = float(hint_avoid)
        self.stale_after_s = float(stale_after_s)
        #: Trust floor (tpumon/actuate/trust.py): rows scoring below it
        #: are WITHHELD — absent from External Metrics answers, frozen
        #: on /hints — instead of steering a controller off degraded
        #: telemetry.
        self.min_trust = float(min_trust)
        #: How long a frozen (untrusted) hint band holds at last-good
        #: before decaying to ``neutral``: last-good is the right answer
        #: for a blip, but a scheduler must not steer on hour-old bands.
        self.hint_decay_s = float(hint_decay_s)
        self._hysteresis = HintHysteresis(hint_hold_cycles)
        # forecast_provider: the ledger plane's forecast_snapshot (or
        # None without a ledger) — feeds the adapter's pool-scope
        # tpumon_days_to_saturation metric.
        self.adapter = ExternalMetricsAdapter(
            self, forecast_provider=forecast_provider
        )
        self._lock = threading.Lock()
        self._rows: list[dict] = []  # guarded-by: self._lock
        self._pool_serve: dict[str, dict] = {}  # guarded-by: self._lock
        self._fleet_serve: dict | None = None  # guarded-by: self._lock
        self._last_cycle_ts = 0.0  # guarded-by: self._lock
        self._cycles = 0  # guarded-by: self._lock
        self._scope_epochs: dict[tuple[str, str], int] = {}  # guarded-by: self._lock
        self._contested = False  # guarded-by: self._lock
        #: Bands to warm-seed into the hysteresis at the next cycle
        #: (spool restore or peer /hints on takeover). Written from the
        #: startup/membership threads, drained on the collect thread —
        #: the queue keeps the hysteresis itself single-threaded.
        self._band_seed: list[list] = []  # guarded-by: self._lock
        #: Collect-thread-only trust bookkeeping (same thread model as
        #: the hysteresis): freeze start per scope, and the monotonic
        #: withheld / epoch-conflict counters families() exposes.
        self._frozen_since: dict[tuple[str, str], float] = {}
        self._withheld_counts: dict[tuple[str, str, str], int] = {}
        self._epoch_conflicts: dict[tuple[str, str], int] = {}

    # -- collect-cycle hook -------------------------------------------------

    def cycle(
        self,
        now: float,
        doc: dict,
        entries: list,
        goodput_jobs: dict | None = None,
        *,
        target_epochs: dict | None = None,
        peer_scope_epochs: dict | None = None,
        restored_targets: set | None = None,
        contested: bool = False,
    ) -> None:
        """One collect cycle: aggregate serve joins off the entries,
        score + trust-gate + hysterese every slice in the rollup doc,
        publish.

        ``target_epochs`` (target -> ownership epoch, from the
        membership plane) and ``peer_scope_epochs`` ((pool, slice) ->
        highest epoch any ALIVE peer claims for the scope) drive the
        split-brain resolution: a scope a peer claims at a NEWER epoch
        is withheld here — the newer owner answers, this shard counts
        the conflict. ``restored_targets`` and ``contested`` feed the
        trust score (spool-restore warmth, double-owned window)."""
        with self._lock:
            band_seed, self._band_seed = self._band_seed, []
        if band_seed:
            self._hysteresis.seed(band_seed)

        slice_serve: dict[tuple[str, str], _ServeAgg] = {}
        pool_serve: dict[str, _ServeAgg] = {}
        fleet_serve = _ServeAgg()
        #: Per-scope trust/epoch inputs joined off the same entries
        #: pass: member feed counts, how many serve restored (spool)
        #: data, and the highest ownership epoch among member targets.
        members: dict[tuple[str, str], int] = {}
        restored: dict[tuple[str, str], int] = {}
        scope_epochs: dict[tuple[str, str], int] = {}
        epochs = target_epochs or {}
        warm = restored_targets or ()
        for entry in entries:
            target, snap, state = entry[0], entry[1], entry[2]
            if not snap:
                continue
            ident = snap.get("identity") or {}
            pool = ident.get("accelerator") or "unknown"
            slc = ident.get("slice") or "?"
            key = (pool, slc)
            members[key] = members.get(key, 0) + 1
            if target in warm:
                restored[key] = restored.get(key, 0) + 1
            epoch = epochs.get(target)
            if epoch:
                scope_epochs[key] = max(scope_epochs.get(key, 0), epoch)
            if state != "up":
                # A stale feed's serve numbers are old news; the slice
                # row still surfaces (marked stale) via the rollup
                # bucket below, so staleness is visible, not silent.
                continue
            serve = snap.get("serve")
            if not serve:
                continue
            slice_serve.setdefault(key, _ServeAgg()).add(serve)
            pool_serve.setdefault(pool, _ServeAgg()).add(serve)
            fleet_serve.add(serve)

        jobs = goodput_jobs or {}
        peer_epochs = peer_scope_epochs or {}
        rows: list[dict] = []
        live: set[tuple[str, str]] = set()
        for (pool, slc), bucket in sorted(doc.get("slices", {}).items()):
            key = (pool, slc)
            live.add(key)
            n = members.get(key, 0)
            trust, trust_inputs = trust_score(
                visibility=bucket.get("visibility"),
                stale=bool(bucket.get("stale")),
                contested=contested,
                restored_fraction=(restored.get(key, 0) / n) if n else 0.0,
            )
            epoch = scope_epochs.get(key, 0)
            peer_epoch = peer_epochs.get(key)
            # Epoch conflicts only exist while a double answer does:
            # rendezvous splits a slice's targets across shards, so two
            # shards LEGITIMATELY hold different epochs for one scope in
            # steady state — epochs disagreeing is normal; epochs
            # disagreeing while the rollup is CONTESTED (more hosts
            # reported than the universe holds — two shards answering
            # for the same targets) is split brain. Resolution is
            # newest-epoch-wins: the older claim withholds, the newer
            # claim serves; both sides count the conflict.
            conflicted = False
            if contested and epoch and peer_epoch and peer_epoch != epoch:
                self._epoch_conflicts[key] = (
                    self._epoch_conflicts.get(key, 0) + 1
                )
                conflicted = peer_epoch > epoch
            withheld_reason = None
            if conflicted:
                # Our claim is the OLDER one: the peer answers; serving
                # our copy alongside would flap the HPA between two
                # truths.
                withheld_reason = "epoch_conflict"
            elif trust < self.min_trust:
                withheld_reason = "untrusted"
            if withheld_reason is not None:
                wkey = (pool, slc, withheld_reason)
                self._withheld_counts[wkey] = (
                    self._withheld_counts.get(wkey, 0) + 1
                )
            score, inputs = headroom_score(bucket, jobs.get(key))
            band = None
            frozen = False
            if score is not None:
                raw_band = band_of(score, self.hint_prefer, self.hint_avoid)
                if withheld_reason is None:
                    self._frozen_since.pop(key, None)
                    band = self._hysteresis.update(key, raw_band)
                else:
                    # Freeze: the degraded score never reaches the
                    # hysteresis — hints hold at last-good, then decay
                    # to neutral once degradation outlives the window.
                    frozen = True
                    since = self._frozen_since.setdefault(key, now)
                    band = self._hysteresis.published_band(key)
                    if band is None or (now - since) > self.hint_decay_s:
                        band = "neutral"
            rows.append(
                {
                    "pool": pool,
                    "slice": slc,
                    "bucket": bucket,
                    "serve": slice_serve[key].to_dict()
                    if key in slice_serve
                    else None,
                    "score": score,
                    "band": band,
                    "inputs": inputs,
                    "stale": bool(bucket.get("stale")),
                    "trust": trust,
                    "trust_inputs": trust_inputs,
                    "epoch": epoch,
                    "withheld": withheld_reason is not None,
                    "withheld_reason": withheld_reason,
                    "band_frozen": frozen,
                    "ts": now,
                }
            )
        self._hysteresis.forget(live)
        for key in [k for k in self._frozen_since if k not in live]:
            del self._frozen_since[key]

        with self._lock:
            self._rows = rows
            self._pool_serve = {
                pool: agg.to_dict()
                for pool, agg in sorted(pool_serve.items())
                if agg.feeds
            }
            self._fleet_serve = fleet_serve.to_dict()
            self._scope_epochs = scope_epochs
            self._contested = bool(contested)
            self._last_cycle_ts = now
            self._cycles += 1

    # -- read model ---------------------------------------------------------

    def rows(self) -> list[dict]:
        """The published per-slice rows (immutable after publish —
        callers may hold the reference across their whole request)."""
        with self._lock:
            return self._rows

    def is_stale(self, now: float) -> bool:
        """True when no collect cycle has published recently — served
        values then carry the stale flag rather than posing as current."""
        with self._lock:
            last = self._last_cycle_ts
        return last <= 0.0 or (now - last) > self.stale_after_s

    def scope_epochs(self) -> dict[tuple[str, str], int]:
        """Published (pool, slice) -> ownership epoch map — what
        /fleet/summary advertises so PEERS can detect a conflicting
        (older) claim for a scope this shard owns."""
        with self._lock:
            return dict(self._scope_epochs)

    def published_bands(self) -> list[list]:
        """Currently-published (pool, slice, band) rows off the READ
        MODEL — safe from any thread; what /fleet/summary advertises so
        a peer adopting our targets can seed its hysteresis warm."""
        with self._lock:
            rows = self._rows
        return [
            [row["pool"], row["slice"], row["band"]]
            for row in rows
            if row["band"]
        ]

    def band_state(self) -> list[list]:
        """Spool-serializable published-band state. Collect thread
        only (reads the hysteresis) — the server captures it inside
        the collect cycle before handing the spool save off."""
        return self._hysteresis.export_state()

    def seed_bands(self, state) -> None:
        """Queue bands (export_state shape) to warm-seed into the
        hysteresis at the next cycle. Safe from any thread — a spool
        restore at startup, or the membership thread adopting targets
        whose bands a peer already published."""
        rows = [
            list(row)
            for row in state or []
            if isinstance(row, (list, tuple)) and len(row) == 3
        ]
        if rows:
            with self._lock:
                self._band_seed.extend(rows)

    # -- exposition ---------------------------------------------------------

    def families(self) -> list:
        from tpumon.families import ACTUATE_FAMILIES

        def gauge(name):
            _, help_text, extra = ACTUATE_FAMILIES[name]
            return GaugeMetricFamily(name, help_text, labels=extra)

        def counter(name):
            _, help_text, extra = ACTUATE_FAMILIES[name]
            # prometheus_client appends _total on render.
            return CounterMetricFamily(
                name[: -len("_total")], help_text, labels=extra
            )

        with self._lock:
            rows = self._rows
            pool_serve = self._pool_serve
            fleet_serve = self._fleet_serve

        serve_fams = {
            key: gauge(f"tpu_fleet_serve_{key}") for key in _SERVE_KEYS
        }

        def emit_serve(labels: tuple, serve: dict | None) -> None:
            if not serve:
                return
            for key, fam in serve_fams.items():
                value = serve.get(key)
                if value is not None:
                    fam.add_metric(labels, value)

        score_fam = gauge("tpu_fleet_hint_headroom_score")
        band_fam = gauge("tpu_fleet_hint_band")
        trans_fam = counter("tpu_fleet_hint_transitions_total")
        trust_fam = gauge("tpu_actuate_trust_score")
        epoch_fam = gauge("tpu_actuate_scope_epoch")
        frozen_fam = gauge("tpu_actuate_hint_frozen")
        withheld_fam = counter("tpu_actuate_withheld_total")
        conflict_fam = counter("tpu_actuate_epoch_conflicts_total")
        pool_scores: dict[str, tuple[float, float]] = {}
        fleet_weight = fleet_score = 0.0
        for row in rows:
            labels = ("slice", row["pool"], row["slice"])
            emit_serve(labels, row["serve"])
            scope = (row["pool"], row["slice"])
            if row.get("trust") is not None:
                trust_fam.add_metric(scope, row["trust"])
            if row.get("epoch"):
                epoch_fam.add_metric(scope, float(row["epoch"]))
            if row["band"] is not None:
                frozen_fam.add_metric(
                    scope, 1.0 if row.get("band_frozen") else 0.0
                )
            if row["score"] is None:
                continue
            score_fam.add_metric(labels, row["score"])
            chips = float(row["bucket"].get("chips") or 0) or 1.0
            w, s = pool_scores.get(row["pool"], (0.0, 0.0))
            pool_scores[row["pool"]] = (w + chips, s + chips * row["score"])
            fleet_weight += chips
            fleet_score += chips * row["score"]
            if row["band"]:
                for band in BANDS:
                    band_fam.add_metric(
                        (row["pool"], row["slice"], band),
                        1.0 if band == row["band"] else 0.0,
                    )
        for pool, (w, s) in sorted(pool_scores.items()):
            score_fam.add_metric(("pool", pool, ""), s / w)
        if fleet_weight:
            score_fam.add_metric(("fleet", "", ""), fleet_score / fleet_weight)
        for pool, serve in pool_serve.items():
            emit_serve(("pool", pool, ""), serve)
        emit_serve(("fleet", "", ""), fleet_serve)
        for (pool, slc), count in sorted(self._hysteresis.transitions.items()):
            trans_fam.add_metric((pool, slc), float(count))
        for (pool, slc, reason), count in sorted(
            self._withheld_counts.items()
        ):
            withheld_fam.add_metric((pool, slc, reason), float(count))
        for (pool, slc), count in sorted(self._epoch_conflicts.items()):
            conflict_fam.add_metric((pool, slc), float(count))

        out = []
        for fam in (
            *serve_fams.values(),
            score_fam,
            band_fam,
            trans_fam,
            trust_fam,
            epoch_fam,
            frozen_fam,
            withheld_fam,
            conflict_fam,
        ):
            if fam.samples:
                out.append(fam)
        return out

    # -- query surfaces -----------------------------------------------------

    def hints_response(self, query_string: str = "") -> tuple[bytes, str]:
        """``GET /hints``: the per-slice hint table plus the annotation
        patch shapes (``?pool=`` narrows to one pool)."""
        from urllib.parse import parse_qs

        params = {
            k: v[-1] for k, v in parse_qs(query_string or "").items()
        }
        pool_filter = params.get("pool")
        with self._lock:
            rows = self._rows
            last_ts = self._last_cycle_ts
            cycles = self._cycles
        slices = []
        for row in rows:
            if pool_filter and row["pool"] != pool_filter:
                continue
            entry: dict = {
                "pool": row["pool"],
                "slice": row["slice"],
                "score": row["score"],
                "band": row["band"],
                "stale": row["stale"],
                "inputs": row["inputs"],
                "trust": row.get("trust"),
                "trust_inputs": row.get("trust_inputs", {}),
                "withheld": bool(row.get("withheld")),
                "frozen": bool(row.get("band_frozen")),
            }
            if row.get("withheld_reason"):
                entry["withheld_reason"] = row["withheld_reason"]
            if row.get("epoch"):
                entry["epoch"] = row["epoch"]
            if row["score"] is not None and row["band"] is not None:
                annotations = {
                    ANNOTATION_SCORE: f"{row['score']:.3f}",
                    ANNOTATION_BAND: row["band"],
                }
                entry["annotations"] = annotations
                # Ready-to-apply strategic-merge patch for a scheduler
                # extender / descheduler (kubectl patch --type merge).
                entry["patch"] = {"metadata": {"annotations": annotations}}
            slices.append(entry)
        doc = {
            "ts": last_ts,
            "cycles": cycles,
            "thresholds": {
                "prefer": self.hint_prefer,
                "avoid": self.hint_avoid,
                "hold_cycles": self._hysteresis.hold_cycles,
                "min_trust": self.min_trust,
                "hint_decay_s": self.hint_decay_s,
            },
            "slices": slices,
        }
        return json.dumps(doc, sort_keys=True).encode(), "200 OK"

    def debug_block(self) -> dict:
        """The /debug/vars "actuate" block: O(1) state, no rows."""
        with self._lock:
            rows = self._rows
            last_ts = self._last_cycle_ts
            cycles = self._cycles
            contested = self._contested
        return {
            "cycles": cycles,
            "last_cycle_ts": last_ts,
            "slices": len(rows),
            "serving_slices": sum(1 for r in rows if r["serve"]),
            "scored_slices": sum(1 for r in rows if r["score"] is not None),
            "hint_transitions": sum(
                self._hysteresis.transitions.values()
            ),
            "min_trust": self.min_trust,
            "contested": contested,
            "withheld_slices": sum(1 for r in rows if r.get("withheld")),
            "frozen_slices": sum(1 for r in rows if r.get("band_frozen")),
            "withheld_total": sum(self._withheld_counts.values()),
            "epoch_conflicts_total": sum(self._epoch_conflicts.values()),
        }
