"""Operational self-test CLI — the `dcgmi discovery` analogue.

``python -m tpumon.doctor [--backend ...]`` prints what the exporter
would see on this node: backend resolution, topology identity, per-metric
sample status (ok / empty=runtime-detached / error), coverage vs the ≥95%
BASELINE target, device-health verdicts (tpumon.health), and
pod-attribution availability. Exit code 0 when coverage meets the target
(or the node is a deviceless stub) AND no device-health check is crit;
1 otherwise — usable as an init-container sanity gate.
"""

from __future__ import annotations

import sys
from collections import Counter

from tpumon.backends import create_backend
from tpumon.backends.base import BackendError
from tpumon.config import Config
from tpumon.health import COVERAGE_TARGET
from tpumon.parsing import parse
from tpumon.schema import coverage, spec_for


class _CachedBackend:
    """Memoizes sample() results (including failures) so the health
    snapshot reuses the per-metric loop's device queries instead of
    hitting the runtime a second time."""

    def __init__(self, backend) -> None:
        self._backend = backend
        self._samples: dict[str, tuple[bool, object]] = {}

    def sample(self, name: str):
        if name not in self._samples:
            try:
                self._samples[name] = (True, self._backend.sample(name))
            except Exception as exc:
                self._samples[name] = (False, exc)
        ok, value = self._samples[name]
        if not ok:
            raise value
        return value

    def __getattr__(self, attr):
        return getattr(self._backend, attr)


def run(cfg: Config, out=sys.stdout, backend=None) -> int:
    """``backend`` overrides creation from cfg (tests, embedding); a
    caller-supplied backend is NOT closed — the caller owns it."""

    def p(line: str = "") -> None:
        print(line, file=out)

    owned = backend is None
    try:
        backend = _CachedBackend(backend or create_backend(cfg))
    except BackendError as exc:
        p(f"backend: FAILED to initialize ({exc})")
        return 1

    try:
        version_fn = getattr(backend, "version", None)
        p(f"backend: {backend.name} (version {version_fn() if version_fn else '?'})")

        topo = backend.topology()
        p(
            f"topology: {topo.accelerator_type} | slice={topo.slice_name} "
            f"host={topo.hostname} worker={topo.worker_id}/{topo.num_hosts} "
            f"chips={topo.num_chips} cores={topo.num_cores}"
        )
        for chip in topo.chips:
            coords = ",".join(str(c) for c in chip.coords) if chip.coords else "?"
            p(f"  chip {chip.index}: coords=({coords}) id={chip.device_id}")

        try:
            supported = backend.list_metrics()
        except Exception as exc:
            p(f"metrics: enumeration FAILED ({exc})")
            return 1

        p(f"\nmetrics ({len(supported)} supported):")
        attached = False
        for name in supported:
            spec = spec_for(name)
            if spec is None:
                p(f"  {name:34s} -> UNMAPPED (coverage gap)")
                continue
            try:
                raw = backend.sample(name)
            except Exception as exc:
                p(f"  {name:34s} -> ERROR: {exc}")
                continue
            if raw.empty:
                p(f"  {name:34s} -> {spec.family} (no data: runtime detached)")
                continue
            result = parse(raw, spec)
            attached = True
            p(
                f"  {name:34s} -> {spec.family} "
                f"({len(result.points)} points"
                + (f", {result.errors} parse errors" if result.errors else "")
                + ")"
            )

        # Merged-transport accounting (grpc backend, SURVEY §3.3): each
        # unified metric counts once, routed to exactly one transport.
        sources_fn = getattr(backend, "sources", None)
        if sources_fn is not None:
            routes = Counter(sources_fn().values())
            if routes:
                p(
                    "transport routing: "
                    + ", ".join(
                        f"{n} via {src}" for src, n in sorted(routes.items())
                    )
                )
        watch_fn = getattr(backend, "watch_states", None)
        if watch_fn is not None:
            states = Counter(watch_fn().values())
            if states:
                p(
                    "watch streams: "
                    + ", ".join(
                        f"{n} {state}" for state, n in sorted(states.items())
                    )
                )
        renames_fn = getattr(backend, "suspected_renames", None)
        if renames_fn is not None:
            for server_name, sdk_name in sorted(renames_fn().items()):
                p(
                    f"WARNING: service metric {server_name!r} looks like "
                    f"SDK metric {sdk_name!r} renamed — suppressed from "
                    "the merged list so coverage counts it once; add it "
                    "to GRPC_METRIC_ALIASES if the mapping is confirmed"
                )

        # Env-aware target: the same TPUMON_HEALTH_COVERAGE_TARGET knob
        # the health evaluator honors (doctor gates CI/init containers,
        # so its verdict must match the configured contract, not the
        # compiled default).
        from tpumon.health import env_thresholds

        target = env_thresholds().coverage_target
        cov = coverage(supported)
        p(f"\ncoverage: {cov:.1%} (target >= {target:.0%})")
        if supported and not attached:
            p(
                "note: all metrics empty — no runtime/workload attached to "
                "the accelerator (expected on idle nodes; SURVEY.md §2.2)"
            )

        # Device-health verdicts (the dcgmi `health -c` analogue): the
        # poll cycle computes the report (PollStats.health) — the exact
        # doc /health/devices serves — and the _CachedBackend makes it
        # reuse the loop's samples, so zero extra device queries.
        from tpumon import health as health_mod
        from tpumon.exporter.collector import build_families
        from tpumon.trace import Tracer

        # Trace the one cycle doctor runs (tpumon.trace) — the same span
        # tree a live exporter serves at /debug/traces, printed below as
        # the per-stage breakdown.
        tracer = Tracer(slow_cycle_ms=float("inf"), ring=1)
        with tracer.cycle():
            _, stats = build_families(backend, cfg)
        health_doc = stats.health or {"status": health_mod.OK, "findings": []}
        health_status = health_doc["status"]
        p(f"\ndevice health: {health_status.upper()}")
        for f in health_doc["findings"]:
            p(f"  [{f['severity']}] {f['message']}")

        # Slowest stages of that cycle, duration-sorted — the "which
        # stage would eat a 1 Hz budget on this node" answer without a
        # running exporter.
        (trace_doc,) = tracer.traces() or ({"spans": (), "duration_seconds": 0.0},)
        stages = sorted(
            trace_doc["spans"],
            key=lambda s: -s["duration_seconds"],
        )
        if stages:
            p(
                "\npoll stage breakdown (one cycle, "
                f"{trace_doc['duration_seconds'] * 1e3:.1f} ms total):"
            )
            for s in stages[:6]:
                p(f"  {s['name']:<28s} {s['duration_seconds'] * 1e3:8.2f} ms")

        # Fault-tolerance plane (tpumon/resilience): the policy a live
        # exporter would run with this config, plus the chaos notice —
        # an operator reading doctor output during an incident needs to
        # know whether fault injection is part of the picture. Live
        # breaker/staleness state comes from the running exporter
        # (GET /debug/vars "resilience", or the smi DEGRADED line).
        if cfg.resilience:
            p(
                "\nresilience: enabled — retries "
                f"{max(1, cfg.retry_attempts) - 1} per call, breaker "
                f"opens after {cfg.breaker_failures} consecutive "
                f"failures ({cfg.breaker_open_s:.0f}s probe window), "
                f"last-good families served up to {cfg.stale_serve_s:.0f}s"
                + (
                    f", watchdog recovers hangs after "
                    f"{cfg.watchdog_hang_s:.0f}s"
                    if cfg.watchdog_hang_s > 0
                    else ", watchdog disabled"
                )
            )
        else:
            p("\nresilience: disabled (TPUMON_RESILIENCE=0)")

        # Self-protection plane (tpumon/guard): the admission-control /
        # watermark policy a live exporter would run with this config.
        # Live shed counts come from the running exporter (GET
        # /debug/vars "guard", or the smi GUARD line).
        if cfg.guard:
            from tpumon.guard.memwatch import resolve_watermarks

            soft_b, hard_b = resolve_watermarks(
                cfg.guard_soft_rss_mb, cfg.guard_hard_rss_mb
            )
            if soft_b or hard_b:
                watermarks = (
                    f"memory watermarks soft {soft_b / 1e6:.0f} MB / "
                    f"hard {hard_b / 1e6:.0f} MB"
                )
            else:
                watermarks = (
                    "memory watermarks disarmed (no container memory "
                    "limit detected)"
                )
            p(
                "self-protection: enabled — debug endpoints "
                f"{cfg.guard_debug_rps:g} rps / {cfg.guard_debug_inflight} "
                f"in flight, /metrics {cfg.guard_metrics_inflight} in "
                f"flight, header deadline {cfg.guard_header_timeout_s:g}s, "
                f"series budget {cfg.guard_max_series_per_family}/family, "
                + watermarks
            )
        else:
            p("self-protection: disabled (TPUMON_GUARD=0)")
        fault_spec = getattr(backend, "spec", None)
        if cfg.faults or fault_spec is not None:
            desc = (
                fault_spec.describe()
                if fault_spec is not None and hasattr(fault_spec, "describe")
                else cfg.faults
            )
            p(f"WARNING: fault injection ACTIVE (TPUMON_FAULTS): {desc}")

        # Streaming anomaly detection (tpumon.anomaly): doctor runs ONE
        # poll cycle, and every detector needs warmup/streaks, so there is
        # no verdict to print here — only the armed roster. Live verdicts
        # (shared ok/warn/crit ordering) come from the running exporter:
        # GET /anomalies, or the `tpumon smi` anomalies line.
        # Energy/cost plane (tpumon/energy): which power source this
        # node would report — measured when the device library lists a
        # power metric, otherwise the duty×TDP model with the table row
        # (or override) it rides on. The operator's "can I trust the
        # watts" answer without a running exporter.
        if cfg.energy:
            from tpumon.energy import env_thresholds as energy_tuning
            from tpumon.energy import tdp_for
            from tpumon.schema import SPECS_BY_FAMILY

            power_spec = SPECS_BY_FAMILY["accelerator_power_watts"]
            has_power = power_spec.source in supported
            et = energy_tuning()
            tdp_w, tdp_key = tdp_for(topo.accelerator_type, et)
            if has_power:
                p(
                    "energy: power source MEASURED (device metric "
                    f"{power_spec.source}); model fallback duty×TDP "
                    f"{tdp_w:.0f} W/chip ({tdp_key})"
                )
            else:
                p(
                    "energy: power source MODELED — no device power "
                    f"telemetry; duty×TDP {tdp_w:.0f} W/chip "
                    f"({tdp_key}; override via TPUMON_ENERGY_TDP_W)"
                    + (
                        f", ${et.dollars_per_kwh:g}/kWh"
                        if et.dollars_per_kwh > 0
                        else ", cost family off (TPUMON_ENERGY_DOLLARS_PER_KWH unset)"
                    )
                )
        else:
            p("energy: disabled (TPUMON_ENERGY=0)")

        if cfg.anomaly:
            from tpumon.anomaly import DETECTOR_NAMES

            roster = list(DETECTOR_NAMES)
            if cfg.hostcorr:
                from tpumon.hostcorr import HOSTCORR_DETECTOR_NAMES

                roster += list(HOSTCORR_DETECTOR_NAMES)
            if cfg.energy:
                from tpumon.energy import ENERGY_DETECTOR_NAMES

                roster += list(ENERGY_DETECTOR_NAMES)
            p(
                "anomaly detection: enabled (detectors: "
                + ", ".join(roster)
                + "; verdicts stream from the exporter's GET /anomalies)"
            )
        else:
            p("anomaly detection: disabled (TPUMON_ANOMALY=0)")

        # Host-correlation plane (tpumon/hostcorr): probe the host-signal
        # groups once — the "would straggler attribution work on this
        # node" answer. Procfs reads only, zero device queries; live
        # verdicts come from the exporter's GET /hostcorr.
        if cfg.hostcorr:
            import time as _time

            from tpumon.hostcorr import SIGNAL_GROUPS, HostSampler

            probe = HostSampler(cfg.hostcorr_proc_root)
            host_sig = probe.sample(_time.time())
            group_s = ", ".join(
                f"{g}={'ok' if host_sig.groups.get(g) else 'ABSENT'}"
                for g in SIGNAL_GROUPS
            )
            root_s = (
                f" (proc root {cfg.hostcorr_proc_root})"
                if cfg.hostcorr_proc_root
                else ""
            )
            if host_sig.available:
                pods = len(host_sig.sched)
                p(
                    f"host correlation: enabled — {group_s}; "
                    f"{pods} kubepods pod(s) mapped{root_s}"
                )
            else:
                p(
                    "host correlation: enabled but NO host signals "
                    f"readable ({group_s}){root_s} — straggler verdicts "
                    "degrade to device-only attribution"
                )
        else:
            p("host correlation: disabled (TPUMON_HOSTCORR=0)")

        # Invariant analyzer (tpumon/analysis, docs/INVARIANTS.md): the
        # last `python -m tpumon.tools.check` verdict + its age, so an
        # operator can see whether the checkout's cross-file discipline
        # (knobs/families/locks/deadlines) was ever proven, and when.
        p(_invariants_line())

        from tpumon.attribution import PodResourcesClient

        # Runtime monitoring gRPC endpoint: reachability + (when the
        # server speaks reflection) the actual service names.
        reachable_fn = getattr(backend, "service_reachable", None)
        if reachable_fn is not None:
            prefix = f"monitoring grpc ({getattr(backend, 'addr', '?')}): "
            available_fn = getattr(backend, "grpc_available", None)
            if available_fn is not None and not available_fn():
                # Missing Python dep, NOT a runtime problem — don't send
                # the operator off to debug the TPU.
                p(prefix + "cannot probe (grpcio unavailable)")
            elif reachable_fn():
                line = prefix + "reachable"
                services = getattr(backend, "services", lambda: None)()
                if services:
                    line += " — services: " + ", ".join(services)
                p(line)
            else:
                p(prefix + "unreachable (no runtime attached)")

        client = PodResourcesClient(cfg.kubelet_socket, cfg.grpc_timeout)
        devices = client.list_devices()
        client.close()
        if devices is None:
            p("pod attribution: unavailable (no kubelet socket / grpcio)")
        else:
            p(f"pod attribution: OK ({len(devices)} accelerator allocations)")

        if topo.num_chips == 0 and not supported:
            p("\nverdict: OK (deviceless node, stub mode)")
            return 0
        if health_status == health_mod.CRIT:
            p("\nverdict: DEVICE HEALTH CRITICAL")
            return 1
        if cov >= target:
            p("\nverdict: OK")
            return 0
        p("\nverdict: COVERAGE BELOW TARGET")
        return 1
    finally:
        if owned:
            backend.close()


def _invariants_line(now: float | None = None) -> str:
    """One doctor line from the analyzer stamp (never gates the exit
    code — discipline status is advisory here, enforced in CI)."""
    import time as _time

    from tpumon.analysis import ANALYZER_VERSION, baseline_count, stamp_info

    stamp = stamp_info()
    baselined = baseline_count()
    if stamp is None:
        return (
            f"invariants: not checked (analyzer {ANALYZER_VERSION}, "
            f"{baselined} baselined) — run python -m tpumon.tools.check"
        )
    age = max(0.0, (now if now is not None else _time.time()) - stamp.get("ts", 0.0))
    if age < 120:
        age_s = f"{age:.0f}s ago"
    elif age < 7200:
        age_s = f"{age / 60:.0f}m ago"
    else:
        age_s = f"{age / 3600:.1f}h ago"
    by_rule = stamp.get("new_by_rule") or {}
    per_rule = (
        " [" + ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items())) + "]"
        if by_rule
        else ""
    )
    verdict = "ok" if stamp.get("ok") else (
        f"{stamp.get('new_violations', '?')} NEW violations{per_rule}"
        + (
            f", {stamp['stale_baseline_entries']} stale baseline entries"
            if stamp.get("stale_baseline_entries")
            else ""
        )
    )
    return (
        f"invariants: {verdict} ({stamp.get('baselined', baselined)} "
        f"baselined; checked {age_s}, analyzer "
        f"{stamp.get('analyzer_version', ANALYZER_VERSION)})"
    )


def run_aggregator(url: str, out=sys.stdout, timeout: float = 5.0) -> int:
    """``--aggregator URL`` mode: one actuation-health probe against a
    running fleet aggregator — is the actuation surface TRUSTWORTHY
    right now (trust floor, withheld/frozen scopes, epoch conflicts,
    contested ownership)? Exit 0 when every scored scope answers; 1
    when any answer is being withheld or the probe fails."""
    import json as _json
    import urllib.error
    import urllib.request

    def p(line: str = "") -> None:
        print(line, file=out)

    base = url.rstrip("/")
    if not base.startswith(("http://", "https://")):
        base = "http://" + base
    try:
        with urllib.request.urlopen(
            base + "/debug/vars", timeout=timeout
        ) as resp:
            doc = _json.loads(resp.read().decode())
    except (urllib.error.URLError, OSError, ValueError) as exc:
        p(f"aggregator {base}: UNREACHABLE ({exc})")
        return 1
    p(f"aggregator {base}: up (cycles {doc.get('cycles', '?')})")
    membership = doc.get("membership") or {}
    p(
        f"membership: universe {membership.get('universe', '?')}, "
        f"owned {membership.get('owned', '?')}, alive shards "
        f"{membership.get('alive_shards', '?')}, epoch_seq "
        f"{membership.get('epoch_seq', 0)}, takeovers "
        f"{membership.get('takeovers_total', 0)}"
    )
    actuate = doc.get("actuate")
    if not actuate:
        p("actuation: disabled (TPUMON_FLEET_ACTUATE=0)")
        p("\nverdict: OK (observation-only aggregator)")
        return 0
    p(
        f"actuation: trust floor {actuate.get('min_trust', 0.0):.2f}, "
        f"{actuate.get('scored_slices', 0)} scored / "
        f"{actuate.get('slices', 0)} slices"
    )
    withheld = actuate.get("withheld_slices", 0)
    frozen = actuate.get("frozen_slices", 0)
    conflicts = actuate.get("epoch_conflicts_total", 0)
    if actuate.get("contested"):
        p(
            "  CONTESTED: two shards briefly own overlapping targets "
            "(takeover window; self-healing)"
        )
    if conflicts:
        p(
            f"  epoch conflicts since start: {conflicts} "
            "(resolved newest-epoch-wins; sustained growth means a "
            "partition is not healing)"
        )
    if withheld or frozen:
        p(
            f"  WITHHELD now: {withheld} scope(s) answering absent, "
            f"{frozen} hint band(s) frozen at last-good "
            f"({actuate.get('withheld_total', 0)} withheld cycles "
            "since start)"
        )
        p("\nverdict: ACTUATION DEGRADED (answers being withheld)")
        return 1
    p("\nverdict: OK (all scopes trusted)")
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # The aggregator probe is argv-sniffed, not a Config field: it
    # targets a remote service and needs none of the node-local
    # backend configuration Config.load resolves.
    if "--aggregator" in argv:
        idx = argv.index("--aggregator")
        if idx + 1 >= len(argv):
            print("--aggregator requires a URL", file=sys.stderr)
            return 2
        return run_aggregator(argv[idx + 1])
    return run(Config.load(argv))


if __name__ == "__main__":
    sys.exit(main())
