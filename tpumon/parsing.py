"""Raw device-metric string vectors → normalized sample points.

The libtpu monitoring SDK reports every metric as a list of strings whose
internal format varies per metric (wire formats captured live in
SURVEY.md §2.2 and encoded as :class:`tpumon.schema.Shape`). This module is
the single place those strings are interpreted; backends stay dumb pipes and
the exporter core consumes typed :class:`Point` objects.

Robustness contract (SURVEY.md §4.2): malformed entries are *skipped and
counted*, never raised — a garbled row from the device library must not take
down the exporter. Hypothesis tests fuzz this module directly.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from tpumon.backends.base import RawMetric
from tpumon.schema import STATS, FamilySpec, KeyKind, Shape

_ICI_LINK_RE = re.compile(
    r"^tray(?P<tray>\d+)\.chip(?P<chip>\d+)\.ici(?P<port>\d+)\.(?P<dir>\w+)$"
)
_CORE_RE = re.compile(r"^(?:tensorcore[_-]?)?(?P<core>\d+)$")


@dataclass(frozen=True)
class Point:
    """One labeled numeric sample destined for a Prometheus family."""

    value: float
    labels: dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class ParseResult:
    points: tuple[Point, ...]
    #: Number of entries that could not be interpreted (skipped, counted).
    errors: int = 0

    @property
    def empty(self) -> bool:
        return not self.points


def _to_float(token: str) -> float | None:
    try:
        return float(token.strip())
    except (ValueError, AttributeError):
        return None


def _core_label(key: str) -> str:
    """Normalize 'tensorcore_3' / 'tensorcore-3' / '3' → '3'."""
    m = _CORE_RE.match(key.strip())
    return m.group("core") if m else key.strip()


def _key_labels(kind: KeyKind, key: str) -> dict[str, str] | None:
    key = key.strip()
    if kind is KeyKind.BUFFER_SIZE:
        return {"buffer_size": key}
    if kind is KeyKind.CORE:
        return {"core": _core_label(key)}
    if kind is KeyKind.BUFFER_OP:
        # "2MB+-ALL_REDUCE" → buffer "2MB+", op "ALL_REDUCE". The op name is
        # [A-Z_]+ so rsplit on the last '-' before an op-shaped suffix.
        m = re.match(r"^(?P<buf>.+?)-(?P<op>[A-Za-z_]+)$", key)
        if m:
            return {"buffer_size": m.group("buf"), "op": m.group("op")}
        return {"buffer_size": key, "op": "UNKNOWN"}
    if kind is KeyKind.ICI_LINK:
        labels = {"link": key}
        m = _ICI_LINK_RE.match(key)
        if m:
            labels.update(
                tray=m.group("tray"),
                chip=m.group("chip"),
                port=m.group("port"),
                dir=m.group("dir"),
            )
        else:
            labels.update(tray="", chip="", port="", dir="")
        return labels
    return {}


def _indexed(raw: RawMetric, label_key: str) -> ParseResult:
    points: list[Point] = []
    errors = 0
    for idx, entry in enumerate(raw.data):
        val = _to_float(entry)
        if val is None:
            errors += 1
            continue
        points.append(Point(val, {label_key: str(idx)}))
    return ParseResult(tuple(points), errors)


def _keyed(raw: RawMetric, kind: KeyKind) -> ParseResult:
    points: list[Point] = []
    errors = 0
    for idx, entry in enumerate(raw.data):
        key, sep, value = entry.partition(":")
        if sep:
            val = _to_float(value)
            labels = _key_labels(kind, key)
        else:
            # Bare numeric fallback observed nowhere yet but cheap to allow:
            # treat position as the key.
            val = _to_float(entry)
            labels = (
                {"core": str(idx)}
                if kind is KeyKind.CORE
                else {"link": str(idx), "tray": "", "chip": "", "port": "", "dir": ""}
            )
        if val is None or labels is None:
            errors += 1
            continue
        points.append(Point(val, labels))
    return ParseResult(tuple(points), errors)


def _rows(raw: RawMetric, keyed: bool) -> tuple[list[list[str]], int]:
    """Group the raw vector into percentile rows.

    Two layouts occur in the wild and both are accepted:

    - one comma-joined string per row: ``["8MB+, 1.0, 2.0, 3.0, 4.0, 5.0"]``
    - a flat token list: ``["8MB+", "1.0", ..., "16MB+", "1.1", ...]`` where
      a non-numeric token starts a new row (keyed shapes), or fixed-width
      chunks of ``len(STATS)`` (plain shape).
    """
    errors = 0
    if any("," in entry for entry in raw.data):
        rows = [
            [tok.strip() for tok in entry.split(",") if tok.strip()]
            for entry in raw.data
        ]
        return [r for r in rows if r], errors

    tokens = [entry.strip() for entry in raw.data if entry.strip()]
    if not keyed:
        width = len(STATS)
        return [tokens[i : i + width] for i in range(0, len(tokens), width)], errors

    rows: list[list[str]] = []
    current: list[str] | None = None
    for tok in tokens:
        if _to_float(tok) is None:  # key token starts a row
            current = [tok]
            rows.append(current)
        elif current is None:
            errors += 1  # value before any key
        else:
            current.append(tok)
    return rows, errors


def _pctl(raw: RawMetric, kind: KeyKind) -> ParseResult:
    keyed = kind is not KeyKind.NONE
    rows, errors = _rows(raw, keyed)
    points: list[Point] = []
    for row in rows:
        if keyed:
            if len(row) < 2:
                errors += 1
                continue
            key, values = row[0], row[1:]
            base = _key_labels(kind, key)
        else:
            key, values = "", row
            base = {}
        if base is None:
            errors += 1
            continue
        for stat, tok in zip(STATS, values):
            val = _to_float(tok)
            if val is None:
                errors += 1
                continue
            points.append(Point(val, {**base, "stat": stat}))
        # A short or long row is corruption either way: count, don't hide.
        errors += abs(len(values) - len(STATS))
    return ParseResult(tuple(points), errors)


def parse(raw: RawMetric, spec: FamilySpec) -> ParseResult:
    """Interpret one raw metric sample according to its schema spec.

    An empty vector is the libtpu 'runtime not attached' state
    (SURVEY.md §2.2) and yields zero points with zero errors — the family
    is simply absent from this scrape.
    """
    if raw.empty:
        return ParseResult(())
    if spec.shape is Shape.PER_CHIP:
        return _indexed(raw, "chip")
    if spec.shape is Shape.PER_CORE:
        return _indexed(raw, "core")
    if spec.shape is Shape.KEYED:
        return _keyed(raw, spec.key_kind)
    if spec.shape in (Shape.PCTL_KEYED, Shape.PCTL_PLAIN):
        return _pctl(raw, spec.key_kind)
    raise AssertionError(f"unhandled shape {spec.shape}")
