{{- define "tpumon.name" -}}
{{- .Chart.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "tpumon.labels" -}}
app.kubernetes.io/name: {{ include "tpumon.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}

{{- define "tpumon.selectorLabels" -}}
app.kubernetes.io/name: {{ include "tpumon.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end -}}
